"""On-hardware sanity for the round-4 flash-attention changes (PERF.md).

Interpreter-mode tests can hide Mosaic lowering bugs; this drives the
masked kernels and ring-flash on the real chip and cross-checks against the
dense oracle. Run when the axon tunnel is healthy:

    python perf_flash_check.py
"""
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from bench import _sync


def dense_ref(q, k, v, causal, km=None):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    T = s.shape[-1]
    vis = jnp.ones((T, T), bool)[None, None]
    if causal:
        vis = vis & jnp.tril(jnp.ones((T, T), bool))[None, None]
    if km is not None:
        vis = vis & (km[:, None, None, :] > 0)
    p = jax.nn.softmax(jnp.where(vis, s, -1e30), axis=-1)
    # fully-masked rows output 0 (the framework-wide convention; see
    # ops/flash_attention.py _fwd_kernel)
    p = jnp.where(jnp.any(vis, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def main():
    import deeplearning4j_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(0)
    T, d, h, b = 4096, 64, 4, 2
    q, k, v = (jnp.asarray(rng.normal(size=(b, T, h, d)), jnp.bfloat16)
               for _ in range(3))
    km = jnp.asarray((rng.random((b, T)) > 0.2).astype(np.float32))

    print("backend:", jax.default_backend())
    assert fa.supported(T, d, 0.0, np.asarray(km))

    # masked forward
    t0 = time.perf_counter()
    got = fa.flash_attention(q, k, v, causal=True, key_mask=km)
    _sync(got)
    print(f"masked flash fwd T={T}: {time.perf_counter() - t0:.2f}s "
          f"(incl. compile)")
    want = dense_ref(q, k, v, True, km)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
    print("max |flash - dense| =", err)
    assert err < 2e-2, err            # bf16 tolerance

    # masked backward
    def loss_f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          key_mask=km).astype(jnp.float32)
                       ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_ref(q, k, v, True, km) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for name, a, bb in zip("qkv", gf, gd):
        e = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - bb.astype(jnp.float32))))
        print(f"max |d{name} diff| = {e}")
        assert e < 5e-2, (name, e)

    # in-kernel dropout on hardware: the counter-hash PRNG must lower via
    # Mosaic to the same decisions the CPU-interpret tests pinned (oracle
    # = dropout_keep_mask, bit-identical arithmetic by construction)
    rate, seed = 0.3, 1234
    got_dr = fa.flash_attention(q, k, v, causal=False, dropout_rate=rate,
                                dropout_seed=seed)
    keep = fa.dropout_keep_mask(b * h, T, T, seed, rate)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    p = jax.nn.softmax(s, axis=-1) * keep.reshape(b, h, T, T) / (1.0 - rate)
    want_dr = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    e_dr = float(jnp.max(jnp.abs(got_dr.astype(jnp.float32) - want_dr)))
    print("max |flash-dropout - masked-dense| =", e_dr)
    assert e_dr < 2e-2, e_dr

    # masked flash vs dense timing at T=8192 (the round-3 7.5x checkpoint,
    # now with a mask in-kernel)
    T2 = 8192
    q2, k2, v2 = (jnp.asarray(rng.normal(size=(1, T2, 4, 64)), jnp.bfloat16)
                  for _ in range(3))
    km2 = jnp.asarray((rng.random((1, T2)) > 0.2).astype(np.float32))
    f_j = jax.jit(lambda a, b_, c: fa.flash_attention(a, b_, c, causal=True,
                                                      key_mask=km2))
    d_j = jax.jit(lambda a, b_, c: dense_ref(a, b_, c, True, km2))
    _sync(f_j(q2, k2, v2)); _sync(d_j(q2, k2, v2))   # compile+warm
    t0 = time.perf_counter()
    for _ in range(5):
        o = f_j(q2, k2, v2)
    _sync(o)
    tf = (time.perf_counter() - t0) / 5
    t0 = time.perf_counter()
    for _ in range(5):
        o = d_j(q2, k2, v2)
    _sync(o)
    td = (time.perf_counter() - t0) / 5
    print(f"T={T2} masked: flash {tf*1e3:.1f} ms vs dense {td*1e3:.1f} ms "
          f"({td/tf:.1f}x)")
    print("FLASH HARDWARE CHECK OK")


def block_one():
    """Child for blocksweep: time flash fwd and fwd+bwd at the transformer
    bench's attention shapes (bench.py bench_transformer_lm: b=4, h=8,
    T=8192, d=64 -> bh=32). The block size comes from DL4J_TPU_FLASH_BLOCK
    (import-time knob — that is why each value needs a fresh process)."""
    import json

    from bench import _warm_time
    import deeplearning4j_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(0)
    b, T, h, d = 4, 8192, 8, 64
    # the sweep must measure the cap it advertises: pick_block at these
    # shapes has to resolve to exactly the exported cap
    assert fa.pick_block(T, d) == fa.BLOCK, (fa.pick_block(T, d), fa.BLOCK)
    q, k, v = (jnp.asarray(rng.normal(size=(b, T, h, d)), jnp.bfloat16)
               for _ in range(3))
    f = jax.jit(lambda a, b_, c: fa.flash_attention(a, b_, c, causal=True))
    g = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(
        fa.flash_attention(a, b_, c, causal=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))
    tf = _warm_time(f, q, k, v)
    tg = _warm_time(g, q, k, v)
    print(json.dumps({"block": fa.BLOCK, "fwd_ms": tf * 1e3,
                      "fwdbwd_ms": tg * 1e3}))


def blocksweep():
    """A/B DL4J_TPU_FLASH_BLOCK (import-time knob -> fresh subprocess per
    value) at the transformer bench attention shapes."""
    import json
    import subprocess
    import sys

    print(f"{'block':>6} {'fwd_ms':>9} {'fwdbwd_ms':>10}")
    # 1024 is excluded: pick_block's [blk,blk]-intermediate budget caps
    # picks at 768, which doesn't divide T=8192 (block-one asserts the
    # pick resolves to the advertised cap)
    for blk in (128, 256, 512):
        env = dict(os.environ, DL4J_TPU_FLASH_BLOCK=str(blk))
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "block-one"],
                capture_output=True, text=True, env=env, timeout=900)
        except subprocess.TimeoutExpired:
            print(f"{blk:>6} FAILED timeout", flush=True)
            continue
        line = None
        for ln in reversed((p.stdout or "").splitlines()):
            try:
                line = json.loads(ln)
                break
            except ValueError:
                continue
        if p.returncode or not line:
            print(f"{blk:>6} FAILED rc={p.returncode} "
                  f"{(p.stderr or '')[-300:]}", flush=True)
            continue
        print(f"{blk:>6} {line['fwd_ms']:>9.1f} {line['fwdbwd_ms']:>10.1f}",
              flush=True)


if __name__ == "__main__":
    import sys
    cmd = sys.argv[1] if len(sys.argv) > 1 else "check"
    if cmd == "blocksweep":
        blocksweep()
    elif cmd == "block-one":
        block_one()
    else:
        main()
