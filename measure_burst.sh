#!/bin/bash
# Round-4 measurement burst, flap-tolerant: the axon tunnel wedges and
# recovers unpredictably, so instead of one linear pass this LOOPS over the
# stages for up to BURST_WINDOW seconds (default 8h), skipping stages that
# already succeeded (marker files in .burst_state/). Each bench config
# persists its own partial result (bench.py), so any up-window makes
# permanent progress. Heartbeat watchdog (bench.py BENCH_HB) kills wedged
# children in ~20 min instead of 40.
cd "$(dirname "$0")"
STATE=.burst_state
# fresh state per invocation (bench.py's own per-config partials persist in
# BASELINE.json regardless); BURST_RESUME=1 keeps completed-stage markers
# from a previous run
[ -z "$BURST_RESUME" ] && rm -rf "$STATE"
mkdir -p "$STATE"
DEADLINE=$(( $(date +%s) + ${BURST_WINDOW:-28800} ))
echo "=== burst start $(date -u +%H:%M:%S) (deadline +$(( (DEADLINE-$(date +%s))/60 )) min) ==="

run_stage() {  # run_stage <name> <cmd...>
  local name=$1; shift
  [ -f "$STATE/$name.ok" ] && return 0
  echo "--- stage $name ($(date -u +%H:%M:%S)) ---"
  "$@"
  local rc=$?
  echo "$name rc=$rc"
  [ $rc -eq 0 ] && touch "$STATE/$name.ok"
  return $rc
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  # short probe window per cycle; the outer loop provides the long horizon
  run_stage headline env BENCH_PROBE_WINDOW_S=900 python bench.py
  if [ -f "$STATE/headline.ok" ]; then
    run_stage all      env BENCH_PROBE_WINDOW_S=600 python bench.py --all
    run_stage flash    python perf_flash_check.py
    run_stage roofline python perf_lstm.py roofline
    run_stage ab       python perf_lstm.py ab
    run_stage sweep    python perf_lstm.py sweep
  fi
  if [ -f "$STATE/headline.ok" ] && [ -f "$STATE/all.ok" ] && \
     [ -f "$STATE/flash.ok" ] && [ -f "$STATE/roofline.ok" ] && \
     [ -f "$STATE/ab.ok" ] && [ -f "$STATE/sweep.ok" ]; then
    echo "=== all stages complete $(date -u +%H:%M:%S) ==="
    exit 0
  fi
  echo "--- cycle incomplete; sleeping 600s ($(date -u +%H:%M:%S)) ---"
  sleep 600
done
echo "=== burst window exhausted $(date -u +%H:%M:%S) ==="
ls "$STATE"
exit 1
