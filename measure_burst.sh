#!/bin/bash
# One TPU up-window → every round-4 measurement, in priority order.
# Each stage is independently useful; a re-wedge mid-burst keeps earlier
# results (bench.py persists per-config partials itself).
cd "$(dirname "$0")"
echo "=== burst start $(date -u +%H:%M:%S) ==="

echo "--- stage 1: headline ResNet50 ---"
BENCH_PROBE_WINDOW_S=${BURST_WINDOW:-14400} python bench.py
rc=$?
echo "headline rc=$rc"
if [ $rc -ne 0 ]; then
  echo "backend never came up; burst aborted"
  exit $rc
fi

echo "--- stage 2: bench --all ($(date -u +%H:%M:%S)) ---"
BENCH_PROBE_WINDOW_S=600 python bench.py --all
echo "all rc=$?"

echo "--- stage 3: flash hardware check ($(date -u +%H:%M:%S)) ---"
python perf_flash_check.py
echo "flash rc=$?"

echo "--- stage 4: LSTM roofline ($(date -u +%H:%M:%S)) ---"
python perf_lstm.py roofline
echo "roofline rc=$?"

echo "--- stage 4b: LSTM persistent-kernel A/B ($(date -u +%H:%M:%S)) ---"
python perf_lstm.py ab
echo "ab rc=$?"

echo "--- stage 5: LSTM sweep ($(date -u +%H:%M:%S)) ---"
python perf_lstm.py sweep
echo "sweep rc=$?"
echo "=== burst done $(date -u +%H:%M:%S) ==="
