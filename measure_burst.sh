#!/bin/bash
# Round-4 measurement burst, flap-tolerant: the axon tunnel wedges and
# recovers unpredictably, so instead of one linear pass this LOOPS over the
# stages for up to BURST_WINDOW seconds (default 8h), skipping stages that
# already succeeded (marker files in .burst_state/). Each bench config
# persists its own partial result (bench.py), so any up-window makes
# permanent progress. Heartbeat watchdog (bench.py BENCH_HB) kills wedged
# children in ~20 min instead of 40.
cd "$(dirname "$0")"
STATE=.burst_state
# fresh state per invocation (bench.py's own per-config partials persist in
# BASELINE.json regardless); BURST_RESUME=1 keeps completed-stage markers
# from a previous run
[ -z "$BURST_RESUME" ] && rm -rf "$STATE"
mkdir -p "$STATE"
DEADLINE=$(( $(date +%s) + ${BURST_WINDOW:-28800} ))
echo "=== burst start $(date -u +%H:%M:%S) (deadline +$(( (DEADLINE-$(date +%s))/60 )) min) ==="

run_stage() {  # run_stage <name> <cmd...>
  local name=$1; shift
  [ -f "$STATE/$name.ok" ] && return 0
  echo "--- stage $name ($(date -u +%H:%M:%S)) ---"
  "$@"
  local rc=$?
  echo "$name rc=$rc"
  [ $rc -eq 0 ] && touch "$STATE/$name.ok"
  return $rc
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  # short probe window per cycle; the outer loop provides the long horizon.
  # BENCH_DEADLINE_S=0: bench.py's self-imposed deadline exists to beat the
  # DRIVER's kill window; here the outer timeout owns the budget, and the
  # internal deadline would kill a healthy cold-compile measurement mid-run
  # outer budget covers bench.py's own worst case: 900s probe + 2400s run
  # + 600s re-probe + 2400s retry (+ slack) — never kill a healthy run
  run_stage headline env BENCH_PROBE_WINDOW_S=900 BENCH_DEADLINE_S=0 \
    timeout 6600 python bench.py
  if [ -f "$STATE/headline.ok" ]; then
    if [ ! -f "$STATE/all.ok" ]; then
      # stderr to a plain file (no procsub race), echoed to the log after
      run_stage all env BENCH_PROBE_WINDOW_S=600 python bench.py --all \
        2> "$STATE/all.err"
      cat "$STATE/all.err" >&2
      # a fresh `all` sweep measured these configs with CURRENT code —
      # skip the dedicated re-measure stages for whichever it covered
      # (pattern anchored to a NUMERIC value: bench also prints
      # '# <name>: no result line ...' on a lost measurement)
      if [ -f "$STATE/all.ok" ] && [ -f "$STATE/all.err" ]; then
        grep -Eq "# transformer_lm_tokens_per_sec: [0-9]" "$STATE/all.err" \
          && touch "$STATE/transformer.ok"
        grep -Eq "# keras_inception_parallelwrapper_images_per_sec: [0-9]" \
          "$STATE/all.err" && touch "$STATE/inception2.ok"
        grep -Eq "# graves_lstm_charrnn_chars_per_sec: [0-9]" \
          "$STATE/all.err" && touch "$STATE/lstm2.ok"
      fi
    fi
    # perf_* scripts have no tunnel watchdog of their own: a wedged backend
    # init would block the loop forever, so (a) probe the tunnel cheaply
    # before each stage — a wedged tunnel skips the stage this cycle
    # instead of burning its whole timeout — and (b) bound each stage's
    # wall clock anyway (the tunnel can wedge mid-run too)
    # ONE probe per cycle (cached): a wedged tunnel fails every probe the
    # same way, and 7 needed stages × 150s of probing per down-cycle slowed
    # the loop to ~2 cycles/hour — per-cycle probing notices a recovery
    # within ~12 min instead of ~27
    PROBE_RESULT=""
    probe() {
      if [ -z "$PROBE_RESULT" ]; then
        if timeout 150 python -c "import jax; jax.devices()" \
            >/dev/null 2>&1; then PROBE_RESULT=ok; else PROBE_RESULT=down; fi
      fi
      [ "$PROBE_RESULT" = ok ]
    }
    # marker check BEFORE the probe: completed stages must not pay the
    # 150s probe on wedged cycles
    need() { [ ! -f "$STATE/$1.ok" ]; }
    # configs the `all` stage missed (wedge mid-sweep) or whose bench code
    # changed after it ran: measured individually, persisted via --write
    need transformer && probe && run_stage transformer \
        timeout 2400 python bench.py --one transformer_lm_tokens_per_sec --write
    need inception2  && probe && run_stage inception2 \
        timeout 2400 python bench.py --one \
        keras_inception_parallelwrapper_images_per_sec --write
    # the bf16-recurrence change landed after the `all` sweep ran
    need lstm2       && probe && run_stage lstm2 \
        timeout 1800 python bench.py --one \
        graves_lstm_charrnn_chars_per_sec --write
    need flash    && probe && run_stage flash \
                     timeout 1800 python perf_flash_check.py
    # r5b: flash BLOCK A/B at the transformer bench shapes (fresh
    # subprocess per value — import-time knob) + LSTM latency attribution
    # budget: 3 blocks x <=900s child timeout + parent startup slack
    need blocksweep && probe && run_stage blocksweep \
                     timeout 3000 python perf_flash_check.py blocksweep
    need micro    && probe && run_stage micro \
                     timeout 1200 python perf_lstm.py micro
    # r5c: stream dtype x unroll x fused (6 cells x <=900s + slack)
    need stream   && probe && run_stage stream \
                     timeout 6000 python perf_lstm.py stream
    need roofline && probe && run_stage roofline \
                     timeout 1200 python perf_lstm.py roofline
    need ab       && probe && run_stage ab \
                     timeout 1800 python perf_lstm.py ab
    # r5: U-cap sweep (fresh subprocess per U — trace-time knob);
    # budget: 6 Us x <=900s child timeout + slack
    need unroll   && probe && run_stage unroll \
                     timeout 6000 python perf_lstm.py unroll
    # r5: ResNet50 HBM-wall experiments, split so a timeout loses one
    # sub-stage, not all eight configs
    need rescost  && probe && run_stage rescost \
                     timeout 1800 bash -c \
                     "python perf_exp.py cost 256 && python perf_exp.py cost 512"
    need resbench && probe && run_stage resbench \
                     timeout 1800 python perf_exp.py bench2
    need resremat && probe && run_stage resremat \
                     timeout 2400 python perf_exp.py remat
    need sweep    && probe && run_stage sweep \
                     timeout 2400 python perf_lstm.py sweep
  fi
  if [ -f "$STATE/headline.ok" ] && [ -f "$STATE/all.ok" ] && \
     [ -f "$STATE/transformer.ok" ] && [ -f "$STATE/inception2.ok" ] && \
     [ -f "$STATE/lstm2.ok" ] && [ -f "$STATE/unroll.ok" ] && \
     [ -f "$STATE/flash.ok" ] && [ -f "$STATE/roofline.ok" ] && \
     [ -f "$STATE/ab.ok" ] && [ -f "$STATE/sweep.ok" ] && \
     [ -f "$STATE/rescost.ok" ] && [ -f "$STATE/resbench.ok" ] && \
     [ -f "$STATE/resremat.ok" ] && [ -f "$STATE/blocksweep.ok" ] && \
     [ -f "$STATE/micro.ok" ] && [ -f "$STATE/stream.ok" ]; then
    echo "=== all stages complete $(date -u +%H:%M:%S) ==="
    exit 0
  fi
  echo "--- cycle incomplete; sleeping 600s ($(date -u +%H:%M:%S)) ---"
  sleep 600
done
echo "=== burst window exhausted $(date -u +%H:%M:%S) ==="
ls "$STATE"
exit 1
