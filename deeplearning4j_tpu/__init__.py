"""deeplearning4j_tpu — a TPU-native deep-learning framework with the capability
surface of Deeplearning4j 0.9.x, rebuilt from scratch on JAX/XLA.

See SURVEY.md at the repo root for the structural analysis of the reference and
the mapping from its CUDA/JVM architecture to this TPU-first design.
"""
__version__ = "0.1.0"

from .nn.conf import (NeuralNetConfiguration, MultiLayerConfiguration,
                      OptimizationAlgorithm, GradientNormalization, BackpropType,
                      WorkspaceMode, CacheMode, GlobalConfig)
from .nn.conf.inputs import InputType
from .nn.activations import Activation
from .nn.losses import LossFunction, LossFunctions
from .nn.weights import WeightInit
from .nn.updaters import (Sgd, Adam, AdaMax, Nadam, Nesterovs, RmsProp, AdaGrad,
                          AdaDelta, NoOp, AMSGrad)
from .nn.multilayer import MultiLayerNetwork
from .nn.graph import ComputationGraph
from .nn.conf.graph import ComputationGraphConfiguration
from .datasets.dataset import DataSet, MultiDataSet, DataSetIterator, ListDataSetIterator
from .datasets.prefetch import PrefetchDataSetIterator
from .datasets.bucketing import ShapeBucketingDataSetIterator
from .datasets.normalizers import (NormalizerStandardize, NormalizerMinMaxScaler,
                                   ImagePreProcessingScaler)
from .utils.model_serializer import ModelSerializer
from .nn.transferlearning import (TransferLearning, FineTuneConfiguration,
                                  TransferLearningHelper)
from .serving import (InferenceServer, ModelRegistry, ContinuousBatcher,
                      OverloadedError, DeadlineExceededError)
