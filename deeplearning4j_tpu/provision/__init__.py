"""TPU pod provisioning: the cloud bring-up counterpart of the reference's
AWS module.

Reference ``deeplearning4j-scaleout/deeplearning4j-aws`` (1,427 LoC):
``ec2/Ec2BoxCreator.java`` (spin up N EC2 boxes from an AMI),
``ec2/provision/HostProvisioner.java`` (ssh: upload + run commands),
``ec2/provision/ClusterSetup.java`` (workers + parameter-server roles),
``s3/`` (dataset upload/download). The TPU-native equivalents:

 - boxes/AMI → TPU pod slices (``gcloud compute tpus tpu-vm create`` with an
   accelerator type + software version);
 - per-host ssh provisioning → ``tpu-vm ssh --worker=all`` (one command
   reaches every host of a slice);
 - worker/parameter-server role split → none: the multi-controller SPMD
   runtime is symmetric (``parallel/distributed.py``), so bring-up is
   "launch the same command on all workers";
 - S3 dataset staging → GCS ``gsutil`` staging into the data dir the
   fetchers read (``datasets/fetchers.py``).

This environment has zero egress, so the module builds and validates the
exact command lines (dry-run) rather than shelling them; ``run=True``
executes through subprocess for real deployments. Command construction is
fully unit-tested — the same split the reference's tests make (they never
talk to AWS either).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shlex
import subprocess
import time
from typing import Dict, List, Optional

__all__ = ["TpuPodConfig", "TpuPodProvisioner", "HostProvisioner",
           "GcsStager", "ClusterSetup", "PodLifecycle"]


@dataclasses.dataclass
class TpuPodConfig:
    """Reference ``Ec2BoxCreator`` ctor (amiId, numBoxes, size, securityGroup)
    → TPU slice parameters."""
    name: str
    zone: str
    accelerator_type: str = "v5litepod-16"     # the BASELINE.json target
    runtime_version: str = "tpu-ubuntu2204-base"
    project: Optional[str] = None
    network: Optional[str] = None
    preemptible: bool = False
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)


class TpuPodProvisioner:
    """Builds/executes the pod lifecycle commands (``Ec2BoxCreator.create``/
    ``blowupBoxes`` equivalents)."""

    def __init__(self, config: TpuPodConfig, runner=None):
        self.config = config
        self.custom_runner = runner is not None   # PodLifecycle honors it
        self._run = runner or (lambda cmd: subprocess.run(
            cmd, check=True, capture_output=True, text=True))

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _scope(self) -> List[str]:
        c = self.config
        out = ["--zone", c.zone]
        if c.project:
            out += ["--project", c.project]
        return out

    def create_command(self) -> List[str]:
        c = self.config
        cmd = self._base() + ["create", c.name] + self._scope() + [
            "--accelerator-type", c.accelerator_type,
            "--version", c.runtime_version]
        if c.network:
            cmd += ["--network", c.network]
        if c.preemptible:
            cmd += ["--preemptible"]
        if c.tags:
            # one comma-joined --labels flag: gcloud ArgDict flags override
            # on repetition, so per-tag flags would keep only the last tag
            cmd += ["--labels", ",".join(f"{k}={v}"
                                         for k, v in sorted(c.tags.items()))]
        return cmd

    def delete_command(self) -> List[str]:
        return (self._base() + ["delete", self.config.name]
                + self._scope() + ["--quiet"])

    def describe_command(self) -> List[str]:
        return self._base() + ["describe", self.config.name] + self._scope()

    def create(self, run: bool = False):
        cmd = self.create_command()
        return self._run(cmd) if run else cmd

    def delete(self, run: bool = False):
        cmd = self.delete_command()
        return self._run(cmd) if run else cmd


class HostProvisioner:
    """Reference ``HostProvisioner.java`` (ssh upload + run-with-sudo) over
    ``tpu-vm ssh/scp``; ``worker='all'`` fans out to every host of the slice
    — the loop over boxes the reference hand-rolls."""

    def __init__(self, provisioner: TpuPodProvisioner, worker: str = "all"):
        self.p = provisioner
        self.worker = str(worker)

    def run_command(self, remote_cmd: str) -> List[str]:
        return (self.p._base() + ["ssh", self.p.config.name]
                + self.p._scope()
                + ["--worker", self.worker, "--command", remote_cmd])

    def upload_command(self, local_path: str, remote_path: str) -> List[str]:
        return (self.p._base() + ["scp", local_path,
                                  f"{self.p.config.name}:{remote_path}"]
                + self.p._scope() + ["--worker", self.worker])

    def run(self, remote_cmd: str, run: bool = False):
        cmd = self.run_command(remote_cmd)
        return self.p._run(cmd) if run else cmd


class GcsStager:
    """Reference ``s3/uploader/S3Uploader`` + ``s3/reader/S3Downloader`` →
    GCS staging into/out of the fetchers' data dir."""

    def __init__(self, bucket: str):
        self.bucket = bucket.rstrip("/")

    def upload_command(self, local_path: str, remote_name: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r", local_path,
                f"{self.bucket}/{remote_name}"]

    def download_command(self, remote_name: str, local_path: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r",
                f"{self.bucket}/{remote_name}", local_path]


class ClusterSetup:
    """Reference ``ClusterSetup.java``: provision boxes then launch training.
    Symmetric SPMD removes the worker/parameter-server split — every host
    gets the SAME launch line (multi-controller; coordinator = worker 0's
    address, ``parallel/distributed.py::initialize_distributed``)."""

    def __init__(self, provisioner: TpuPodProvisioner,
                 train_script: str = "train.py",
                 env: Optional[Dict[str, str]] = None):
        self.provisioner = provisioner
        self.train_script = train_script
        self.env = dict(env or {})

    def launch_command(self) -> List[str]:
        """The symmetric all-workers launch (env + python3 script), shared
        by plan() and PodLifecycle."""
        hosts = HostProvisioner(self.provisioner)
        launch = " ".join(
            [f"{k}={shlex.quote(v)}" for k, v in sorted(self.env.items())]
            + ["python3", shlex.quote(self.train_script)])
        return hosts.run_command(launch)

    def plan(self) -> List[List[str]]:
        """The full bring-up as a command list (dry-run inspectable)."""
        hosts = HostProvisioner(self.provisioner)
        return [
            self.provisioner.create_command(),
            hosts.upload_command(self.train_script, self.train_script),
            self.launch_command(),
        ]


class PodLifecycle:
    """The full rehearsable bring-up — the executable counterpart of the
    reference's ``ClusterSetup.java`` lifecycle (create boxes → provision
    every host → launch the distributed job → tear down), with two
    properties the reference lacks and a pod bring-up needs:

    - **Journaled idempotent re-entry**: every completed step is recorded
      (step name + command hash) in a JSON journal; re-running ``bringup()``
      after a mid-flight failure skips the steps that already completed and
      resumes at the first incomplete/changed one. Changing a step's
      command invalidates its journal entry (hash mismatch ⇒ re-run).
    - **Existence-aware create**: ``describe`` probes the pod first; an
      already-created pod skips ``create`` even with a fresh journal, so
      two operators (or a crashed run) cannot double-create.

    All cloud interaction goes through the injected ``executor`` (a
    callable ``cmd → object with returncode/stdout``); tests rehearse the
    complete lifecycle against a fake, the same split the reference's
    AWS tests make. Real deployments pass ``subprocess.run``-backed
    execution (the default)."""

    #: step order of a bring-up (teardown is separate)
    STEPS = ("create", "wait_ready", "provision", "stage_data", "launch")

    def __init__(self, setup: ClusterSetup,
                 stager: Optional[GcsStager] = None,
                 datasets: Optional[List[str]] = None,
                 setup_commands: Optional[List[str]] = None,
                 journal_path: Optional[str] = None,
                 executor=None, poll_interval_s: float = 10.0,
                 ready_timeout_s: float = 900.0,
                 data_dir: str = "~/.deeplearning4j_tpu"):
        self.setup = setup
        self.provisioner = setup.provisioner
        self.hosts = HostProvisioner(self.provisioner)
        self.stager = stager
        self.datasets = list(datasets or [])
        self.setup_commands = list(setup_commands or [])
        self.journal_path = journal_path or (
            f".pod_lifecycle_{self.provisioner.config.name}.json")
        # executor precedence: explicit arg > a runner injected on the
        # provisioner (the pre-existing seam — auth wrappers etc. must not
        # be silently bypassed) > plain subprocess. A custom runner may
        # raise on non-zero exit (the provisioner default does); the
        # probe/poll paths treat that as rc != 0.
        if executor is not None:
            self._exec = executor
        elif self.provisioner.custom_runner:
            self._exec = self.provisioner._run
        else:
            self._exec = (lambda cmd: subprocess.run(
                cmd, capture_output=True, text=True))
        self.poll_interval_s = poll_interval_s
        self.ready_timeout_s = ready_timeout_s
        self.data_dir = data_dir

    # ------------------------------------------------------------- journal
    def _load_journal(self) -> Dict:
        try:
            with open(self.journal_path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return {}

    def _save_journal(self, journal: Dict):
        tmp = self.journal_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(journal, fh, indent=2)
        os.replace(tmp, self.journal_path)

    @staticmethod
    def _hash(cmds: List[List[str]]) -> str:
        return hashlib.sha256(
            json.dumps(cmds, sort_keys=True).encode()).hexdigest()[:16]

    # --------------------------------------------------------------- steps
    def _step_commands(self, step: str) -> List[List[str]]:
        """The commands a step will run (dry-run inspectable, and the
        basis of the journal hash — edit a step, it re-runs)."""
        if step == "create":
            return [self.provisioner.create_command()]
        if step == "wait_ready":
            return [self.provisioner.describe_command()]
        if step == "provision":
            out = [self.hosts.upload_command(self.setup.train_script,
                                             self.setup.train_script)]
            out += [self.hosts.run_command(c) for c in self.setup_commands]
            return out
        if step == "stage_data":
            if not (self.stager and self.datasets):
                return []
            def expr(path: str) -> str:
                # '~' must reach the REMOTE shell expandable: single-quoting
                # it would stage into a literal './~' dir while the fetchers
                # expanduser() to the real home — use "$HOME" + quoted rest
                if path.startswith("~/"):
                    return '"$HOME"' + shlex.quote(path[1:])
                return shlex.quote(path)

            out = []
            for name in self.datasets:
                dst = f"{self.data_dir}/{name}"
                parts = self.stager.download_command(name, dst)
                cmd = " ".join(map(shlex.quote, parts[:-1])) + " " + expr(dst)
                # mkdir the PARENT (data dir) only, and rm any partial dst
                # first: `gsutil cp -r` into an EXISTING dir nests the
                # dataset one level too deep (<dst>/<name>/...), invisible
                # to the fetchers — both on pre-created dirs and on RETRY
                # after a mid-copy failure (the journal re-runs this step)
                out.append(self.hosts.run_command(
                    f"mkdir -p {expr(self.data_dir)} && "
                    f"rm -rf {expr(dst)} && {cmd}"))
            return out
        if step == "launch":
            return [self.setup.launch_command()]
        raise ValueError(f"unknown step {step!r}")

    def _describe(self):
        """describe with raising-runner tolerance: a runner that raises on
        non-zero exit (the provisioner default) reads as rc != 0."""
        try:
            return self._exec(self.provisioner.describe_command())
        except subprocess.CalledProcessError as e:
            import types
            return types.SimpleNamespace(returncode=e.returncode or 1,
                                         stdout=e.stdout or "",
                                         stderr=e.stderr or "")

    def _pod_exists(self) -> bool:
        """True/False from describe — but a TRANSIENT failure (auth, rate
        limit, network) is neither: treating it as 'gone' would wipe the
        journal and re-launch the job on a live pod, so anything that
        isn't an explicit not-found raises instead."""
        r = self._describe()
        if r.returncode == 0:
            return True
        err = (getattr(r, "stderr", "") or "").lower()
        if "not_found" in err or "not found" in err or "404" in err:
            return False
        raise RuntimeError(
            f"describe failed transiently (rc={r.returncode}): "
            f"{err[-300:] or 'no stderr'} — cannot tell whether pod "
            f"{self.provisioner.config.name!r} exists; retry when the "
            f"control plane answers")

    def _run_step(self, step: str):
        if step == "create":
            if self._pod_exists():     # double-create guard
                return
            self._check(self._exec(self.provisioner.create_command()),
                        "create")
            return
        if step == "wait_ready":
            deadline = time.monotonic() + self.ready_timeout_s
            while True:
                r = self._describe()
                state = getattr(r, "stdout", "") or ""
                if r.returncode == 0 and "READY" in state:
                    return
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"pod {self.provisioner.config.name} not READY "
                        f"within {self.ready_timeout_s:.0f}s "
                        f"(last describe rc={r.returncode})")
                time.sleep(self.poll_interval_s)
        for cmd in self._step_commands(step):
            self._check(self._exec(cmd), step)

    @staticmethod
    def _check(result, step: str):
        rc = getattr(result, "returncode", 0)
        if rc:
            err = (getattr(result, "stderr", "") or "")[-500:]
            raise RuntimeError(f"lifecycle step {step!r} failed rc={rc}: "
                               f"{err}")

    # ----------------------------------------------------------- lifecycle
    def bringup(self) -> List[str]:
        """Run all bring-up steps in order, journaling completion; returns
        the list of steps actually EXECUTED this call (skipped ones are
        absent — the idempotence the tests assert).

        A completed journal is only trusted while the pod still EXISTS: a
        preempted/externally-deleted pod invalidates the journal and the
        bring-up starts over (otherwise a dead pod would be reported as
        successfully up)."""
        journal = self._load_journal()
        if journal and not self._pod_exists():
            journal = {}                 # pod gone: nothing "done" survives
            self._save_journal(journal)
        ran: List[str] = []
        for step in self.STEPS:
            h = self._hash(self._step_commands(step))
            entry = journal.get(step)
            if entry and entry.get("done") and entry.get("hash") == h:
                continue                        # journaled + unchanged: skip
            self._run_step(step)
            ran.append(step)
            journal[step] = {"done": True, "hash": h}
            self._save_journal(journal)
        return ran

    def teardown(self, clear_journal: bool = True):
        """Delete the pod (idempotent: a missing pod is success) and —
        by default — clear the journal so the next bringup() starts
        fresh."""
        if self._pod_exists():
            self._check(self._exec(self.provisioner.delete_command()),
                        "teardown")
        if clear_journal:
            try:
                os.remove(self.journal_path)
            except OSError:
                pass
