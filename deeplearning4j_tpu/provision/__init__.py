"""TPU pod provisioning: the cloud bring-up counterpart of the reference's
AWS module.

Reference ``deeplearning4j-scaleout/deeplearning4j-aws`` (1,427 LoC):
``ec2/Ec2BoxCreator.java`` (spin up N EC2 boxes from an AMI),
``ec2/provision/HostProvisioner.java`` (ssh: upload + run commands),
``ec2/provision/ClusterSetup.java`` (workers + parameter-server roles),
``s3/`` (dataset upload/download). The TPU-native equivalents:

 - boxes/AMI → TPU pod slices (``gcloud compute tpus tpu-vm create`` with an
   accelerator type + software version);
 - per-host ssh provisioning → ``tpu-vm ssh --worker=all`` (one command
   reaches every host of a slice);
 - worker/parameter-server role split → none: the multi-controller SPMD
   runtime is symmetric (``parallel/distributed.py``), so bring-up is
   "launch the same command on all workers";
 - S3 dataset staging → GCS ``gsutil`` staging into the data dir the
   fetchers read (``datasets/fetchers.py``).

This environment has zero egress, so the module builds and validates the
exact command lines (dry-run) rather than shelling them; ``run=True``
executes through subprocess for real deployments. Command construction is
fully unit-tested — the same split the reference's tests make (they never
talk to AWS either).
"""
from __future__ import annotations

import dataclasses
import shlex
import subprocess
from typing import Dict, List, Optional

__all__ = ["TpuPodConfig", "TpuPodProvisioner", "HostProvisioner",
           "GcsStager", "ClusterSetup"]


@dataclasses.dataclass
class TpuPodConfig:
    """Reference ``Ec2BoxCreator`` ctor (amiId, numBoxes, size, securityGroup)
    → TPU slice parameters."""
    name: str
    zone: str
    accelerator_type: str = "v5litepod-16"     # the BASELINE.json target
    runtime_version: str = "tpu-ubuntu2204-base"
    project: Optional[str] = None
    network: Optional[str] = None
    preemptible: bool = False
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)


class TpuPodProvisioner:
    """Builds/executes the pod lifecycle commands (``Ec2BoxCreator.create``/
    ``blowupBoxes`` equivalents)."""

    def __init__(self, config: TpuPodConfig, runner=None):
        self.config = config
        self._run = runner or (lambda cmd: subprocess.run(
            cmd, check=True, capture_output=True, text=True))

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def _scope(self) -> List[str]:
        c = self.config
        out = ["--zone", c.zone]
        if c.project:
            out += ["--project", c.project]
        return out

    def create_command(self) -> List[str]:
        c = self.config
        cmd = self._base() + ["create", c.name] + self._scope() + [
            "--accelerator-type", c.accelerator_type,
            "--version", c.runtime_version]
        if c.network:
            cmd += ["--network", c.network]
        if c.preemptible:
            cmd += ["--preemptible"]
        if c.tags:
            # one comma-joined --labels flag: gcloud ArgDict flags override
            # on repetition, so per-tag flags would keep only the last tag
            cmd += ["--labels", ",".join(f"{k}={v}"
                                         for k, v in sorted(c.tags.items()))]
        return cmd

    def delete_command(self) -> List[str]:
        return (self._base() + ["delete", self.config.name]
                + self._scope() + ["--quiet"])

    def describe_command(self) -> List[str]:
        return self._base() + ["describe", self.config.name] + self._scope()

    def create(self, run: bool = False):
        cmd = self.create_command()
        return self._run(cmd) if run else cmd

    def delete(self, run: bool = False):
        cmd = self.delete_command()
        return self._run(cmd) if run else cmd


class HostProvisioner:
    """Reference ``HostProvisioner.java`` (ssh upload + run-with-sudo) over
    ``tpu-vm ssh/scp``; ``worker='all'`` fans out to every host of the slice
    — the loop over boxes the reference hand-rolls."""

    def __init__(self, provisioner: TpuPodProvisioner, worker: str = "all"):
        self.p = provisioner
        self.worker = str(worker)

    def run_command(self, remote_cmd: str) -> List[str]:
        return (self.p._base() + ["ssh", self.p.config.name]
                + self.p._scope()
                + ["--worker", self.worker, "--command", remote_cmd])

    def upload_command(self, local_path: str, remote_path: str) -> List[str]:
        return (self.p._base() + ["scp", local_path,
                                  f"{self.p.config.name}:{remote_path}"]
                + self.p._scope() + ["--worker", self.worker])

    def run(self, remote_cmd: str, run: bool = False):
        cmd = self.run_command(remote_cmd)
        return self.p._run(cmd) if run else cmd


class GcsStager:
    """Reference ``s3/uploader/S3Uploader`` + ``s3/reader/S3Downloader`` →
    GCS staging into/out of the fetchers' data dir."""

    def __init__(self, bucket: str):
        self.bucket = bucket.rstrip("/")

    def upload_command(self, local_path: str, remote_name: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r", local_path,
                f"{self.bucket}/{remote_name}"]

    def download_command(self, remote_name: str, local_path: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r",
                f"{self.bucket}/{remote_name}", local_path]


class ClusterSetup:
    """Reference ``ClusterSetup.java``: provision boxes then launch training.
    Symmetric SPMD removes the worker/parameter-server split — every host
    gets the SAME launch line (multi-controller; coordinator = worker 0's
    address, ``parallel/distributed.py::initialize_distributed``)."""

    def __init__(self, provisioner: TpuPodProvisioner,
                 train_script: str = "train.py",
                 env: Optional[Dict[str, str]] = None):
        self.provisioner = provisioner
        self.train_script = train_script
        self.env = dict(env or {})

    def plan(self) -> List[List[str]]:
        """The full bring-up as a command list (dry-run inspectable)."""
        hosts = HostProvisioner(self.provisioner)
        launch = " ".join(
            [f"{k}={shlex.quote(v)}" for k, v in sorted(self.env.items())]
            + ["python3", shlex.quote(self.train_script)])
        return [
            self.provisioner.create_command(),
            hosts.upload_command(self.train_script, self.train_script),
            hosts.run_command(launch),
        ]
