"""Probe plane: black-box synthetic monitoring with golden-answer checks.

The third telemetry plane. The push plane (paramserver ``OP_TELEMETRY``
→ ``FleetState``) and the scrape plane (``TelemetryCollector`` polling
``GET /telemetry``) are both **self-report**: a replica whose model path
is wedged — or quietly returning wrong answers after a bad weight load —
can keep serving a perfectly healthy ``/telemetry`` forever. Gray
failures like that are invisible to every signal the stack has. This
module is the external check:

- :class:`ProbeTarget` — one replica endpoint plus its **golden set**:
  canonical inputs and f32 expected outputs captured through the real
  serving path by :meth:`~deeplearning4j_tpu.serving.registry.
  ServedModel.golden` (version-keyed — an AOT warmup artifact ships the
  oracle for exactly the weights it was exported from).
- :class:`Prober` — an opt-in daemon (same lifecycle shape as the
  history sampler and the collector: idempotent ``start(interval_s)``,
  timed-join ``stop()``, deterministic ``tick(now=)`` test seam) that
  fires real ``POST /v1/models/<m>/predict`` requests from the
  *outside* and compares answers against the golden set within the
  precision-keyed ``atol``.

Every probe is a client-side SLI:
``probe_requests_total{target,model,outcome=ok|error|timeout|mismatch}``,
``probe_latency_ms{target,model}`` (worst latencies latch their probe
trace ids as exemplars), and ``probe_last_success_age_s{target}`` — the
**deadman**: only an ``ok`` probe resets it, so a replica answering
quickly but WRONGLY still trips it. Probes mint their own trace context
and send it as ``X-DL4J-Trace``, so every probe — including one that
500s — is resolvable on the replica's own ``/trace``; they also send
``X-DL4J-Probe: 1`` so the serving tier bypasses the response cache end
to end (a cached golden answer proves nothing about the live model
path, and probes must never evict real traffic's entries).

Closing the loop: ``alerts.default_probe_rules()`` (availability burn,
client-observed p99, any-mismatch, deadman) evaluates over the prober's
own :class:`~.history.MetricsHistory` ring each tick, and
``control.policies.probe_failure_policy`` restarts a replica that fails
probes while self-reporting healthy. Sustained failure (``fail_threshold``
consecutive non-ok probes) also lands as a timestamped ``health_problem``
flight event (kind="probe") on THIS process's ``/healthz`` — resolvable
exactly like alert problems once probes recover.

Lock discipline: the prober's ``_lock`` is a LEAF — it guards only the
target table and per-target state; HTTP probes, metric writes, flight
events, health recording, history sampling and alert evaluation all run
with no lock held (tests/test_lockwatch.py pins acquisitions > 0 and
outgoing edges == 0).

See docs/OBSERVABILITY.md "Probe plane".
"""
from __future__ import annotations

import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

import numpy as np

from .lockwatch import make_lock

log = logging.getLogger(__name__)

__all__ = ["ProbeTarget", "Prober", "get_prober"]

#: default probe cadence (seconds) — one real prediction per target per
#: tick; same order as the scrape plane, far below serving QPS
DEFAULT_INTERVAL_S = 5.0

#: per-probe HTTP timeout (seconds); a hung replica costs one probe slot
#: (outcome="timeout"), never the whole tick loop
DEFAULT_TIMEOUT_S = 5.0

#: consecutive non-ok probes before the incident lands on /healthz as a
#: health_problem (kind="probe") — one flap never dirties the ring
DEFAULT_FAIL_THRESHOLD = 3

#: comparison tolerance when a golden set carries none (f32 serving)
DEFAULT_ATOL = 1e-4


class ProbeTarget:
    """One probe-plane endpoint: a label, the replica's base URL
    (scheme optional; ``/v1/models/<model>/predict`` is appended), the
    model to probe and its **golden set** — the dict
    :meth:`ServedModel.golden` returns (``inputs``, f32 ``outputs``,
    ``atol``, ``version``). ``model`` defaults to the golden set's own
    ``model`` key."""

    def __init__(self, label: str, url: str, golden: Dict[str, Any],
                 model: Optional[str] = None,
                 deadline_ms: Optional[float] = None):
        self.label = str(label)
        url = str(url)
        if "://" not in url:
            url = f"http://{url}"
        self.url = url.rstrip("/")
        if not isinstance(golden, dict) or "inputs" not in golden \
                or "outputs" not in golden:
            raise ValueError(
                f"probe target {label!r}: golden must be a dict with "
                f"'inputs' and 'outputs' (ServedModel.golden() shape)")
        self.model = str(model if model is not None
                         else golden.get("model") or "")
        if not self.model:
            raise ValueError(f"probe target {label!r}: no model name "
                             f"(pass model= or a golden with 'model')")
        # inputs stay nested lists (the JSON body); expected becomes the
        # f32 oracle array the comparison runs against
        self.inputs = np.asarray(golden["inputs"], np.float32).tolist()
        self.expected = np.asarray(golden["outputs"], np.float32)
        self.atol = float(golden.get("atol") or DEFAULT_ATOL)
        self.version = golden.get("version")
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)

    def to_dict(self) -> dict:
        return {"label": self.label, "url": self.url, "model": self.model,
                "golden_version": self.version, "atol": self.atol}

    def __repr__(self):
        return (f"ProbeTarget({self.label!r}, {self.url!r}, "
                f"model={self.model!r}, version={self.version!r})")


class _ProbeDumpSource:
    """Registry-shaped adapter (``.dump()``) so the prober's
    :class:`MetricsHistory` samples the process registry with the probe
    series FILTERED to the current target set — a long-lived process
    registry must not leak a retired target's stale
    ``probe_last_success_age_s`` into the deadman rule (the same
    retired-series hazard ``TelemetryCollector.fleet_dump`` filters)."""

    def __init__(self, prober: "Prober"):
        self._prober = prober

    def dump(self) -> dict:
        return self._prober.probe_dump()


class Prober:
    """Black-box prober daemon. Opt-in like the collector: construction
    starts nothing; tests drive :meth:`tick` deterministically;
    production calls ``start(interval_s)`` and ``stop()`` timed-joins
    the thread.

    ``history`` defaults to a private :class:`~.history.MetricsHistory`
    sampling the process registry with probe series filtered to the
    CURRENT target set (:meth:`probe_dump`), and ``engine`` to a
    private :class:`~.alerts.AlertEngine` over it — attach the probe
    SLO pack with ``prober.engine.add(*default_probe_rules(prober))``.
    """

    def __init__(self, history=None, engine=None, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 fail_threshold: int = DEFAULT_FAIL_THRESHOLD):
        from .history import MetricsHistory
        from .alerts import AlertEngine
        self.history = (history if history is not None
                        else MetricsHistory(
                            registry=_ProbeDumpSource(self)))
        self.engine = (engine if engine is not None
                       else AlertEngine(history=self.history))
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.fail_threshold = max(1, int(fail_threshold))
        self._lock = make_lock("Prober._lock")
        self._targets: Dict[str, ProbeTarget] = {}
        #: per-target probe state (guarded by the leaf lock): outcome of
        #: the last probe, consecutive non-ok count, deadman timestamps,
        #: the last probe's trace id (the /trace join key)
        self._state: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ targets
    def add_target(self, label: str, url: str, golden: Dict[str, Any],
                   model: Optional[str] = None,
                   deadline_ms: Optional[float] = None) -> "Prober":
        target = ProbeTarget(label, url, golden, model=model,
                             deadline_ms=deadline_ms)
        with self._lock:
            self._targets[target.label] = target
            self._state.setdefault(target.label, {})
        return self

    def remove_target(self, label: str):
        with self._lock:
            self._targets.pop(str(label), None)
            self._state.pop(str(label), None)

    def targets(self) -> List[ProbeTarget]:
        with self._lock:
            return [self._targets[k] for k in sorted(self._targets)]

    def failing_targets(self) -> List[ProbeTarget]:
        """Targets whose LAST probe was not ``ok`` (the actuator-side
        view ``control.policies.probe_failure_policy`` reads at fire
        time — error, timeout and mismatch all count: a wrong answer is
        as failed as no answer)."""
        with self._lock:
            return [self._targets[k] for k in sorted(self._targets)
                    if self._state.get(k, {}).get("last_outcome")
                    not in (None, "ok")]

    # ------------------------------------------------------------ probing
    def _probe(self, target: ProbeTarget, trace_header: str) -> np.ndarray:
        """One UNLOCKED golden-set replay: a real ``POST .../predict``
        carrying the probe's own trace context and the cache-bypass
        marker. Returns the replica's f32 outputs; raises on transport
        or HTTP failure."""
        from ..serving.server import PROBE_HEADER, TRACE_HEADER
        body: Dict[str, Any] = {"inputs": target.inputs}
        if target.deadline_ms is not None:
            body["deadline_ms"] = target.deadline_ms
        req = urllib.request.Request(
            f"{target.url}/v1/models/{target.model}/predict",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: trace_header,
                     PROBE_HEADER: "1"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            doc = json.loads(r.read().decode("utf-8"))
        return np.asarray(doc.get("outputs"), np.float32)

    @staticmethod
    def _probe_metrics(target: ProbeTarget):
        from .registry import get_registry
        reg = get_registry()
        return (reg.histogram("probe_latency_ms",
                              "client-observed synthetic probe latency",
                              target=target.label, model=target.model),
                reg.gauge("probe_last_success_age_s",
                          "seconds since the target last answered a probe "
                          "CORRECTLY (the deadman — mismatches do not "
                          "reset it)", target=target.label))

    @staticmethod
    def _count(target: ProbeTarget, outcome: str):
        from .registry import get_registry
        get_registry().counter(
            "probe_requests_total",
            "synthetic probes by outcome (ok|error|timeout|mismatch)",
            target=target.label, model=target.model,
            outcome=outcome).inc()

    def tick(self, now: Optional[float] = None) -> dict:
        """One probe pass (the daemon's beat; also the test seam).

        Probes every configured target with NO lock held, classifies
        each answer (``ok`` / ``error`` / ``timeout`` / ``mismatch``),
        lands the SLI series, maintains the deadman gauge, records
        edge-triggered ``probe_target_failing`` / ``_recovered`` flight
        events, folds sustained failure into ``/healthz`` as a
        ``health_problem`` (kind="probe"), then samples the history ring
        and evaluates the probe alert engine. Returns a per-tick summary
        so tests latch exact numbers."""
        from .flightrec import get_flight_recorder
        from .health import get_health
        from .tracer import new_context
        t_tick0 = time.perf_counter()
        now = float(now) if now is not None else time.time()
        with self._lock:
            targets = [self._targets[k] for k in sorted(self._targets)]
        probed: List[str] = []
        outcomes: Dict[str, str] = {}
        errors: Dict[str, str] = {}
        probe_ms: Dict[str, float] = {}
        for target in targets:
            hist, age_gauge = self._probe_metrics(target)
            ctx = new_context()
            trace_hex = f"{ctx.trace_id:x}"
            outcome, detail = "ok", ""
            t0 = time.perf_counter()
            try:
                out = self._probe(target,
                                  f"{ctx.trace_id:x}:{ctx.span_id:x}")
                if out.shape != target.expected.shape or not np.allclose(
                        out, target.expected, atol=target.atol,
                        equal_nan=False):
                    outcome = "mismatch"
                    detail = (f"answer diverges from golden "
                              f"{target.version or '?'} "
                              f"(atol={target.atol:g})")
            except (socket.timeout, TimeoutError) as e:
                outcome, detail = "timeout", f"{type(e).__name__}: {e}"
            except urllib.error.URLError as e:
                # a timeout surfaces as URLError(reason=timeout) too
                timed_out = isinstance(getattr(e, "reason", None),
                                       (socket.timeout, TimeoutError))
                outcome = "timeout" if timed_out else "error"
                detail = f"{type(e).__name__}: {e}"
            except Exception as e:          # bad JSON, refused, 5xx body
                outcome, detail = "error", f"{type(e).__name__}: {e}"
            ms = (time.perf_counter() - t0) * 1e3
            # every probe is a data point — a down replica must show up
            # in the client-side latency distribution, not vanish
            hist.observe(ms, exemplar=trace_hex)
            self._count(target, outcome)
            probe_ms[target.label] = ms
            outcomes[target.label] = outcome
            if outcome != "ok":
                errors[target.label] = detail
            with self._lock:
                st = self._state.setdefault(target.label, {})
                was = st.get("last_outcome")
                st.setdefault("first_probe_t", now)
                st["last_outcome"] = outcome
                st["last_detail"] = detail or None
                st["last_trace_id"] = trace_hex
                st["last_probe_t"] = now
                st["probes"] = st.get("probes", 0) + 1
                if outcome == "ok":
                    st["consecutive_failures"] = 0
                    st["last_success_t"] = now
                else:
                    st["consecutive_failures"] = \
                        st.get("consecutive_failures", 0) + 1
                fails = st["consecutive_failures"]
                age = now - st.get("last_success_t",
                                   st["first_probe_t"])
            age_gauge.set(max(0.0, age))
            if outcome != "ok" and was in (None, "ok"):
                # edge-triggered, never per-tick — and the event carries
                # the probe's OWN trace id, resolvable on the replica
                get_flight_recorder().record(
                    "probe_target_failing", target=target.label,
                    model=target.model, url=target.url, outcome=outcome,
                    trace_id=trace_hex, detail=detail)
                log.warning("probe of %s (%s %s) failing: %s — %s",
                            target.label, target.url, target.model,
                            outcome, detail)
            elif outcome == "ok" and was not in (None, "ok"):
                get_flight_recorder().record(
                    "probe_target_recovered", target=target.label,
                    model=target.model, url=target.url,
                    trace_id=trace_hex)
            if outcome != "ok" and fails == self.fail_threshold:
                # sustained: the gray failure lands on THIS process's
                # /healthz as a timestamped, resolvable problem
                get_health().record_problem(
                    "probe", f"target {target.label} ({target.model}) "
                             f"failed {fails} consecutive probes: "
                             f"{outcome} — {detail} "
                             f"[trace {trace_hex}]")
            probed.append(target.label)
        # upward loop: probe series -> history ring -> probe SLO engine
        if targets:
            self.history.sample(now=now)
            self.engine.evaluate(now=now, strict=False)
        return {"t": now, "probed": probed, "outcomes": outcomes,
                "errors": errors, "probe_ms": probe_ms,
                "duration_ms": (time.perf_counter() - t_tick0) * 1e3}

    # ------------------------------------------------------------ queries
    def probe_dump(self) -> dict:
        """The registry dump the prober's history samples: all families,
        with ``probe_*`` series filtered to the CURRENT target set —
        retiring a target retires its series from rule evaluation (its
        stale deadman gauge must not fire forever)."""
        from .registry import get_registry
        dump = get_registry().dump()
        with self._lock:
            current = set(self._targets)
        out = {}
        for name, fam in dump.items():
            if not name.startswith("probe_"):
                out[name] = fam
                continue
            rows = [r for r in fam.get("children", [])
                    if r.get("labels", {}).get("target") in current]
            if rows:
                out[name] = {**{k: v for k, v in fam.items()
                                if k != "children"}, "children": rows}
        return out

    def last_failure_trace(self) -> Optional[str]:
        """The most recent failing target's probe trace id (exemplar
        seam for the deadman/mismatch rules — resolvable on the guilty
        replica's ``/trace``)."""
        with self._lock:
            worst = None
            for k in sorted(self._targets):
                st = self._state.get(k, {})
                if st.get("last_outcome") in (None, "ok"):
                    continue
                t = st.get("last_probe_t") or 0.0
                if worst is None or t > worst[0]:
                    worst = (t, st.get("last_trace_id"))
        return worst[1] if worst else None

    def failure_detail(self) -> str:
        """One-line 'who is failing and why' for alert annotations."""
        with self._lock:
            rows = [f"{k}: {st.get('last_outcome')}"
                    f" ({st.get('last_detail') or 'no detail'})"
                    for k in sorted(self._targets)
                    if (st := self._state.get(k, {})).get("last_outcome")
                    not in (None, "ok")]
        return "; ".join(rows)

    def snapshot(self) -> dict:
        """The prober's own state (targets, outcomes, deadman ages) —
        the ``GET /probes`` / ``monitor --probes`` view."""
        now = time.time()
        with self._lock:
            targets = {}
            for k, t in sorted(self._targets.items()):
                st = self._state.get(k, {})
                base = st.get("last_success_t", st.get("first_probe_t"))
                targets[k] = {
                    "url": t.url, "model": t.model,
                    "golden_version": t.version, "atol": t.atol,
                    "last_outcome": st.get("last_outcome"),
                    "consecutive_failures":
                        st.get("consecutive_failures", 0),
                    "probes": st.get("probes", 0),
                    "last_trace_id": st.get("last_trace_id"),
                    "last_detail": st.get("last_detail"),
                    "last_probe_t": st.get("last_probe_t"),
                    "last_success_age_s": (max(0.0, now - base)
                                           if base is not None else None),
                }
        return {"interval_s": self.interval_s,
                "timeout_s": self.timeout_s,
                "fail_threshold": self.fail_threshold,
                "running": self.running(),
                "targets": targets}

    # ---------------------------------------------------------- lifecycle
    def start(self, interval_s: Optional[float] = None) -> "Prober":
        """Start the background probe loop (idempotent). The thread is
        a daemon AND joined by :meth:`stop` — THR002 discipline."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="prober", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        # first probe immediately: the deadman baseline exists after one
        # interval, not two
        self._safe_tick()
        while not self._stop.wait(self.interval_s):
            self._safe_tick()

    def _safe_tick(self):
        try:
            self.tick()
        except Exception:
            log.exception("prober tick failed")

    def stop(self, timeout: float = 5.0):
        with self._lock:
            thread, self._thread = self._thread, None
            if thread is not None:
                # set the event INSIDE the lock: a concurrent start()
                # serializes behind us and clears it for ITS thread —
                # setting after release could kill the fresh loop on its
                # first wait() (same invariant as MetricsHistory.stop)
                self._stop.set()
        if thread is not None:
            thread.join(timeout)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()


#: lazily-created process-global prober (no thread, no targets until
#: someone configures and starts it — tier-1 suites run with zero
#: probers); the GET /probes endpoint serves its snapshot
_PROBER: Optional[Prober] = None
_PROBER_LOCK = threading.Lock()


def get_prober() -> Prober:
    global _PROBER
    with _PROBER_LOCK:
        if _PROBER is None:
            _PROBER = Prober()
        return _PROBER
