"""Metric history: a bounded ring of timestamped registry snapshots.

Every endpoint PRs 2-9 built (``/metrics``, ``/profile``, ``/fleet``,
``/trace``) is a point-in-time snapshot — nothing in the process can
answer "is p99 WORSE than five minutes ago" or "how many compiles
happened in the last minute", which is exactly what an alert rule needs
(monitor/alerts.py) and what a human wants first when paged. This module
closes that gap with the cheapest possible primitive: a bounded deque of
``(wall-clock t, MetricsRegistry.dump())`` samples taken by a background
sampler thread (interval ``DL4J_TPU_HISTORY_INTERVAL``, default 2 s; ring
capacity ``DL4J_TPU_HISTORY_SIZE``, default 512 — ~17 min at the default
interval), plus the window/rate/delta/quantile readers the alert engine
and the ``trends`` block of ``GET /profile`` are built on.

Windowed histogram quantiles are HONEST: ``quantile_over`` subtracts the
bucket counts of the oldest in-window sample from the newest, so the
quantile describes only the samples recorded INSIDE the window — a p99
breach clears once the slow requests age out, instead of being dragged
forever by the process-lifetime histogram. Units ride the dump's
per-family ``unit`` field, so seconds-valued series read in seconds.

The sampler is OPT-IN: nothing starts it implicitly (tier-1 suites run
with zero history threads), ``start()`` is idempotent, and ``stop()``
joins the thread. Each tick also drives the registered listeners — the
alert engine hooks itself in via :meth:`MetricsHistory.add_listener`, so
one thread both samples and evaluates.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .lockwatch import make_lock
from .registry import LatencyHistogram, get_registry

log = logging.getLogger(__name__)

__all__ = ["MetricsHistory", "get_history"]

#: background sampler cadence (seconds); the alert engine's hold-down and
#: burn-rate windows quantize to it
DEFAULT_INTERVAL_S = float(os.environ.get("DL4J_TPU_HISTORY_INTERVAL", "2"))

#: ring capacity (samples); oldest evicted first
DEFAULT_CAPACITY = int(os.environ.get("DL4J_TPU_HISTORY_SIZE", "512"))


def _match(row_labels: Dict[str, str], labels: Optional[Dict[str, str]]
           ) -> bool:
    """True when every requested label matches the child's (subset match —
    ``labels=None`` matches every child of the family)."""
    if not labels:
        return True
    return all(row_labels.get(k) == str(v) for k, v in labels.items())


class MetricsHistory:
    """Bounded ring of ``(t, dump)`` samples + windowed readers.

    All readers tolerate an empty or too-short ring by returning ``None``
    — an alert rule evaluated before two samples exist simply does not
    breach, it never crashes the sampler.
    """

    def __init__(self, capacity: Optional[int] = None,
                 interval_s: Optional[float] = None, registry=None):
        self.capacity = int(capacity or DEFAULT_CAPACITY)
        self.interval_s = float(interval_s or DEFAULT_INTERVAL_S)
        self._registry = registry
        self._lock = make_lock("MetricsHistory._lock")
        self._ring: deque = deque(maxlen=self.capacity)
        self._listeners: List[Callable[["MetricsHistory"], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ sampling
    def sample(self, now: Optional[float] = None) -> float:
        """Take one snapshot NOW (the sampler's tick; also the test seam —
        tests drive time explicitly instead of sleeping). Returns the
        sample's timestamp."""
        reg = self._registry if self._registry is not None else get_registry()
        dump = reg.dump()         # registry lock NOT held under ours
        t = float(now) if now is not None else time.time()
        with self._lock:
            self._ring.append((t, dump))
        return t

    def add_listener(self, fn: Callable[["MetricsHistory"], None]):
        """``fn(history)`` runs after every sampler tick (the alert
        engine's evaluation hook). Listener errors are logged, never
        fatal — a broken rule must not kill the sampler."""
        with self._lock:
            self._listeners.append(fn)

    def _tick(self):
        self.sample()
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(self)
            except Exception:
                log.exception("metrics-history listener %r failed", fn)

    def start(self, interval_s: Optional[float] = None) -> "MetricsHistory":
        """Start the background sampler (idempotent). The thread is a
        daemon AND joined by :meth:`stop` — tier-1's THR002 discipline."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="metrics-history-sampler",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        # first sample immediately: an alert engine attached at start
        # should see data after one interval, not two
        self._tick()
        while not self._stop.wait(self.interval_s):
            self._tick()

    def stop(self, timeout: float = 5.0):
        with self._lock:
            thread, self._thread = self._thread, None
            if thread is not None:
                # set the event INSIDE the lock: a concurrent start()
                # serializes behind us and clears it for ITS thread —
                # setting after release could kill the freshly started
                # sampler on its first wait()
                self._stop.set()
        if thread is not None:
            thread.join(timeout)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------- reading
    def samples(self) -> List[Tuple[float, dict]]:
        with self._lock:
            return list(self._ring)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()

    def window(self, seconds: float, now: Optional[float] = None
               ) -> List[Tuple[float, dict]]:
        """Samples no older than ``seconds`` (oldest first)."""
        now = float(now) if now is not None else time.time()
        cut = now - float(seconds)
        return [(t, d) for t, d in self.samples() if t >= cut]

    def covers(self, seconds: float, now: Optional[float] = None,
               tolerance_s: Optional[float] = None) -> bool:
        """True when the in-window samples actually SPAN the window (the
        oldest one sits within ``tolerance_s`` — default a quarter-window
        — of the far edge). Windowed math over an uncovered window
        silently describes a shorter span: a 30s-old ring would make a
        5-minute burn-rate window equal to the 30s one, and the
        multi-window SLO protection would degenerate to a single window
        (monitor/alerts.py guards every window with this)."""
        win = self.window(seconds, now=now)
        if len(win) < 2:
            return False
        tol = (float(tolerance_s) if tolerance_s is not None
               else 0.25 * float(seconds))
        return (win[-1][0] - win[0][0]) >= float(seconds) - tol

    def at_age(self, age_s: float, now: Optional[float] = None,
               tolerance_s: Optional[float] = None
               ) -> Optional[Tuple[float, dict]]:
        """The sample closest to ``now - age_s`` (None on an empty ring).
        ``tolerance_s`` rejects the match when nothing landed within that
        distance of the target — a 15s-old ring must answer "what was it
        5 minutes ago" with None, not with a 15s-old value silently
        mislabeled as 5-minutes-old (the trends block passes one)."""
        now = float(now) if now is not None else time.time()
        target = now - float(age_s)
        best = None
        for t, d in self.samples():
            if best is None or abs(t - target) < abs(best[0] - target):
                best = (t, d)
        if best is not None and tolerance_s is not None \
                and abs(best[0] - target) > float(tolerance_s):
            return None
        return best

    # ------------------------------------------------------- scalar math
    @staticmethod
    def value_of(dump: dict, metric: str,
                 labels: Optional[Dict[str, str]] = None,
                 agg: str = "sum") -> Optional[float]:
        """Aggregate of a dump family's matching scalar children (None
        when the family or a matching child is absent). ``agg="sum"``
        (counters, totals), ``"max"`` (the worst single child — e.g.
        "any one model's queue near ITS cap", where a sum across models
        would compare apples to one model's cap) or ``"min"`` (the
        weakest child — e.g. "any scrape target down" reads min of
        ``fleet_target_up`` across targets)."""
        fam = dump.get(metric)
        if not fam:
            return None
        vals = [row["value"] for row in fam.get("children", [])
                if "value" in row and _match(row.get("labels", {}), labels)]
        if not vals:
            return None
        if agg == "max":
            return float(max(vals))
        if agg == "min":
            return float(min(vals))
        return float(sum(vals))

    def current(self, metric: str,
                labels: Optional[Dict[str, str]] = None,
                agg: str = "sum") -> Optional[float]:
        """The newest sample's value (scrape-lag at most one interval)."""
        samples = self.samples()
        return (self.value_of(samples[-1][1], metric, labels, agg=agg)
                if samples else None)

    def delta(self, metric: str, seconds: float,
              labels: Optional[Dict[str, str]] = None,
              now: Optional[float] = None) -> Optional[float]:
        """newest − oldest-in-window for a counter family (None without at
        least two in-window samples). Missing-then-present families read
        as growth from 0 — a counter that first increments mid-window."""
        win = self.window(seconds, now=now)
        if len(win) < 2:
            return None
        v1 = self.value_of(win[-1][1], metric, labels)
        if v1 is None:
            return None
        v0 = self.value_of(win[0][1], metric, labels) or 0.0
        return v1 - v0

    def rate(self, metric: str, seconds: float,
             labels: Optional[Dict[str, str]] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a counter over the trailing window
        (one ring pass — delta and dt come from the same slice)."""
        win = self.window(seconds, now=now)
        if len(win) < 2:
            return None
        dt = win[-1][0] - win[0][0]
        if dt <= 0:
            return None
        v1 = self.value_of(win[-1][1], metric, labels)
        if v1 is None:
            return None
        v0 = self.value_of(win[0][1], metric, labels) or 0.0
        return (v1 - v0) / dt

    def max_over(self, metric: str, seconds: float,
                 labels: Optional[Dict[str, str]] = None,
                 now: Optional[float] = None,
                 agg: str = "sum") -> Optional[float]:
        """Max of a gauge across the in-window samples."""
        vals = [self.value_of(d, metric, labels, agg=agg)
                for _, d in self.window(seconds, now=now)]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    # ---------------------------------------------------- histogram math
    @staticmethod
    def _hist_state(dump: dict, metric: str,
                    labels: Optional[Dict[str, str]]
                    ) -> Optional[Tuple[List[int], float, str]]:
        """Merged (bucket counts, count, unit) of matching histogram
        children in one dump."""
        fam = dump.get(metric)
        if not fam or fam.get("type") != "histogram":
            return None
        counts = None
        n = 0.0
        for row in fam.get("children", []):
            if "buckets" not in row or not _match(row.get("labels", {}),
                                                 labels):
                continue
            if counts is None:
                counts = [0] * len(row["buckets"])
            for i, c in enumerate(row["buckets"]):
                counts[i] += c
            n += row.get("count", 0)
        if counts is None:
            return None
        return counts, n, fam.get("unit") or "ms"

    def quantile_over(self, metric: str, q: float, seconds: float,
                      labels: Optional[Dict[str, str]] = None,
                      now: Optional[float] = None) -> Optional[float]:
        """The q-quantile of ONLY the histogram samples recorded inside
        the trailing window, from bucket-count deltas (newest − oldest
        in-window) — bucket-upper-edge resolution, in the family's unit.
        None without two in-window samples or with zero in-window
        recordings (an idle histogram has no windowed p99, which alert
        rules treat as "no breach")."""
        win = self.window(seconds, now=now)
        if len(win) < 2:
            return None
        newest = self._hist_state(win[-1][1], metric, labels)
        if newest is None:
            return None
        counts1, n1, unit = newest
        oldest = self._hist_state(win[0][1], metric, labels)
        counts0, n0 = (oldest[0], oldest[1]) if oldest else \
            ([0] * len(counts1), 0.0)
        d_counts = [max(c1 - c0, 0) for c1, c0 in zip(counts1, counts0)]
        d_n = n1 - n0
        if d_n <= 0:
            return None
        edges = LatencyHistogram.bucket_edges(unit)
        rank = q * (d_n - 1)
        seen = 0
        for b, c in enumerate(d_counts):
            seen += c
            if seen > rank:
                return edges[b]
        return edges[-1]

    # ------------------------------------------------------- HTTP payload
    def describe(self) -> Dict[str, object]:
        """The ``GET /history`` default payload: ring meta + family names
        (series are fetched one at a time with ``?metric=``)."""
        samples = self.samples()
        return {
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "samples": len(samples),
            "running": self.running(),
            "oldest_t": samples[0][0] if samples else None,
            "newest_t": samples[-1][0] if samples else None,
            "metrics": sorted(samples[-1][1]) if samples else [],
        }

    def series(self, metric: str, seconds: Optional[float] = None,
               labels: Optional[Dict[str, str]] = None
               ) -> Dict[str, object]:
        """One metric's time series for ``GET /history?metric=``: scalars
        as ``{"t", "value"}`` points (summed across matching children),
        histograms as ``{"t", "count", "sum"}``."""
        samples = (self.window(seconds) if seconds is not None
                   else self.samples())
        points = []
        for t, dump in samples:
            fam = dump.get(metric)
            if not fam:
                continue
            if fam.get("type") == "histogram":
                st = self._hist_state(dump, metric, labels)
                if st is not None:
                    counts, n, _unit = st
                    total = sum(row.get("sum", 0.0)
                                for row in fam.get("children", [])
                                if _match(row.get("labels", {}), labels))
                    points.append({"t": t, "count": n, "sum": total})
            else:
                v = self.value_of(dump, metric, labels)
                if v is not None:
                    points.append({"t": t, "value": v})
        fam = samples[-1][1].get(metric) if samples else None
        return {"metric": metric,
                "type": fam.get("type") if fam else None,
                "unit": fam.get("unit") if fam else None,
                "points": points}


#: the process-global history the sampler/alert engine/endpoints share —
#: created eagerly (cheap: no thread until start())
_HISTORY = MetricsHistory()


def get_history() -> MetricsHistory:
    return _HISTORY
