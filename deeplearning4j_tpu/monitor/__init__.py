"""Unified monitor subsystem (docs/OBSERVABILITY.md).

One place to scrape, correlate, and alarm on everything the framework
does — replacing the three ad-hoc holders observability was fragmented
across (``ParamServerMetrics``, ``PerformanceListener``/
``StepTimerListener``, ``ui/stats``):

- :func:`get_registry` — the process-global :class:`MetricsRegistry`
  (labeled counters / gauges / histograms, Prometheus text rendering;
  served at ``GET /metrics`` on ``ui/server.py``).
- :func:`get_tracer` — the host-side span :class:`Tracer` (ring buffer,
  Chrome trace-event JSON at ``GET /trace``, nests
  ``jax.profiler.TraceAnnotation``).
- :func:`get_health` — the :class:`HealthState` behind ``GET /healthz``,
  plus :class:`TrainingHealthListener`, the NaN/divergence/stall watchdog
  with ``warn``/``raise``/``halt`` actions.
- :func:`get_flight_recorder` — the bounded structured event log (worker
  join/leave/rejoin, retry exhaustion, peer failures, health transitions)
  that dumps JSONL to disk on halt or crash.
- :func:`get_fleet` — per-worker telemetry shipped over the paramserver's
  ``OP_TELEMETRY``: the merged ``GET /fleet`` scrape, the merged
  multi-``pid`` Chrome trace, and worker staleness for ``/healthz``.
- :func:`get_collector` — the pull-based scrape plane: a
  :class:`TelemetryCollector` polling each replica's ``GET /telemetry``
  (registry + trace tail + seq-cursored flight events + health in one
  round trip) into the same :class:`FleetState` table, with a private
  history ring so the alert rules evaluate FLEET-scope SLOs
  (``default_fleet_scope_rules``).
- :func:`get_prober` — the probe plane: a :class:`Prober` firing real
  ``POST /v1/models/<m>/predict`` requests at each :class:`ProbeTarget`
  from the outside and comparing answers against the target's golden
  set (``ServedModel.golden()``) — the black-box correctness signal
  self-reported telemetry cannot provide (``default_probe_rules``).
- :func:`get_incident_recorder` — the incident plane: an
  :class:`IncidentRecorder` that captures the full diagnostic state at
  every alert *fire* edge (history window, pinned exemplar spans, flight
  events, jit table, lock census, probe/collector snapshots) into one
  merged :class:`Incident` per overlapping firing window and persists
  resolved incidents as content-addressed ``.dl4jinc`` bundles
  (``GET /incidents``, ``incident show``).
- :func:`get_history` — the bounded ring of timestamped registry
  snapshots behind ``GET /history`` and the ``trends`` block of
  ``/profile`` (opt-in background sampler; windowed rate/delta/quantile
  readers).
- :func:`get_alert_engine` — declarative threshold / burn-rate SLO rules
  evaluated over the history: OK→PENDING→FIRING with hold-down,
  ``alert_firing``/``alert_resolved`` flight events,
  ``alerts_firing{rule=}`` gauge, ``GET /alerts``.

The fit loops, transport channel, parameter-server client/server, and
async dataset iterator are pre-instrumented against these globals. The
per-iteration score fetch that instrumentation needs is a device→host
VALUE fetch (the completion barrier rule from ``utils/profiling.py``);
:func:`set_enabled` (False) turns the fit-loop instrumentation off for
benchmarks that need maximally-async stepping with no listeners attached.
"""
from __future__ import annotations

import os

import contextlib

from .lockwatch import (InstrumentedLock, LockWatch, get_lockwatch,
                        make_lock, make_rlock, make_condition)
from .registry import (MetricsRegistry, LatencyHistogram, Counter, Gauge,
                       Histogram, get_registry, render_prometheus_dump)
from .tracer import SpanContext, Tracer, get_tracer, new_context
from .health import (HealthState, get_health, TrainingHealthListener,
                     TrainingHealthError)
from .flightrec import FlightRecorder, get_flight_recorder
from .fleet import FleetState, get_fleet, merge_traces
from .history import MetricsHistory, get_history
from .alerts import (AlertEngine, AlertError, AlertRule, BurnRateRule,
                     FleetStalenessRule, HealthRule, ThresholdRule,
                     default_fleet_rules, default_fleet_scope_rules,
                     default_probe_rules, default_rules,
                     default_serving_rules, default_training_rules,
                     get_alert_engine)
from .collector import (ScrapeTarget, TelemetryCollector, get_collector,
                        telemetry_snapshot)
from .probes import ProbeTarget, Prober, get_prober
from .incidents import (Incident, IncidentRecorder, abort_open_incidents,
                        get_incident_recorder, load_bundle,
                        render_incident_text)
from .jitwatch import (MonitoredJit, JitRegistry, monitored_jit,
                       get_jit_registry, sample_device_memory,
                       maybe_sample_device_memory, profile_report,
                       render_profile_text)

__all__ = [
    "MetricsRegistry", "LatencyHistogram", "Counter", "Gauge", "Histogram",
    "get_registry", "render_prometheus_dump", "SpanContext", "Tracer",
    "get_tracer", "new_context", "HealthState", "get_health",
    "TrainingHealthListener", "TrainingHealthError",
    "FlightRecorder", "get_flight_recorder", "FleetState", "get_fleet",
    "merge_traces", "MonitoredJit", "JitRegistry", "monitored_jit",
    "get_jit_registry", "sample_device_memory",
    "maybe_sample_device_memory", "profile_report",
    "render_profile_text", "InstrumentedLock", "LockWatch",
    "get_lockwatch", "make_lock", "make_rlock", "make_condition",
    "MetricsHistory", "get_history", "AlertEngine", "AlertError",
    "AlertRule", "ThresholdRule", "BurnRateRule", "HealthRule",
    "FleetStalenessRule", "get_alert_engine", "default_rules",
    "default_serving_rules", "default_training_rules",
    "default_fleet_rules", "default_fleet_scope_rules",
    "default_probe_rules",
    "ScrapeTarget", "TelemetryCollector", "get_collector",
    "telemetry_snapshot", "ProbeTarget", "Prober", "get_prober",
    "Incident", "IncidentRecorder", "get_incident_recorder",
    "abort_open_incidents", "load_bundle", "render_incident_text",
    "set_enabled", "enabled", "record_training_iteration", "step_span",
]

#: fit-loop instrumentation switch — when False the containers skip the
#: per-iteration value fetch (and all metric/health writes) unless
#: listeners are attached, restoring fully-async dispatch. Defaults on
#: (a bare fit populates /metrics and /healthz); flip per process with
#: DL4J_TPU_MONITOR=0 or at runtime with set_enabled(False).
_ENABLED = os.environ.get("DL4J_TPU_MONITOR", "1") not in ("0", "false", "")


def set_enabled(value: bool):
    global _ENABLED
    _ENABLED = bool(value)


def enabled() -> bool:
    return _ENABLED


@contextlib.contextmanager
def step_span(iteration: int):
    """The per-minibatch training span. The caller MUST perform its
    device→host value fetch (``float(loss)``) inside this span so the span
    measures the finished step, not its dispatch (value-fetch barrier rule,
    ``utils/profiling.py``). Span close also samples the device-memory
    gauges (throttled, AFTER the span ends so the sampling cost never
    inflates the step duration) — the step boundary is where
    donation/sharding decisions have just landed, so
    ``device_memory_in_use_bytes`` tracks the working set step-by-step
    (docs/OBSERVABILITY.md "Compilation & memory")."""
    try:
        with get_tracer().span("step", cat="train",
                               iteration=int(iteration)) as ctx:
            yield ctx
    finally:
        maybe_sample_device_memory()


def record_training_iteration(model, iteration: int, score: float,
                              batch_size: int = 0, step_ms: float = None,
                              etl_ms: float = None):
    """One call per applied minibatch from the container fit loops: bumps
    the training counters/gauges and the health liveness state."""
    reg = get_registry()
    reg.counter("training_iterations_total",
                "optimizer iterations applied").inc()
    reg.gauge("training_score", "last minibatch score").set(score)
    reg.gauge("training_iteration", "last iteration index").set(iteration)
    if batch_size:
        reg.counter("training_examples_total",
                    "examples consumed by fit").inc(batch_size)
    if step_ms is not None:
        reg.histogram("training_step_ms",
                      "wall-clock per applied step, value-fetch "
                      "barrier included").observe(step_ms)
    if etl_ms is not None:
        reg.histogram("training_etl_ms",
                      "host wait for the next minibatch").observe(etl_ms)
    get_health().record_iteration(iteration, score)
