"""Crash flight recorder: bounded structured event log → JSONL dump.

Metrics say *how much*, traces say *how long* — neither says *what
happened*: why a worker vanished at 14:03, whether it rejoined, which rank
died mid-gather, what tripped the health watchdog. This module is the
black box for exactly those discrete operational events. Feeders across
the stack append structured records (worker join/leave/rejoin from the
paramserver training master, retry-budget exhaustion from the client,
``PeerFailedError`` from the transport mesh, health problems and halts
from ``monitor/health.py``); the buffer is bounded and thread-safe, so
recording is always safe from hot paths and serve loops.

The buffer reaches disk as JSONL (one JSON object per line, append-
friendly, greppable) on the three paths that matter:

- ``TrainingHealthListener`` halt → ``HealthState.record_halt`` dumps;
- an uncaught exception → the crash hook (installed on first
  :func:`get_flight_recorder` use) dumps before delegating to the
  previous ``sys.excepthook``;
- explicitly, via :meth:`FlightRecorder.dump` or the
  ``monitor --events`` CLI view.

``DL4J_TPU_FLIGHT_DIR`` picks the dump directory (default: the system
temp dir). See docs/OBSERVABILITY.md "Fleet observability".
"""
from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = ["FlightRecorder", "get_flight_recorder", "install_crash_hook"]


class FlightRecorder:
    """Bounded, thread-safe structured event log.

    Each record is ``{"t": wall-clock seconds, "seq": monotonic sequence
    number, "event": kind, ...fields}``. ``seq`` survives into dumps so
    event ORDER is provable even when two events land within clock
    resolution (the join/leave/rejoin assertions depend on it). The newest
    ``capacity`` events win; evictions are counted (``dropped``), never
    silent.
    """

    def __init__(self, capacity: int = 4096,
                 dump_dir: Optional[str] = None):
        from .lockwatch import make_lock
        self._lock = make_lock("FlightRecorder._lock")
        self._events = deque(maxlen=int(capacity))
        self._seq = 0
        self.dropped = 0
        self.dump_dir = dump_dir
        self.last_dump_path: Optional[str] = None

    def record(self, event: str, **fields) -> Dict[str, object]:
        """Append one structured event; returns the stored record. Fields
        must be JSON-serializable scalars (enforced at dump time, not here
        — recording must never raise into a training loop)."""
        rec = {"t": time.time(), "event": str(event), **fields}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(rec)
        return rec

    def events(self) -> List[Dict[str, object]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)

    # ------------------------------------------------------------- dumping
    def _default_path(self) -> str:
        base = (self.dump_dir
                or os.environ.get("DL4J_TPU_FLIGHT_DIR")
                or tempfile.gettempdir())
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        return os.path.join(base, f"flightrec-{os.getpid()}-{stamp}.jsonl")

    def dump(self, path: Optional[str] = None, reason: str = "explicit"
             ) -> Optional[str]:
        """Write the buffer to ``path`` (default: a timestamped file in the
        dump dir) as JSONL and return the path — or None when the write
        failed (a dying process must never die harder because its black
        box had no disk). Non-serializable field values degrade to repr."""
        path = path or self._default_path()
        events = self.events()
        try:
            with open(path, "w") as fh:
                for rec in events:
                    fh.write(json.dumps(rec, default=repr) + "\n")
        except OSError as e:
            log.warning("flight-recorder dump to %s failed: %s", path, e)
            return None
        self.last_dump_path = path
        log.info("flight recorder: %d event(s) dumped to %s (%s)",
                 len(events), path, reason)
        return path


#: the process-global recorder every subsystem feeds
_RECORDER = FlightRecorder()
_HOOK_INSTALLED = False
_HOOK_LOCK = threading.Lock()


def install_crash_hook():
    """Chain a ``sys.excepthook`` that dumps the flight recorder before
    delegating to the previous hook — the 'process crashes' dump path.
    Idempotent; keeps whatever hook was installed before (pytest, IPython,
    user hooks) fully functional."""
    global _HOOK_INSTALLED
    with _HOOK_LOCK:
        if _HOOK_INSTALLED:
            return
        prev = sys.excepthook

        def _dump_and_delegate(exc_type, exc, tb):
            _RECORDER.record("crash", error=repr(exc),
                             error_type=exc_type.__name__)
            _RECORDER.dump(reason="uncaught exception")
            prev(exc_type, exc, tb)

        sys.excepthook = _dump_and_delegate
        _HOOK_INSTALLED = True


def get_flight_recorder() -> FlightRecorder:
    """The process-global :class:`FlightRecorder`. First use arms the
    crash-dump excepthook so an uncaught exception leaves a JSONL black
    box behind."""
    install_crash_hook()
    return _RECORDER
