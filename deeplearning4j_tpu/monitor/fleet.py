"""Fleet state: per-worker telemetry aggregated at the parameter server.

A data-parallel run over the paramserver is N processes with N disjoint
monitor registries, N trace ring buffers, and N health states. Workers
periodically ship a compact telemetry report over the PS protocol's
``OP_TELEMETRY`` (``paramserver/client.py .send_telemetry``); the server
lands every report here, in the process-global :class:`FleetState`
(:func:`get_fleet`). What that buys:

- ``GET /fleet`` (``ui/server.py``): the merged registry view as
  Prometheus text, every worker's series re-labeled with ``worker=<id>``
  (via ``registry.render_prometheus_dump``), plus synthesized
  ``fleet_worker_up`` / ``fleet_worker_last_seen_age_s`` liveness series.
- ``GET /fleet/trace``: a merged Chrome-trace export — each process on
  its own ``pid`` row (metadata ``process_name`` events), with the
  propagated trace IDs (``tracer.SpanContext``) tying a client ``ps/push``
  span to the server's ``ps/apply`` span across rows.
- Per-worker liveness folded into ``/healthz``: a worker whose last
  report is older than ``stale_after`` is marked stale (the dead-worker
  signal an external prober alarms on).

See docs/OBSERVABILITY.md "Fleet observability".
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import render_prometheus_dump

__all__ = ["FleetState", "get_fleet", "merge_traces"]

#: seconds without a telemetry report before a worker counts as stale
DEFAULT_STALE_AFTER = 15.0

#: per-worker merged-trace retention (events). Reports ACCUMULATE here
#: (each one ships only the newest ring tail, so replacement would drop
#: spans older than one report); the bound keeps a chatty worker from
#: growing the fleet table without limit.
TRACE_EVENTS_PER_WORKER = 4096


def _span_key(ev: dict):
    """Identity of one span occurrence: (trace_id, span_id, ts). The
    telemetry clients ship the newest ring TAIL each report, so
    consecutive reports overlap — this key is what merge-time dedup
    collapses on. Events without the full key (metadata rows, foreign
    formats) get None: never deduped."""
    args = ev.get("args") or {}
    tid, sid, ts = args.get("trace_id"), args.get("span_id"), ev.get("ts")
    if tid is None or sid is None or ts is None:
        return None
    return (tid, sid, ts)


def merge_traces(named_events: Dict[str, List[dict]],
                 pids: Optional[Dict[str, int]] = None) -> dict:
    """Merge per-process trace-event lists into ONE Chrome-trace document:
    each label gets its own ``pid`` row (with a ``process_name`` metadata
    event, so Perfetto shows 'worker:w1' instead of a bare number) while
    ``tid`` and the propagated ``trace_id``/``span_id`` args survive
    untouched — causality across rows stays visible.

    ``pids`` maps label → pid row; labels not in the map are numbered
    after the mapped rows in sorted order. Without a map, pids follow
    sorted-label enumeration — which RENUMBERS every row when a label
    joins or leaves, so callers exporting repeatedly (the fleet table)
    pass their stable assignment. Duplicate span occurrences (same
    ``(trace_id, span_id, ts)`` — overlapping telemetry report windows)
    are dropped after their first appearance."""
    pids = dict(pids or {})
    next_pid = max(pids.values(), default=-1) + 1
    for label in sorted(named_events):
        if label not in pids:
            pids[label] = next_pid
            next_pid += 1
    events: List[dict] = []
    seen = set()
    for label in sorted(named_events, key=lambda lb: pids[lb]):
        pid = pids[label]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        for ev in named_events[label]:
            key = _span_key(ev)
            if key is not None:
                if key in seen:
                    continue
                seen.add(key)
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FleetState:
    """Thread-safe per-worker last-report table.

    One per process via :func:`get_fleet` (the parameter server feeds it;
    the UI server and ``/healthz`` read it), or standalone in tests.
    Staleness is computed at READ time from ``last_seen`` — a silent
    worker's age keeps growing, exactly like ``/healthz``'s
    ``last_iteration_age_s``.
    """

    def __init__(self, stale_after: float = DEFAULT_STALE_AFTER):
        self.stale_after = float(stale_after)
        from .lockwatch import make_lock
        self._lock = make_lock("FleetState._lock")
        self._workers: Dict[str, dict] = {}
        #: stable label → pid assignment for merged traces: a label keeps
        #: its pid for the table's lifetime, so a replica joining or
        #: leaving never renumbers the other Perfetto process rows
        #: between successive exports
        self._pids: Dict[str, int] = {}

    def _pid_for_locked(self, label: str) -> int:
        """First-seen pid assignment (caller holds ``_lock``). Pids are
        never reused or renumbered while the table lives; ``clear()``
        resets the assignment with everything else."""
        if label not in self._pids:
            self._pids[label] = max(self._pids.values(), default=-1) + 1
        return self._pids[label]

    # ------------------------------------------------------------- feeding
    def record_report(self, worker: str, report: dict, *,
                      append_flight: bool = False):
        """Land one telemetry report — pushed over ``OP_TELEMETRY`` or
        pulled by the scrape-plane collector (monitor/collector.py), the
        table cannot tell and the merged surfaces must not: ``registry``
        (a ``MetricsRegistry.dump()``), optional ``trace_events`` (Chrome
        trace events), ``flight_events``, ``exemplars`` and ``health`` —
        all already plain JSON from the wire.

        Trace events ACCUMULATE into a bounded per-worker ring, deduped
        by ``(trace_id, span_id, ts)`` — clients ship the newest ring
        tail each report, so consecutive reports overlap; replacement
        would drop history, blind appending would duplicate every
        overlapped span. ``append_flight=True`` (the collector's
        cursored feed, where each report carries only NEW events)
        extends the flight-event ring instead of replacing it."""
        worker = str(worker)
        with self._lock:
            entry = self._workers.setdefault(
                worker, {"first_seen": time.time(), "reports": 0})
            self._pid_for_locked(f"worker:{worker}")
            entry["last_seen"] = time.time()
            entry["reports"] += 1
            entry["registry"] = report.get("registry") or {}
            if report.get("trace_events") is not None:
                ring = entry.setdefault(
                    "trace_events", deque(maxlen=TRACE_EVENTS_PER_WORKER))
                seen = {_span_key(ev) for ev in ring}
                seen.discard(None)
                for ev in report["trace_events"]:
                    key = _span_key(ev)
                    if key is not None and key in seen:
                        continue
                    if key is not None:
                        seen.add(key)
                    ring.append(ev)
            if report.get("flight_events") is not None:
                if append_flight:
                    ring = entry.setdefault(
                        "flight_events",
                        deque(maxlen=TRACE_EVENTS_PER_WORKER))
                    ring.extend(report["flight_events"])
                else:
                    entry["flight_events"] = list(report["flight_events"])
            if report.get("exemplars") is not None:
                entry["exemplars"] = dict(report["exemplars"])
            if report.get("health") is not None:
                entry["health"] = report["health"]

    def clear(self):
        with self._lock:
            self._workers.clear()
            self._pids.clear()

    # ------------------------------------------------------------- reading
    def liveness(self) -> dict:
        """JSON liveness table: the ``/fleet?format=json`` payload and the
        block ``/healthz`` folds in. When workers report sharded-
        paramserver series, a per-shard rollup rides along as
        ``"shards"`` (see :meth:`shard_block`)."""
        now = time.time()
        with self._lock:
            workers = {
                w: {"last_seen_age_s": now - e["last_seen"],
                    "stale": (now - e["last_seen"]) > self.stale_after,
                    "reports": e["reports"],
                    "series": len(e.get("registry") or {})}
                for w, e in self._workers.items()}
        out = {"stale_after_s": self.stale_after,
               "workers": workers,
               "stale": sorted(w for w, i in workers.items()
                               if i["stale"])}
        shards = self.shard_block()
        if shards:
            out["shards"] = shards
        return out

    def shard_block(self) -> Dict[str, dict]:
        """Per-shard rollup of the sharded-paramserver series workers ship
        over OP_TELEMETRY (docs/PARALLELISM.md "Sharded parameter-server
        fleet"): for each shard label, the max ``paramserver_shard_
        staleness`` across workers (and the per-worker values — the
        rebalance/dead-shard audit view), plus ``paramserver_wire_bytes_
        total`` summed over ops/directions/workers. Empty when no worker
        reports the series (a fleet without the sharded client)."""
        with self._lock:
            regs = {w: e.get("registry") or {}
                    for w, e in self._workers.items()}
        shards: Dict[str, dict] = {}

        def entry(label: str) -> dict:
            return shards.setdefault(label, {
                "staleness_max": 0.0, "staleness": {},
                "wire_bytes": {"tx": 0.0, "rx": 0.0}})

        for worker, reg in regs.items():
            fam = reg.get("paramserver_shard_staleness") or {}
            for row in fam.get("children", []):
                label = row.get("labels", {}).get("shard")
                if label is None:
                    continue
                ent = entry(label)
                value = float(row.get("value", 0.0))
                ent["staleness"][worker] = value
                ent["staleness_max"] = max(ent["staleness_max"], value)
            fam = reg.get("paramserver_wire_bytes_total") or {}
            for row in fam.get("children", []):
                labels = row.get("labels", {})
                label = labels.get("shard")
                direction = labels.get("direction")
                # client rows only: a worker co-hosting a shard node ships
                # BOTH roles in one registry, and the server rows are the
                # same bytes seen from the other end — summing both would
                # double-count every frame
                if label is None or direction not in ("tx", "rx") \
                        or labels.get("role") != "client":
                    continue
                entry(label)["wire_bytes"][direction] += \
                    float(row.get("value", 0.0))
        return shards

    def merged_dump(self) -> Dict[str, dict]:
        """The merged fleet registry view as a DUMP (the wire shape
        ``MetricsRegistry.dump()`` produces): every worker's shipped
        series re-labeled ``worker=<id>``, preceded by the synthesized
        ``fleet_worker_up`` / ``fleet_worker_last_seen_age_s`` liveness
        series (staleness computed at read time, as always). This is
        what ``/fleet`` renders AND what the scrape-plane collector's
        history ring samples — one merge, two surfaces, so alert rules
        evaluated over the fleet history see exactly the series a
        Prometheus scrape would. Type conflicts across workers (same
        family name, different type — a half-upgraded fleet) keep the
        first-seen type and drop the conflicting worker's children for
        that family rather than emitting an invalid exposition; the
        per-family ``unit`` rides along so windowed quantiles over the
        merged dump read bucket edges in the right unit."""
        now = time.time()
        with self._lock:
            items = [(w, e.get("registry") or {}, now - e["last_seen"])
                     for w, e in sorted(self._workers.items())]
        up = {"type": "gauge", "help": "1 while the worker's telemetry is "
              "fresh, 0 once stale", "children": []}
        age = {"type": "gauge",
               "help": "seconds since the worker's last telemetry report",
               "children": []}
        merged: Dict[str, dict] = {"fleet_worker_up": up,
                                   "fleet_worker_last_seen_age_s": age}
        for worker, dump, age_s in items:
            up["children"].append(
                {"labels": {"worker": worker},
                 "value": 0.0 if age_s > self.stale_after else 1.0})
            age["children"].append(
                {"labels": {"worker": worker}, "value": age_s})
            for name, fam in dump.items():
                tgt = merged.setdefault(
                    name, {"type": fam["type"],
                           "help": fam.get("help", ""), "children": []})
                if tgt["type"] != fam["type"]:
                    continue        # mixed-version fleet: skip, don't lie
                if "unit" in fam:
                    tgt.setdefault("unit", fam["unit"])
                for row in fam["children"]:
                    row = dict(row)
                    row["labels"] = {**row["labels"], "worker": worker}
                    tgt["children"].append(row)
        return merged

    def render_prometheus(self) -> str:
        """The merged fleet scrape: :meth:`merged_dump` as Prometheus
        text."""
        return render_prometheus_dump(self.merged_dump())

    def worst_exemplar(self, metric: str,
                       worker: Optional[str] = None) -> Optional[str]:
        """The worst latched exemplar trace id a worker shipped for
        ``metric`` (``worker=None``: across the whole fleet). Exemplars
        live only in each replica's LIVE registry, so the ``/telemetry``
        reply carries them explicitly and the fleet-scope latency rules
        read them here — a fleet p99 alert must point at the guilty
        replica's offending request, resolvable on THAT replica's
        ``/trace``."""
        with self._lock:
            rows = [(w, e.get("exemplars") or {})
                    for w, e in self._workers.items()
                    if worker is None or w == str(worker)]
        worst = None
        for _w, exemplars in rows:
            for row in exemplars.get(metric) or []:
                if row.get("exemplar") is None:
                    continue
                if worst is None or row.get("value", 0.0) > worst[0]:
                    worst = (row.get("value", 0.0), row["exemplar"])
        return worst[1] if worst else None

    def merged_trace(self, local_events: Optional[List[dict]] = None,
                     local_label: str = "server") -> dict:
        """One Chrome-trace document for the whole fleet: every worker's
        shipped trace events plus this process's own (default: the global
        tracer — the server-side ``ps/apply`` spans live there), each on
        its own STABLE ``pid`` row (first-seen assignment, so a replica
        joining or leaving between exports never renumbers the others),
        overlapping report windows deduped by (trace_id, span_id, ts)."""
        with self._lock:
            named = {f"worker:{w}": list(e.get("trace_events") or [])
                     for w, e in self._workers.items()}
            pids = {label: self._pid_for_locked(label)
                    for label in list(named) + [local_label]}
        if local_events is None:
            from .tracer import get_tracer
            local_events = get_tracer().events()
        named[local_label] = list(local_events)
        return merge_traces(named, pids=pids)


#: the process-global fleet table (the parameter server writes, the UI
#: server and /healthz read)
_FLEET = FleetState()


def get_fleet() -> FleetState:
    return _FLEET
