"""Fleet state: per-worker telemetry aggregated at the parameter server.

A data-parallel run over the paramserver is N processes with N disjoint
monitor registries, N trace ring buffers, and N health states. Workers
periodically ship a compact telemetry report over the PS protocol's
``OP_TELEMETRY`` (``paramserver/client.py .send_telemetry``); the server
lands every report here, in the process-global :class:`FleetState`
(:func:`get_fleet`). What that buys:

- ``GET /fleet`` (``ui/server.py``): the merged registry view as
  Prometheus text, every worker's series re-labeled with ``worker=<id>``
  (via ``registry.render_prometheus_dump``), plus synthesized
  ``fleet_worker_up`` / ``fleet_worker_last_seen_age_s`` liveness series.
- ``GET /fleet/trace``: a merged Chrome-trace export — each process on
  its own ``pid`` row (metadata ``process_name`` events), with the
  propagated trace IDs (``tracer.SpanContext``) tying a client ``ps/push``
  span to the server's ``ps/apply`` span across rows.
- Per-worker liveness folded into ``/healthz``: a worker whose last
  report is older than ``stale_after`` is marked stale (the dead-worker
  signal an external prober alarms on).

See docs/OBSERVABILITY.md "Fleet observability".
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .registry import render_prometheus_dump

__all__ = ["FleetState", "get_fleet", "merge_traces"]

#: seconds without a telemetry report before a worker counts as stale
DEFAULT_STALE_AFTER = 15.0


def merge_traces(named_events: Dict[str, List[dict]]) -> dict:
    """Merge per-process trace-event lists into ONE Chrome-trace document:
    each label gets its own ``pid`` row (with a ``process_name`` metadata
    event, so Perfetto shows 'worker:w1' instead of a bare number) while
    ``tid`` and the propagated ``trace_id``/``span_id`` args survive
    untouched — causality across rows stays visible."""
    events: List[dict] = []
    for pid, label in enumerate(sorted(named_events)):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        for ev in named_events[label]:
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FleetState:
    """Thread-safe per-worker last-report table.

    One per process via :func:`get_fleet` (the parameter server feeds it;
    the UI server and ``/healthz`` read it), or standalone in tests.
    Staleness is computed at READ time from ``last_seen`` — a silent
    worker's age keeps growing, exactly like ``/healthz``'s
    ``last_iteration_age_s``.
    """

    def __init__(self, stale_after: float = DEFAULT_STALE_AFTER):
        self.stale_after = float(stale_after)
        from .lockwatch import make_lock
        self._lock = make_lock("FleetState._lock")
        self._workers: Dict[str, dict] = {}

    # ------------------------------------------------------------- feeding
    def record_report(self, worker: str, report: dict):
        """Land one OP_TELEMETRY report: ``registry`` (a
        ``MetricsRegistry.dump()``), optional ``trace_events`` (Chrome
        trace events) and ``flight_events`` — all already plain JSON from
        the wire."""
        worker = str(worker)
        with self._lock:
            entry = self._workers.setdefault(
                worker, {"first_seen": time.time(), "reports": 0})
            entry["last_seen"] = time.time()
            entry["reports"] += 1
            entry["registry"] = report.get("registry") or {}
            if report.get("trace_events") is not None:
                entry["trace_events"] = list(report["trace_events"])
            if report.get("flight_events") is not None:
                entry["flight_events"] = list(report["flight_events"])

    def clear(self):
        with self._lock:
            self._workers.clear()

    # ------------------------------------------------------------- reading
    def liveness(self) -> dict:
        """JSON liveness table: the ``/fleet?format=json`` payload and the
        block ``/healthz`` folds in. When workers report sharded-
        paramserver series, a per-shard rollup rides along as
        ``"shards"`` (see :meth:`shard_block`)."""
        now = time.time()
        with self._lock:
            workers = {
                w: {"last_seen_age_s": now - e["last_seen"],
                    "stale": (now - e["last_seen"]) > self.stale_after,
                    "reports": e["reports"],
                    "series": len(e.get("registry") or {})}
                for w, e in self._workers.items()}
        out = {"stale_after_s": self.stale_after,
               "workers": workers,
               "stale": sorted(w for w, i in workers.items()
                               if i["stale"])}
        shards = self.shard_block()
        if shards:
            out["shards"] = shards
        return out

    def shard_block(self) -> Dict[str, dict]:
        """Per-shard rollup of the sharded-paramserver series workers ship
        over OP_TELEMETRY (docs/PARALLELISM.md "Sharded parameter-server
        fleet"): for each shard label, the max ``paramserver_shard_
        staleness`` across workers (and the per-worker values — the
        rebalance/dead-shard audit view), plus ``paramserver_wire_bytes_
        total`` summed over ops/directions/workers. Empty when no worker
        reports the series (a fleet without the sharded client)."""
        with self._lock:
            regs = {w: e.get("registry") or {}
                    for w, e in self._workers.items()}
        shards: Dict[str, dict] = {}

        def entry(label: str) -> dict:
            return shards.setdefault(label, {
                "staleness_max": 0.0, "staleness": {},
                "wire_bytes": {"tx": 0.0, "rx": 0.0}})

        for worker, reg in regs.items():
            fam = reg.get("paramserver_shard_staleness") or {}
            for row in fam.get("children", []):
                label = row.get("labels", {}).get("shard")
                if label is None:
                    continue
                ent = entry(label)
                value = float(row.get("value", 0.0))
                ent["staleness"][worker] = value
                ent["staleness_max"] = max(ent["staleness_max"], value)
            fam = reg.get("paramserver_wire_bytes_total") or {}
            for row in fam.get("children", []):
                labels = row.get("labels", {})
                label = labels.get("shard")
                direction = labels.get("direction")
                # client rows only: a worker co-hosting a shard node ships
                # BOTH roles in one registry, and the server rows are the
                # same bytes seen from the other end — summing both would
                # double-count every frame
                if label is None or direction not in ("tx", "rx") \
                        or labels.get("role") != "client":
                    continue
                entry(label)["wire_bytes"][direction] += \
                    float(row.get("value", 0.0))
        return shards

    def render_prometheus(self) -> str:
        """The merged fleet scrape: every worker's shipped registry dump
        re-rendered with a ``worker`` label, preceded by the synthesized
        liveness series. Type conflicts across workers (same family name,
        different type — a half-upgraded fleet) keep the first-seen type
        and drop the conflicting worker's children for that family rather
        than emitting an invalid exposition."""
        now = time.time()
        with self._lock:
            items = [(w, e.get("registry") or {}, now - e["last_seen"])
                     for w, e in sorted(self._workers.items())]
        up = {"type": "gauge", "help": "1 while the worker's telemetry is "
              "fresh, 0 once stale", "children": []}
        age = {"type": "gauge",
               "help": "seconds since the worker's last telemetry report",
               "children": []}
        merged: Dict[str, dict] = {"fleet_worker_up": up,
                                   "fleet_worker_last_seen_age_s": age}
        for worker, dump, age_s in items:
            up["children"].append(
                {"labels": {"worker": worker},
                 "value": 0.0 if age_s > self.stale_after else 1.0})
            age["children"].append(
                {"labels": {"worker": worker}, "value": age_s})
            for name, fam in dump.items():
                tgt = merged.setdefault(
                    name, {"type": fam["type"],
                           "help": fam.get("help", ""), "children": []})
                if tgt["type"] != fam["type"]:
                    continue        # mixed-version fleet: skip, don't lie
                for row in fam["children"]:
                    row = dict(row)
                    row["labels"] = {**row["labels"], "worker": worker}
                    tgt["children"].append(row)
        return render_prometheus_dump(merged)

    def merged_trace(self, local_events: Optional[List[dict]] = None,
                     local_label: str = "server") -> dict:
        """One Chrome-trace document for the whole fleet: every worker's
        shipped trace events plus this process's own (default: the global
        tracer — the server-side ``ps/apply`` spans live there), each on
        its own ``pid`` row."""
        with self._lock:
            named = {f"worker:{w}": list(e.get("trace_events") or [])
                     for w, e in self._workers.items()}
        if local_events is None:
            from .tracer import get_tracer
            local_events = get_tracer().events()
        named[local_label] = list(local_events)
        return merge_traces(named)


#: the process-global fleet table (the parameter server writes, the UI
#: server and /healthz read)
_FLEET = FleetState()


def get_fleet() -> FleetState:
    return _FLEET
