"""Training health: process-global liveness state + watchdog listener.

Two pieces:

- :class:`HealthState` (:func:`get_health`): the thread-safe snapshot the
  ``GET /healthz`` endpoint serves — last-iteration age, last score, a NaN
  latch, halt state, and parameter-server connectivity (fed by
  ``paramserver/client.py``). The fit loops feed it automatically through
  ``monitor.record_training_iteration``, so a NaN training score flips
  ``/healthz`` unhealthy with no listener attached.

- :class:`TrainingHealthListener`: a listener-bus watchdog detecting
  NaN/Inf score (and optionally params), score divergence, and stalled
  iterations, with configurable ``warn`` / ``raise`` / ``halt`` actions.
  ``halt`` sets ``model.halt_requested``, which both containers' ``fit``
  loops check between minibatches — a graceful stop instead of an
  exception unwinding through the training stack.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..optimize.listeners import TrainingListener

log = logging.getLogger(__name__)

__all__ = ["HealthState", "get_health", "TrainingHealthListener",
           "TrainingHealthError"]


class TrainingHealthError(RuntimeError):
    """Raised by :class:`TrainingHealthListener` under ``action="raise"``.
    ``kind`` is one of ``"nan"``, ``"divergence"``, ``"stall"``,
    ``"retrace"``."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


class HealthState:
    """Thread-safe process-global liveness snapshot (the ``/healthz``
    payload). All times are wall-clock; ages are computed at snapshot
    time so a stalled process reports a growing age, not a stale one."""

    def __init__(self):
        from .lockwatch import make_lock
        self._lock = make_lock("HealthState._lock")
        self.reset()

    def reset(self):
        with self._lock:
            self._last_iteration_time: Optional[float] = None
            self._last_iteration: Optional[int] = None
            self._last_score: Optional[float] = None
            self._nan = False
            self._halted: Optional[str] = None
            self._problems: List[str] = []
            self._ps_ops = 0
            self._ps_errors = 0
            self._ps_last_error: Optional[str] = None
            self._ps_connected: Optional[bool] = None

    # ------------------------------------------------------------- feeders
    def record_iteration(self, iteration: int, score: float):
        with self._lock:
            self._last_iteration_time = time.time()
            self._last_iteration = int(iteration)
            self._last_score = float(score)
            if not math.isfinite(float(score)):
                self._nan = True

    def record_problem(self, kind: str, message: str):
        with self._lock:
            if kind == "nan":
                self._nan = True
            self._problems.append(f"{kind}: {message}")
            del self._problems[:-8]  # keep the newest few
        from .flightrec import get_flight_recorder
        get_flight_recorder().record("health_problem", kind=kind,
                                     message=message)

    def record_halt(self, reason: str):
        with self._lock:
            self._halted = reason
        # the black-box moment: training is stopping on purpose — persist
        # the event history NOW, while the process is still healthy enough
        # to write it (docs/OBSERVABILITY.md flight recorder)
        from .flightrec import get_flight_recorder
        fr = get_flight_recorder()
        fr.record("halt", reason=reason)
        fr.dump(reason="training halt")
        # a halt mid-incident must leave the incident's evidence on disk
        # too, not just the raw event log — but only when the incident
        # plane was ever wired (sys.modules gate: a bare process pays
        # nothing, and the flush must never make the halt path die harder)
        import sys
        inc = sys.modules.get("deeplearning4j_tpu.monitor.incidents")
        if inc is not None:
            try:
                inc.abort_open_incidents(reason=f"halt: {reason}")
            except Exception:
                log.exception("incident flush on halt failed")

    def clear_halt(self):
        """A new fit() run supersedes a previous halt (the containers call
        this on entry) — /healthz goes healthy again once training resumes."""
        with self._lock:
            self._halted = None

    def record_ps_ok(self):
        with self._lock:
            self._ps_ops += 1
            self._ps_connected = True

    def record_ps_error(self, message: str):
        with self._lock:
            self._ps_errors += 1
            self._ps_last_error = str(message)
            self._ps_connected = False

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            age = (None if self._last_iteration_time is None
                   else time.time() - self._last_iteration_time)
            healthy = (not self._nan and self._halted is None
                       and self._ps_connected is not False)
            out = {
                "status": "ok" if healthy else "unhealthy",
                "healthy": healthy,
                "last_iteration": self._last_iteration,
                "last_iteration_age_s": age,
                "last_score": self._last_score,
                "nan": self._nan,
                "halted": self._halted,
                "problems": list(self._problems),
                "paramserver": {
                    "connected": self._ps_connected,
                    "ops": self._ps_ops,
                    "errors": self._ps_errors,
                    "last_error": self._ps_last_error,
                },
            }
        # fleet liveness fold-in (outside the lock: the fleet table has its
        # own): on a paramserver-server process /healthz also answers "are
        # the WORKERS alive" — stale workers are listed but do not flip
        # this process unhealthy (a dead worker is the fleet view's alarm;
        # this process is still serving)
        from .fleet import get_fleet
        fleet = get_fleet().liveness()
        if fleet["workers"]:
            out["fleet"] = fleet
        return out


_HEALTH = HealthState()


def get_health() -> HealthState:
    return _HEALTH


class TrainingHealthListener(TrainingListener):
    """Listener-bus training watchdog.

    Checks, per iteration:

    - **NaN/Inf score** — always; with ``check_params_every=N > 0`` also
      scans the param pytree for non-finite values every N iterations
      (opt-in: the scan is a device→host fetch of every leaf).
    - **Divergence** — score exceeding ``divergence_factor ×`` the best
      score of the last ``divergence_window`` iterations, once the window
      is full (positive scores only: the relative rule is meaningless for
      losses at or below zero, e.g. ``minimize=False`` objectives).
    - **Stall** — more than ``stall_timeout`` seconds elapsed between this
      ``iteration_done`` and the previous one. (A *fully* wedged loop never
      fires listeners at all — that case is the prober's job via
      ``/healthz``'s ``last_iteration_age_s``.)
    - **Retrace storm** — the jitwatch detector
      (``monitor/jitwatch.py``) flagged a monitored jit function
      recompiling repeatedly within its window (shape/dtype churn). The
      detector itself already recorded the health problem and the
      ``retrace_storm`` flight event (with the argument-signature delta)
      at compile time; this listener drains the pending storms each
      iteration to apply the configured ``action`` — so ``action="halt"``
      stops a fit that would otherwise grind through per-step
      recompilation. Disable with ``watch_retrace=False``.

    ``action``: ``"warn"`` logs and records the problem in
    :func:`get_health`; ``"raise"`` raises :class:`TrainingHealthError`;
    ``"halt"`` requests a graceful stop by setting
    ``model.halt_requested`` (the containers' fit loops break at the next
    minibatch boundary). Every trigger is appended to ``self.triggered``
    as ``(kind, iteration, message)`` regardless of action.
    """

    ACTIONS = ("warn", "raise", "halt")

    def __init__(self, action: str = "warn", divergence_window: int = 10,
                 divergence_factor: float = 2.0,
                 stall_timeout: Optional[float] = None,
                 check_params_every: int = 0, watch_retrace: bool = True):
        if action not in self.ACTIONS:
            raise ValueError(f"action must be one of {self.ACTIONS}, "
                             f"got {action!r}")
        self.action = action
        self.divergence_window = max(2, int(divergence_window))
        self.divergence_factor = float(divergence_factor)
        self.stall_timeout = stall_timeout
        self.check_params_every = int(check_params_every)
        self.watch_retrace = bool(watch_retrace)
        # storms that fired BEFORE this listener existed are history
        # (already on /healthz and in the flight recorder) — acting on
        # them here would punish the current run for an earlier one
        self._armed_at = time.time()
        self.triggered: List[Tuple[str, int, str]] = []
        self._scores = deque(maxlen=self.divergence_window)
        self._last_time: Optional[float] = None

    # ------------------------------------------------------------- checks
    def _fire(self, model, kind: str, iteration: int, message: str,
              record: bool = True):
        self.triggered.append((kind, iteration, message))
        if record:
            # retrace storms arrive pre-recorded by the jitwatch detector
            # (record=False): recording again would double the /healthz
            # problem and the flight event
            get_health().record_problem(kind, message)
        if self.action == "raise":
            raise TrainingHealthError(kind, message)
        if self.action == "halt":
            get_health().record_halt(message)
            try:
                model.halt_requested = True
            except AttributeError:
                pass  # read-only model object: the health latch still set
            log.warning("TrainingHealthListener HALT: %s", message)
        else:
            log.warning("TrainingHealthListener: %s", message)

    def _params_nonfinite(self, model) -> bool:
        import numpy as np
        import jax
        params = getattr(model, "params", None)
        if params is None:
            return False
        for leaf in jax.tree_util.tree_leaves(params):
            if not bool(np.all(np.isfinite(np.asarray(leaf)))):
                return True
        return False

    def iteration_done(self, model, iteration, score):
        if self.watch_retrace:
            from .jitwatch import get_jit_registry
            reg = get_jit_registry()
            storms = reg.drain_storms()
            if storms:
                me = threading.get_ident()
                # storms carry the fit thread they fired on: act only
                # on THIS thread's (= this model's) storms and requeue
                # the rest — halting model B for model A's shape churn
                # would punish the healthy fit and starve the sick one
                foreign = [s for s in storms
                           if s.get("thread") not in (None, me)]
                reg.requeue_storms(foreign)
                for storm in storms:
                    if storm in foreign or storm.get("t", 0) < self._armed_at:
                        continue
                    self._fire(model, "retrace", iteration,
                               storm["message"], record=False)
        now = time.perf_counter()
        if (self.stall_timeout is not None and self._last_time is not None
                and now - self._last_time > self.stall_timeout):
            self._fire(model, "stall", iteration,
                       f"iteration {iteration} arrived "
                       f"{now - self._last_time:.1f}s after the previous one "
                       f"(stall_timeout={self.stall_timeout}s)")
        self._last_time = now

        score = float(score)
        if not math.isfinite(score):
            self._fire(model, "nan", iteration,
                       f"non-finite score {score} at iteration {iteration}")
            return  # divergence math is meaningless on a NaN stream
        if (self.check_params_every > 0
                and iteration % self.check_params_every == 0
                and self._params_nonfinite(model)):
            self._fire(model, "nan", iteration,
                       f"non-finite parameter values at iteration "
                       f"{iteration}")
            return

        if (len(self._scores) == self._scores.maxlen
                and min(self._scores) > 0.0
                and score > self.divergence_factor * min(self._scores)):
            self._fire(model, "divergence", iteration,
                       f"score {score:.6g} at iteration {iteration} exceeds "
                       f"{self.divergence_factor}x the best of the last "
                       f"{self.divergence_window} iterations "
                       f"({min(self._scores):.6g})")
        self._scores.append(score)
