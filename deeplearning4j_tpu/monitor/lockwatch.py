"""lockwatch: runtime lock-order sanitizer + contention observability.

The static half of the concurrency-correctness pass (``analysis/
lockgraph.py`` — tpulint THR003/THR004) proves properties about the code
that *could* run; this module watches the locks that *do* run. Opt in
with ``DL4J_TPU_LOCKWATCH=1`` (or :func:`set_enabled` before the lock
owners are constructed) and every lock created through the
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition` factory
becomes an instrumented wrapper that records, per acquisition:

- **the per-thread held stack** (with the acquiring source site), from
  which the process-global **observed order graph** is maintained: an
  edge ``A -> B`` means some thread acquired ``B`` while holding ``A``.
  The first edge that closes a cycle is a **lock-order inversion** — the
  interleaving that deadlocks under contention — and fires a
  ``lock_order_inversion`` flight-recorder event plus a health problem
  (``/healthz`` flips unhealthy) carrying both witness sites, the same
  two-path shape THR003 reports statically. ``tests/test_lockwatch.py``
  cross-checks the two: every runtime-observed edge must be derivable by
  the static analyzer.
- **hold time**: a lock held longer than ``DL4J_TPU_LOCKWATCH_HOLD_S``
  (default 5s) fires a ``lock_hold_exceeded`` flight event + health
  problem naming the acquisition site — the runtime form of THR001/THR004
  (something slow ran under the lock). ``Condition.wait`` releases the
  lock for the duration of the wait, so parked waiters never count.
- **metrics**: ``lock_acquisitions_total{lock=}``,
  ``lock_wait_seconds{lock=}`` and ``lock_held_seconds{lock=}`` in the
  monitor registry (seconds-valued histograms on the ``unit="s"`` bucket
  geometry, so their quantiles are honest), rolled into the ``locks``
  contention table of ``GET /profile`` (docs/OBSERVABILITY.md
  "Lockwatch").

When disabled (the default), the factory returns plain ``threading``
primitives — zero overhead, byte-identical behavior. Lock *names* are the
same stable ``ClassName.attr`` / ``module.GLOBAL`` identities the static
analyzer derives, which is what makes the cross-check possible.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

log = logging.getLogger(__name__)

__all__ = ["enabled", "set_enabled", "make_lock", "make_rlock",
           "make_condition", "InstrumentedLock", "LockWatch",
           "get_lockwatch", "contention_table", "HOLD_THRESHOLD_S"]

_ENABLED = os.environ.get("DL4J_TPU_LOCKWATCH", "0") not in ("0", "false",
                                                             "")

#: held longer than this (seconds) fires lock_hold_exceeded; generous by
#: default — the point is catching a blocking call under a lock, not a
#: slow scheduler tick on a loaded CI box
HOLD_THRESHOLD_S = float(os.environ.get("DL4J_TPU_LOCKWATCH_HOLD_S", "5.0"))


def enabled() -> bool:
    return _ENABLED


def set_enabled(value: bool):
    """Programmatic opt-in (tests / embedding code). Only affects locks
    created AFTER the call — module-global locks built at import time stay
    plain unless ``DL4J_TPU_LOCKWATCH=1`` was set before the import."""
    global _ENABLED
    _ENABLED = bool(value)


def _acquire_site() -> str:
    """file.py:line of the frame that asked for the lock — skipping this
    module and threading.py (Condition internals re-acquire through us)."""
    f = sys._getframe(1)
    here = os.path.basename(__file__)
    while f is not None:
        base = os.path.basename(f.f_code.co_filename)
        if base not in (here, "threading.py"):
            return f"{base}:{f.f_lineno}"
        f = f.f_back
    return "?"


class _Held:
    """One entry on a thread's held stack."""

    __slots__ = ("name", "obj", "site", "t0", "depth")

    def __init__(self, name: str, obj, site: str, t0: float):
        self.name = name
        self.obj = obj
        self.site = site
        self.t0 = t0
        self.depth = 1


class _LockStats:
    __slots__ = ("n", "wait_total", "wait_max", "held_total", "held_max")

    def __init__(self):
        self.n = 0
        self.wait_total = 0.0
        self.wait_max = 0.0
        self.held_total = 0.0
        self.held_max = 0.0


class LockWatch:
    """Process-global observed-order graph + contention aggregates.

    All bookkeeping runs under ONE plain (uninstrumented) lock and a
    thread-local busy flag suppresses re-entrant instrumentation, so the
    watcher can never deadlock with the locks it watches — an instrumented
    lock acquired while the watcher is firing its own events is simply not
    recorded.
    """

    def __init__(self):
        self._lock = threading.Lock()          # plain by construction
        self._local = threading.local()
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._adj: Dict[str, Set[str]] = {}
        self._stats: Dict[str, _LockStats] = {}
        self._inversions: List[Dict[str, Any]] = []
        self._hold_events: List[Dict[str, Any]] = []
        self._fired_cycles: Set[frozenset] = set()
        self._handles: Dict[str, tuple] = {}

    # ------------------------------------------------------------ plumbing
    def _held(self) -> List[_Held]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def _busy(self) -> bool:
        return getattr(self._local, "busy", False)

    def _metric_handles(self, name: str):
        with self._lock:
            h = self._handles.get(name)
        if h is not None:
            return h
        from .registry import get_registry
        reg = get_registry()
        h = (reg.counter("lock_acquisitions_total",
                         "lock acquisitions by instrumented locks",
                         lock=name),
             reg.histogram("lock_wait_seconds",
                           "blocking wait to acquire an instrumented "
                           "lock (seconds)", unit="s", lock=name),
             reg.histogram("lock_held_seconds",
                           "time an instrumented lock stayed held "
                           "(seconds)", unit="s", lock=name))
        with self._lock:
            self._handles.setdefault(name, h)
        return h

    # ----------------------------------------------------------- recording
    def note_acquire(self, name: str, obj, wait_s: float, site: str,
                     depth: int = 1):
        if self._busy():
            return
        self._local.busy = True
        try:
            held = self._held()
            for h in reversed(held):
                if h.obj is obj:               # reentrant (RLock)
                    h.depth += 1
                    self._record_wait(name, wait_s)
                    return
            entry = _Held(name, obj, site, time.perf_counter())
            entry.depth = max(1, int(depth))
            outer = [h for h in held if h.name != name]
            held.append(entry)
            self._record_wait(name, wait_s)
            if outer:
                self._note_edges(outer, name, site)
        finally:
            self._local.busy = False

    def note_release(self, name: str, obj) -> int:
        """Pop ``obj`` from the held stack (depth-aware); returns the
        remaining reentrancy depth (0 = fully released)."""
        if self._busy():
            return 0
        self._local.busy = True
        try:
            held = self._held()
            for i in range(len(held) - 1, -1, -1):
                h = held[i]
                if h.obj is obj:
                    if h.depth > 1:
                        h.depth -= 1
                        return h.depth
                    del held[i]
                    self._record_held(name, h,
                                      time.perf_counter() - h.t0)
                    return 0
            return 0
        finally:
            self._local.busy = False

    def note_release_all(self, name: str, obj) -> int:
        """Fully release a (possibly reentrant) hold — the
        ``Condition.wait`` seam (``_release_save``). Returns the depth that
        was held, for :meth:`note_acquire` to restore."""
        if self._busy():
            return 1
        self._local.busy = True
        try:
            held = self._held()
            for i in range(len(held) - 1, -1, -1):
                h = held[i]
                if h.obj is obj:
                    del held[i]
                    self._record_held(name, h,
                                      time.perf_counter() - h.t0)
                    return h.depth
            return 1
        finally:
            self._local.busy = False

    def _record_wait(self, name: str, wait_s: float):
        acq_c, wait_h, _ = self._metric_handles(name)
        acq_c.inc()
        wait_h.observe(wait_s)
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _LockStats()
            st.n += 1
            st.wait_total += wait_s
            st.wait_max = max(st.wait_max, wait_s)

    def _record_held(self, name: str, entry: _Held, held_s: float):
        _, _, held_h = self._metric_handles(name)
        held_h.observe(held_s)
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _LockStats()
            st.held_total += held_s
            st.held_max = max(st.held_max, held_s)
        if held_s > HOLD_THRESHOLD_S:
            info = {"t": time.time(), "lock": name, "site": entry.site,
                    "held_s": round(held_s, 3),
                    "threshold_s": HOLD_THRESHOLD_S}
            with self._lock:
                self._hold_events.append(info)
                del self._hold_events[:-64]
            self._fire("lock_hold_exceeded", "lock_hold",
                       f"lock {name!r} (acquired at {entry.site}) held for "
                       f"{held_s:.3f}s > {HOLD_THRESHOLD_S:.1f}s — "
                       f"something slow ran under it (THR001/THR004 at "
                       f"runtime)", info)

    # ---------------------------------------------------------- order graph
    def _note_edges(self, outer: List[_Held], name: str, site: str):
        firings = []
        with self._lock:
            for h in outer:
                key = (h.name, name)
                if key in self._edges:
                    self._edges[key]["count"] += 1
                    continue
                self._edges[key] = {
                    "count": 1,
                    "witness": f"{h.name} at {h.site} -> {name} at {site}",
                }
                self._adj.setdefault(h.name, set()).add(name)
                back = self._find_path(name, h.name)
                if back is None:
                    continue
                cycle = frozenset([h.name, name] + back)
                if cycle in self._fired_cycles:
                    continue
                self._fired_cycles.add(cycle)
                fwd = self._edges[key]["witness"]
                rev = " ; ".join(
                    self._edges[(a, b)]["witness"]
                    for a, b in zip([name] + back, back))
                info = {"t": time.time(), "locks": sorted(cycle),
                        "path_forward": fwd, "path_reverse": rev}
                self._inversions.append(info)
                firings.append((
                    "lock_order_inversion", "lock_order_inversion",
                    f"lock-order inversion between "
                    f"{' and '.join(sorted(cycle))}: one thread took "
                    f"[{fwd}] while the observed graph already holds "
                    f"[{rev}] — under contention these interleavings "
                    f"deadlock; pick one canonical order "
                    f"(docs/STATIC_ANALYSIS.md THR003 runbook)", info))
        for event, kind, msg, info in firings:
            self._fire(event, kind, msg, info)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS in the observed graph: a path src -> ... -> dst (list of
        hops AFTER src, ending in dst), or None. Caller holds _lock."""
        stack = [(src, [])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _fire(self, event: str, kind: str, msg: str, info: Dict[str, Any]):
        """Flight event + health problem (busy flag is already set, so the
        instrumented locks inside flightrec/health are not re-recorded)."""
        log.warning("lockwatch: %s", msg)
        try:
            from .flightrec import get_flight_recorder
            get_flight_recorder().record(event, **{
                k: v for k, v in info.items() if k != "t"})
            from .health import get_health
            get_health().record_problem(kind, msg)
        except Exception as e:
            log.debug("lockwatch: event fan-out failed: %r", e)

    # ------------------------------------------------------------- reading
    def observed_edges(self) -> Set[Tuple[str, str]]:
        """The runtime-observed held->acquired order graph — what
        ``tests/test_lockwatch.py`` cross-checks against the static
        analyzer's edge set."""
        with self._lock:
            return set(self._edges)

    def edge_witnesses(self) -> Dict[Tuple[str, str], str]:
        with self._lock:
            return {k: dict(v)["witness"] for k, v in self._edges.items()}

    def observed_locks(self) -> Set[str]:
        """Every lock name this watch has seen acquired — the runtime
        acquisition census. ``tests/test_lockwatch.py`` pins the dual of
        the edge cross-check against it: every guard the racegraph
        *infers* (THR005) must name a lock the instrumented flows
        actually acquire (inferred ⊆ observed), so guard inference can't
        silently drift off the real locking behavior."""
        with self._lock:
            return set(self._stats)

    def inversions(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(i) for i in self._inversions]

    def hold_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(i) for i in self._hold_events]

    def contention_table(self) -> Dict[str, Dict[str, Any]]:
        """{lock: acquisitions + exact wait/held mean/max} — the ``locks``
        block of ``GET /profile``."""
        with self._lock:
            stats = {n: (s.n, s.wait_total, s.wait_max, s.held_total,
                         s.held_max) for n, s in self._stats.items()}
            inv = len(self._inversions)
            handles_by_name = dict(self._handles)
        out: Dict[str, Dict[str, Any]] = {}
        for name in sorted(stats):
            n, wt, wm, ht, hm = stats[name]
            out[name] = {
                "acquisitions": n,
                "wait_s_mean": round(wt / n, 6) if n else 0.0,
                "wait_s_max": round(wm, 6),
                "held_s_mean": round(ht / n, 6) if n else 0.0,
                "held_s_max": round(hm, 6),
            }
            # honest bucket quantiles from the unit="s" registry
            # histogram (mean/max above stay exact from _LockStats)
            handles = handles_by_name.get(name)
            if handles is not None:
                ws = handles[1].summary()
                if ws:
                    out[name]["wait_s_p95"] = round(ws["p95_s"], 6)
        if out and inv:
            # surfaced at the table level so a renderer can't miss it
            out["_inversions"] = {"count": inv}
        return out

    def clear(self):
        with self._lock:
            self._edges.clear()
            self._adj.clear()
            self._stats.clear()
            self._inversions.clear()
            self._hold_events.clear()
            self._fired_cycles.clear()


_WATCH = LockWatch()


def get_lockwatch() -> LockWatch:
    return _WATCH


def contention_table() -> Dict[str, Dict[str, Any]]:
    return _WATCH.contention_table()


class InstrumentedLock:
    """``threading.Lock``/``RLock`` wrapper feeding :class:`LockWatch`.

    Duck-compatible where this package needs it: ``acquire(blocking,
    timeout)`` / ``release`` / context manager / ``locked``, plus the
    ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` protocol
    ``threading.Condition`` drives — a Condition built over one of these
    (via :func:`make_condition`) releases the tracked hold for the
    duration of every ``wait``.
    """

    def __init__(self, name: str, rlock: bool = False,
                 watch: Optional[LockWatch] = None):
        self.name = str(name)
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._watch = watch if watch is not None else get_lockwatch()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._watch.note_acquire(self.name, self,
                                     time.perf_counter() - t0,
                                     _acquire_site())
        return ok

    def release(self):
        self._watch.note_release(self.name, self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    # ------------------------------------------- Condition.wait protocol
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        depth = self._watch.note_release_all(self.name, self)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save(), depth
        self._inner.release()
        return None, depth

    def _acquire_restore(self, saved):
        state, depth = saved
        t0 = time.perf_counter()
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._watch.note_acquire(self.name, self,
                                 time.perf_counter() - t0,
                                 _acquire_site(), depth=depth)

    def __repr__(self):
        return f"InstrumentedLock({self.name!r})"


# ---------------------------------------------------------------- factory
def make_lock(name: str):
    """A named lock: plain ``threading.Lock`` when lockwatch is off (the
    default — zero overhead), an :class:`InstrumentedLock` when on. The
    name MUST be the stable static identity (``ClassName.attr`` /
    ``module.GLOBAL``) so runtime edges line up with the THR003 analyzer's
    (``analysis/lockgraph.py`` reads these literals)."""
    if not _ENABLED:
        return threading.Lock()
    return InstrumentedLock(name)


def make_rlock(name: str):
    if not _ENABLED:
        return threading.RLock()
    return InstrumentedLock(name, rlock=True)


def make_condition(name: str):
    """A named condition variable. Instrumented mode builds the Condition
    over an :class:`InstrumentedLock` (RLock-backed, preserving the
    default Condition semantics); waits release the tracked hold."""
    if not _ENABLED:
        return threading.Condition()
    return threading.Condition(InstrumentedLock(name, rlock=True))
