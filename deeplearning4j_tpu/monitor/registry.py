"""Process-global metrics registry: labeled counters, gauges, histograms.

The reference stack has no framework-internal metrics at all — deep
profiling is delegated to ND4J's external ``OpProfiler`` and the Play UI's
``StatsListener`` (PAPER.md §5) — so every subsystem here grew its own
ad-hoc holder (``ParamServerMetrics``, ``PerformanceListener``,
``ui/stats``). This module is the single place they all land: one
thread-safe :class:`MetricsRegistry` per process (:func:`get_registry`)
holding metric *families* (name + type + help) with labeled children, plus
Prometheus text-format rendering for the ``GET /metrics`` endpoint on
``ui/server.py``.

The histogram implementation is :class:`LatencyHistogram` — the
log2-bucketed fixed-memory histogram that previously lived in
``paramserver/metrics.py`` (which now re-exports it and backs its
``ParamServerMetrics`` facade with this registry).

Handles are cheap and cached: ``REGISTRY.counter("x_total", peer="0")``
returns the same :class:`Counter` child every time, so hot paths can either
hold the handle or re-look it up per call.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class LatencyHistogram:
    """Log2-bucketed latency histogram (0.1 ms granularity floor): O(1)
    memory regardless of op count, with mean exact and p50/p95 read from the
    bucket upper edges — the shape ``StepTimerListener.summary()`` reports,
    without retaining every sample."""

    #: bucket b covers [0.1·2^b, 0.1·2^(b+1)) ms; 24 buckets reach ~28 min
    N_BUCKETS = 24

    def __init__(self):
        self.counts = [0] * self.N_BUCKETS
        self.total_ms = 0.0
        self.n = 0
        self.max_ms = 0.0

    def record(self, ms: float):
        ms = max(float(ms), 0.0)
        b = 0
        edge = 0.1
        while ms >= edge * 2 and b < self.N_BUCKETS - 1:
            edge *= 2
            b += 1
        self.counts[b] += 1
        self.total_ms += ms
        self.n += 1
        self.max_ms = max(self.max_ms, ms)

    @classmethod
    def bucket_edges(cls) -> List[float]:
        """Upper edge (ms) of every bucket — the Prometheus ``le`` values."""
        return [0.1 * (2 ** (b + 1)) for b in range(cls.N_BUCKETS)]

    def quantile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile sample."""
        if not self.n:
            return 0.0
        rank = q * (self.n - 1)
        seen = 0
        edge = 0.1
        for b, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                return min(edge * 2, self.max_ms) if c else edge * 2
            edge *= 2
        return self.max_ms

    def summary(self) -> Dict[str, float]:
        if not self.n:
            return {}
        return {"mean_ms": self.total_ms / self.n,
                "p50_ms": self.quantile(0.50),
                "p95_ms": self.quantile(0.95),
                # tail latency is the serving tier's SLO currency
                # (docs/SERVING.md); bucket-edge resolution like p50/p95
                "p99_ms": self.quantile(0.99),
                "max_ms": self.max_ms, "n": float(self.n)}


class Counter:
    """Monotonic counter child. ``inc`` only — decreasing is a bug the
    registry refuses to express (use a Gauge)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, value: float = 1.0):
        if value < 0:
            raise ValueError(f"counter increments must be >= 0, got {value}")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable instantaneous value child."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._value += value

    def dec(self, value: float = 1.0):
        self.inc(-value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Thread-safe wrapper over :class:`LatencyHistogram` (ms samples)."""

    __slots__ = ("_lock", "_hist")

    def __init__(self):
        self._lock = threading.Lock()
        self._hist = LatencyHistogram()

    def observe(self, ms: float):
        with self._lock:
            self._hist.record(ms)

    record = observe

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return self._hist.summary()

    def state(self) -> Tuple[List[int], float, int]:
        """(bucket counts, total_ms, n) snapshot for rendering."""
        with self._lock:
            return list(self._hist.counts), self._hist.total_ms, self._hist.n


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One metric name: type, help text, and labeled children."""

    def __init__(self, name: str, mtype: str, help_text: str):
        self.name = name
        self.type = mtype
        self.help = help_text
        self.children: Dict[Tuple[Tuple[str, str], ...], object] = {}


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    # integral values render without a trailing .0 (Prometheus style)
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricsRegistry:
    """Thread-safe registry of metric families with labeled children.

    ``counter``/``gauge``/``histogram`` create-or-return a child; re-using a
    name with a different type raises (one name, one meaning). ``snapshot``
    gives a point-in-time dict for programmatic use; ``render_prometheus``
    the text exposition ``GET /metrics`` serves.
    """

    def __init__(self):
        # PLAIN lock by necessity, never lockwatch-instrumented: the
        # registry is lockwatch's own data plane — recording any lock's
        # first acquisition creates its metric children THROUGH this
        # lock, so instrumenting it here re-enters a non-reentrant lock
        # (observed as a hard deadlock on the first monitored_jit call
        # under DL4J_TPU_LOCKWATCH=1). Its regions are tiny dict ops,
        # THR001-clean by construction.
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _child(self, mtype: str, name: str, help_text: str,
               labels: Dict[str, str]):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, mtype, help_text)
            elif fam.type != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.type}, "
                    f"cannot re-register as {mtype}")
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = _TYPES[mtype]()
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._child("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._child("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "", **labels) -> Histogram:
        return self._child("histogram", name, help, labels)

    # ------------------------------------------------------------ export
    def dump(self) -> Dict[str, dict]:
        """Full JSON-serializable state: every family with type/help and
        every child with its exact value — histograms keep their bucket
        counts (not just the summary), so a dump can be re-rendered as
        Prometheus text elsewhere. This is the wire form workers ship to
        the parameter server over ``OP_TELEMETRY`` for the fleet view
        (``GET /fleet`` re-renders dumps with a ``worker`` label via
        :func:`render_prometheus_dump`)."""
        with self._lock:
            fams = [(f.name, f.type, f.help, list(f.children.items()))
                    for f in self._families.values()]
        out: Dict[str, dict] = {}
        for name, mtype, help_text, children in fams:
            rows = []
            for key, child in children:
                row = {"labels": dict(key)}
                if mtype == "histogram":
                    counts, total_ms, n = child.state()
                    row["buckets"] = counts
                    row["sum"] = total_ms
                    row["count"] = n
                else:
                    row["value"] = child.value
                rows.append(row)
            out[name] = {"type": mtype, "help": help_text, "children": rows}
        return out

    def snapshot(self) -> Dict[str, List[dict]]:
        """{name: [{"labels": {...}, "type": ..., "value"|"summary"}, ...]}"""
        with self._lock:
            fams = {n: (f.type, list(f.children.items()))
                    for n, f in self._families.items()}
        out: Dict[str, List[dict]] = {}
        for name, (mtype, children) in sorted(fams.items()):
            rows = []
            for key, child in children:
                row = {"labels": dict(key), "type": mtype}
                if mtype == "histogram":
                    row["summary"] = child.summary()
                else:
                    row["value"] = child.value
                rows.append(row)
            out[name] = rows
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4. Histograms render with
        their log2 bucket upper edges as ``le`` (in ms, matching the
        ``_ms``-suffixed metric names), plus ``_sum``/``_count``."""
        return render_prometheus_dump(self.dump())

    def clear(self):
        """Drop every family (tests / process reuse)."""
        with self._lock:
            self._families.clear()


def render_prometheus_dump(dump: Dict[str, dict],
                           extra_labels: Optional[Dict[str, str]] = None
                           ) -> str:
    """Render a :meth:`MetricsRegistry.dump` (possibly one that crossed the
    wire as JSON) as Prometheus text exposition 0.0.4. ``extra_labels`` are
    merged into every child — the fleet view re-renders each worker's dump
    with ``{"worker": id}`` so N processes' series coexist in one scrape.
    Local ``render_prometheus`` is this function over the local dump, so
    the two text forms cannot diverge."""
    extra = dict(extra_labels or {})
    lines: List[str] = []
    for name in sorted(dump):
        fam = dump[name]
        mtype, help_text = fam["type"], fam.get("help", "")
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        children = sorted(fam["children"],
                          key=lambda row: _label_key({**row["labels"],
                                                      **extra}))
        for row in children:
            key = _label_key({**row["labels"], **extra})
            labels = _fmt_labels(key)
            if mtype == "histogram":
                counts, total_ms, n = row["buckets"], row["sum"], row["count"]
                cum = 0
                for edge, c in zip(LatencyHistogram.bucket_edges(), counts):
                    cum += c
                    le = _fmt_labels(key, f'le="{edge:g}"')
                    lines.append(f"{name}_bucket{le} {cum}")
                inf = _fmt_labels(key, 'le="+Inf"')
                lines.append(f"{name}_bucket{inf} {n}")
                lines.append(f"{name}_sum{labels} {_fmt_value(total_ms)}")
                lines.append(f"{name}_count{labels} {n}")
            else:
                lines.append(f"{name}{labels} {_fmt_value(row['value'])}")
    return "\n".join(lines) + "\n"


#: the process-global registry every subsystem writes to
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
