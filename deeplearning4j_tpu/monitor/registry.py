"""Process-global metrics registry: labeled counters, gauges, histograms.

The reference stack has no framework-internal metrics at all — deep
profiling is delegated to ND4J's external ``OpProfiler`` and the Play UI's
``StatsListener`` (PAPER.md §5) — so every subsystem here grew its own
ad-hoc holder (``ParamServerMetrics``, ``PerformanceListener``,
``ui/stats``). This module is the single place they all land: one
thread-safe :class:`MetricsRegistry` per process (:func:`get_registry`)
holding metric *families* (name + type + help) with labeled children, plus
Prometheus text-format rendering for the ``GET /metrics`` endpoint on
``ui/server.py``.

The histogram implementation is :class:`LatencyHistogram` — the
log2-bucketed fixed-memory histogram that previously lived in
``paramserver/metrics.py`` (which now re-exports it and backs its
``ParamServerMetrics`` facade with this registry).

Handles are cheap and cached: ``REGISTRY.counter("x_total", peer="0")``
returns the same :class:`Counter` child every time, so hot paths can either
hold the handle or re-look it up per call.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: first bucket's upper-edge base per unit: the same log2 geometry either
#: way, expressed in the series' own unit — ms series resolve from 0.1 ms,
#: seconds series from 1e-4 s (also 0.1 ms), so sub-100ms seconds-valued
#: samples land in real buckets instead of all collapsing into bucket 0
#: (the PR-6 failure the ``unit="s"`` migration closes)
_UNIT_BASE = {"ms": 0.1, "s": 1e-4}

#: how many worst-bucket exemplars a histogram latches (newest-worst win)
MAX_EXEMPLARS = 8

#: exemplars older than this stop counting as "recent" and are evicted at
#: the next latch/read — without a TTL, 8 multi-second cold-start compiles
#: would squat the latch forever and a genuine p99 breach hours later
#: would surface an hours-old trace id the tracer ring evicted long ago
EXEMPLAR_TTL_S = 600.0


class LatencyHistogram:
    """Log2-bucketed latency histogram (0.1 ms granularity floor): O(1)
    memory regardless of op count, with mean exact and p50/p95 read from the
    bucket upper edges — the shape ``StepTimerListener.summary()`` reports,
    without retaining every sample.

    ``unit`` picks the bucket geometry: ``"ms"`` (default — bucket b covers
    ``[0.1·2^b, 0.1·2^(b+1))`` ms) or ``"s"`` (same geometry from 1e-4 s,
    for seconds-valued series like ``jit_compile_seconds``). Summary keys
    carry the unit (``mean_ms``/``p95_ms`` vs ``mean_s``/``p95_s``) so a
    reader can never mistake one for the other.

    ``record(value, exemplar=...)`` optionally latches an **exemplar** (an
    opaque string — in this stack, a trace id) for the worst recent
    samples: the histogram keeps the ``MAX_EXEMPLARS`` largest-valued
    exemplared samples, so a firing latency alert can surface a concrete
    trace id resolvable against ``GET /trace`` (monitor/alerts.py)."""

    #: 24 log2 buckets reach ~28 min from a 0.1 ms floor
    N_BUCKETS = 24

    def __init__(self, unit: str = "ms",
                 exemplar_ttl_s: float = EXEMPLAR_TTL_S):
        if unit not in _UNIT_BASE:
            raise ValueError(f"unit must be one of {sorted(_UNIT_BASE)}, "
                             f"got {unit!r}")
        self.unit = unit
        self._base = _UNIT_BASE[unit]
        self.counts = [0] * self.N_BUCKETS
        self.total_ms = 0.0      # in self.unit (name predates unit="s")
        self.n = 0
        self.max_ms = 0.0        # in self.unit
        self.exemplar_ttl_s = float(exemplar_ttl_s)
        self.exemplars: deque = deque(maxlen=MAX_EXEMPLARS)

    def _bucket(self, value: float) -> int:
        b = 0
        edge = self._base
        while value >= edge * 2 and b < self.N_BUCKETS - 1:
            edge *= 2
            b += 1
        return b

    def record(self, ms: float, exemplar: Optional[str] = None):
        ms = max(float(ms), 0.0)
        self.counts[self._bucket(ms)] += 1
        self.total_ms += ms
        self.n += 1
        self.max_ms = max(self.max_ms, ms)
        if exemplar is not None:
            self._latch_exemplar(ms, exemplar)

    def _expire_exemplars(self, now: float):
        alive = [e for e in self.exemplars
                 if now - e["t"] <= self.exemplar_ttl_s]
        if len(alive) != len(self.exemplars):
            self.exemplars.clear()
            self.exemplars.extend(alive)

    def _latch_exemplar(self, value: float, exemplar: str):
        """Keep the largest-valued RECENT exemplared samples: expired
        entries (older than ``exemplar_ttl_s``) are evicted first, then
        append while there is room, else displace the smallest kept value
        when this one beats it (ties keep the newer sample — recency
        matters for alert forensics)."""
        now = time.monotonic()
        self._expire_exemplars(now)
        entry = {"value": value, "exemplar": str(exemplar), "t": now}
        if len(self.exemplars) < self.exemplars.maxlen:
            self.exemplars.append(entry)
            return
        worst_i, worst_v = 0, None
        for i, e in enumerate(self.exemplars):
            if worst_v is None or e["value"] < worst_v:
                worst_i, worst_v = i, e["value"]
        if value >= worst_v:
            del self.exemplars[worst_i]
            self.exemplars.append(entry)

    def worst_exemplar(self) -> Optional[Dict[str, object]]:
        """The exemplar of the largest RECENT latched sample (None when no
        unexpired sample carried one) — what a firing latency alert
        surfaces. Expiry applies at read time too, so a long-idle
        histogram never hands an alert a trace id the tracer ring evicted
        long ago."""
        self._expire_exemplars(time.monotonic())
        worst = None
        for e in self.exemplars:
            if worst is None or e["value"] > worst["value"]:
                worst = e
        return dict(worst) if worst else None

    @classmethod
    def bucket_edges(cls, unit: str = "ms") -> List[float]:
        """Upper edge of every bucket in the given unit — the Prometheus
        ``le`` values (ms for ms-series, seconds for ``unit="s"``)."""
        base = _UNIT_BASE[unit]
        return [base * (2 ** (b + 1)) for b in range(cls.N_BUCKETS)]

    def quantile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile sample."""
        if not self.n:
            return 0.0
        rank = q * (self.n - 1)
        seen = 0
        edge = self._base
        for b, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                return min(edge * 2, self.max_ms) if c else edge * 2
            edge *= 2
        return self.max_ms

    def summary(self) -> Dict[str, float]:
        if not self.n:
            return {}
        u = self.unit
        return {f"mean_{u}": self.total_ms / self.n,
                f"p50_{u}": self.quantile(0.50),
                f"p95_{u}": self.quantile(0.95),
                # tail latency is the serving tier's SLO currency
                # (docs/SERVING.md); bucket-edge resolution like p50/p95
                f"p99_{u}": self.quantile(0.99),
                f"max_{u}": self.max_ms, "n": float(self.n)}


class Counter:
    """Monotonic counter child. ``inc`` only — decreasing is a bug the
    registry refuses to express (use a Gauge)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, value: float = 1.0):
        if value < 0:
            raise ValueError(f"counter increments must be >= 0, got {value}")
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable instantaneous value child."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._value += value

    def dec(self, value: float = 1.0):
        self.inc(-value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Thread-safe wrapper over :class:`LatencyHistogram` (samples in the
    family's unit — ms by default, seconds for ``unit="s"`` families)."""

    __slots__ = ("_lock", "_hist")

    def __init__(self, unit: str = "ms"):
        self._lock = threading.Lock()
        self._hist = LatencyHistogram(unit=unit)

    def observe(self, ms: float, exemplar: Optional[str] = None):
        with self._lock:
            self._hist.record(ms, exemplar=exemplar)

    record = observe

    @property
    def unit(self) -> str:
        return self._hist.unit

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return self._hist.summary()

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._hist.quantile(q)

    def worst_exemplar(self) -> Optional[Dict[str, object]]:
        with self._lock:
            return self._hist.worst_exemplar()

    def retarget_unit(self, unit: str) -> bool:
        """Swap in a fresh histogram on the new unit geometry — only
        while EMPTY (the registry's claim-the-unit seam for families a
        read-path lookup created first). Cached handles stay valid: the
        wrapper is the handle, only its inner histogram is replaced.
        Returns False when samples were already recorded."""
        with self._lock:
            if self._hist.n:
                return self._hist.unit == unit
            if self._hist.unit != unit:
                self._hist = LatencyHistogram(unit=unit)
            return True

    def state(self) -> Tuple[List[int], float, int]:
        """(bucket counts, value sum, n) snapshot for rendering — the sum
        is in the family's unit."""
        with self._lock:
            return list(self._hist.counts), self._hist.total_ms, self._hist.n


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One metric name: type, help text, unit (histograms), and labeled
    children."""

    def __init__(self, name: str, mtype: str, help_text: str,
                 unit: Optional[str] = None):
        self.name = name
        self.type = mtype
        self.help = help_text
        #: bucket geometry (histogram families only). None = no creator
        #: has claimed a unit yet (a read-path lookup created the family)
        #: — renders as ms, and the FIRST explicit unit= claims it.
        self.unit = unit
        self.children: Dict[Tuple[Tuple[str, str], ...], object] = {}


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    # integral values render without a trailing .0 (Prometheus style)
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricsRegistry:
    """Thread-safe registry of metric families with labeled children.

    ``counter``/``gauge``/``histogram`` create-or-return a child; re-using a
    name with a different type raises (one name, one meaning). ``snapshot``
    gives a point-in-time dict for programmatic use; ``render_prometheus``
    the text exposition ``GET /metrics`` serves.
    """

    def __init__(self):
        # PLAIN lock by necessity, never lockwatch-instrumented: the
        # registry is lockwatch's own data plane — recording any lock's
        # first acquisition creates its metric children THROUGH this
        # lock, so instrumenting it here re-enters a non-reentrant lock
        # (observed as a hard deadlock on the first monitored_jit call
        # under DL4J_TPU_LOCKWATCH=1). Its regions are tiny dict ops,
        # THR001-clean by construction.
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _child(self, mtype: str, name: str, help_text: str,
               labels: Dict[str, str], unit: Optional[str] = None):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, mtype, help_text,
                                                     unit=unit)
            elif fam.type != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.type}, "
                    f"cannot re-register as {mtype}")
            elif unit is not None and fam.unit is None:
                # a read-path lookup created the family before its
                # creator ran (tests peeking at state(), /profile
                # readers): the FIRST explicit unit claims it, re-gearing
                # any reader-created children — which must still be empty
                # (samples recorded under the wrong geometry cannot be
                # migrated, so that is a real error at the recorder)
                for child in fam.children.values():
                    if not child.retarget_unit(unit):
                        raise ValueError(
                            f"histogram {name!r} recorded samples before "
                            f"any creator claimed unit={unit!r} — create "
                            f"it with the unit before recording")
                fam.unit = unit
            elif unit is not None and fam.unit != unit:
                # one name, one bucket geometry: mixing units under one
                # family would render le= edges that lie for half the
                # children (unit=None means "whatever the family uses")
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"unit={fam.unit!r}, cannot re-register as {unit!r}")
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = (
                    Histogram(unit=fam.unit or "ms")
                    if mtype == "histogram" else _TYPES[mtype]())
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._child("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._child("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  unit: Optional[str] = None, **labels) -> Histogram:
        """``unit`` picks the bucket geometry: ``"ms"`` (default) or
        ``"s"`` for seconds-valued series (``*_seconds`` names — tpulint
        MON001 enforces the pairing), whose quantiles would otherwise
        saturate below 100 ms on ms geometry."""
        return self._child("histogram", name, help, labels, unit=unit)

    # ------------------------------------------------------------ export
    def dump(self) -> Dict[str, dict]:
        """Full JSON-serializable state: every family with type/help and
        every child with its exact value — histograms keep their bucket
        counts (not just the summary), so a dump can be re-rendered as
        Prometheus text elsewhere. This is the wire form workers ship to
        the parameter server over ``OP_TELEMETRY`` for the fleet view
        (``GET /fleet`` re-renders dumps with a ``worker`` label via
        :func:`render_prometheus_dump`)."""
        with self._lock:
            fams = [(f.name, f.type, f.help, f.unit,
                     list(f.children.items()))
                    for f in self._families.values()]
        out: Dict[str, dict] = {}
        for name, mtype, help_text, unit, children in fams:
            rows = []
            for key, child in children:
                row = {"labels": dict(key)}
                if mtype == "histogram":
                    counts, total_ms, n = child.state()
                    row["buckets"] = counts
                    row["sum"] = total_ms
                    row["count"] = n
                else:
                    row["value"] = child.value
                rows.append(row)
            fam_out = {"type": mtype, "help": help_text, "children": rows}
            if mtype == "histogram":
                fam_out["unit"] = unit or "ms"   # le= edges depend on it;
                                                 # old wire dumps without
                                                 # it are ms
            out[name] = fam_out
        return out

    def snapshot(self) -> Dict[str, List[dict]]:
        """{name: [{"labels": {...}, "type": ..., "value"|"summary"}, ...]}"""
        with self._lock:
            fams = {n: (f.type, list(f.children.items()))
                    for n, f in self._families.items()}
        out: Dict[str, List[dict]] = {}
        for name, (mtype, children) in sorted(fams.items()):
            rows = []
            for key, child in children:
                row = {"labels": dict(key), "type": mtype}
                if mtype == "histogram":
                    row["summary"] = child.summary()
                else:
                    row["value"] = child.value
                rows.append(row)
            out[name] = rows
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4. Histograms render
        with their log2 bucket upper edges as ``le`` in the family's own
        unit — ms for ``_ms``-suffixed series, seconds for ``unit="s"``
        families (``*_seconds`` names) — plus ``_sum``/``_count``."""
        return render_prometheus_dump(self.dump())

    def clear(self):
        """Drop every family (tests / process reuse)."""
        with self._lock:
            self._families.clear()


def render_prometheus_dump(dump: Dict[str, dict],
                           extra_labels: Optional[Dict[str, str]] = None
                           ) -> str:
    """Render a :meth:`MetricsRegistry.dump` (possibly one that crossed the
    wire as JSON) as Prometheus text exposition 0.0.4. ``extra_labels`` are
    merged into every child — the fleet view re-renders each worker's dump
    with ``{"worker": id}`` so N processes' series coexist in one scrape.
    Local ``render_prometheus`` is this function over the local dump, so
    the two text forms cannot diverge."""
    extra = dict(extra_labels or {})
    lines: List[str] = []
    for name in sorted(dump):
        fam = dump[name]
        mtype, help_text = fam["type"], fam.get("help", "")
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        edges = LatencyHistogram.bucket_edges(fam.get("unit") or "ms")
        children = sorted(fam["children"],
                          key=lambda row: _label_key({**row["labels"],
                                                      **extra}))
        for row in children:
            key = _label_key({**row["labels"], **extra})
            labels = _fmt_labels(key)
            if mtype == "histogram":
                counts, total_ms, n = row["buckets"], row["sum"], row["count"]
                cum = 0
                for edge, c in zip(edges, counts):
                    cum += c
                    le = _fmt_labels(key, f'le="{edge:g}"')
                    lines.append(f"{name}_bucket{le} {cum}")
                inf = _fmt_labels(key, 'le="+Inf"')
                lines.append(f"{name}_bucket{inf} {n}")
                lines.append(f"{name}_sum{labels} {_fmt_value(total_ms)}")
                lines.append(f"{name}_count{labels} {n}")
            else:
                lines.append(f"{name}{labels} {_fmt_value(row['value'])}")
    return "\n".join(lines) + "\n"


#: the process-global registry every subsystem writes to
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
