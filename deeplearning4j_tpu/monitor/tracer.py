"""Host-side span tracer: ring buffer → Chrome trace-event JSON.

The reference delegates all tracing to external tools; ``utils/profiling``
wraps ``jax.profiler`` for *device* traces. This tracer is the cheap
*host*-side complement: a context-manager/decorator that records wall-clock
spans into a bounded ring buffer and exports them as Chrome trace-event
JSON (``GET /trace`` on the UI server, or :meth:`Tracer.export`) — open the
dump in Perfetto / ``chrome://tracing``. When jax is importable, every span
also nests a ``jax.profiler.TraceAnnotation`` so host spans line up with
device traces captured through ``utils.profiling.trace``.

Timing honesty (the value-fetch barrier rule, ``utils/profiling.py`` /
PERF.md addendum 2): jitted dispatch is asynchronous and
``block_until_ready`` can return early on tunneled backends, so a span
around a bare dispatch measures *dispatch*, not the step. Only a
device→host VALUE fetch (``float(loss)`` / ``np.asarray``) is a reliable
completion barrier. The fit-loop instrumentation keeps its ``float(loss)``
fetch INSIDE the step span for exactly this reason; spans you place around
your own jitted calls must do their own value fetch to mean anything.
"""
from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Tracer", "get_tracer"]


def _trace_annotation():
    """jax.profiler.TraceAnnotation class, or None when jax is absent.
    Resolved lazily so a metrics-only import never pays for jax."""
    global _ANNOTATION
    if _ANNOTATION is _UNRESOLVED:
        try:
            from jax.profiler import TraceAnnotation
            _ANNOTATION = TraceAnnotation
        # deliberately broad + silent: ANY import failure (absent jax,
        # broken profiler build) means "no device annotations", and trace
        # emission must never raise into the training loop
        except Exception:  # tpulint: disable=EXC001
            _ANNOTATION = None
    return _ANNOTATION


_UNRESOLVED = object()
_ANNOTATION = _UNRESOLVED


class Tracer:
    """Bounded ring buffer of completed host spans.

    ``capacity`` bounds memory: the newest ``capacity`` spans win (a
    steady-state training loop keeps the recent window, which is what a
    ``GET /trace`` snapshot wants). Spans on different threads interleave
    naturally — the export carries ``tid`` so Perfetto lays them out per
    thread, and nesting within a thread is reconstructed from ts/dur
    containment.
    """

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._events = deque(maxlen=int(capacity))
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Record one span around the enclosed block. ``args`` become the
        trace event's ``args`` (must be JSON-serializable scalars)."""
        ann_cls = _trace_annotation()
        ann = ann_cls(name) if ann_cls is not None else None
        if ann is not None:
            ann.__enter__()
        start = time.perf_counter()
        try:
            yield self
        finally:
            dur = time.perf_counter() - start
            if ann is not None:
                ann.__exit__(None, None, None)
            ev = {"name": name, "cat": cat, "ph": "X",
                  "ts": (start - self._t0) * 1e6, "dur": dur * 1e6,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            if args:
                ev["args"] = args
            with self._lock:
                self._events.append(ev)

    def trace(self, name: Optional[str] = None, cat: str = "host"):
        """Decorator form: ``@tracer.trace()`` spans every call."""
        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapped(*a, **kw):
                with self.span(span_name, cat=cat):
                    return fn(*a, **kw)
            return wrapped
        return deco

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def export(self) -> Dict:
        """Chrome trace-event JSON object (the ``/trace`` payload): load it
        in Perfetto or ``chrome://tracing`` as-is."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)


#: the process-global tracer the fit loops / transport / PS client write to
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER
