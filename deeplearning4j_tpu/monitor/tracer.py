"""Host-side span tracer: ring buffer → Chrome trace-event JSON.

The reference delegates all tracing to external tools; ``utils/profiling``
wraps ``jax.profiler`` for *device* traces. This tracer is the cheap
*host*-side complement: a context-manager/decorator that records wall-clock
spans into a bounded ring buffer and exports them as Chrome trace-event
JSON (``GET /trace`` on the UI server, or :meth:`Tracer.export`) — open the
dump in Perfetto / ``chrome://tracing``. When jax is importable, every span
also nests a ``jax.profiler.TraceAnnotation`` so host spans line up with
device traces captured through ``utils.profiling.trace``.

Timing honesty (the value-fetch barrier rule, ``utils/profiling.py`` /
PERF.md addendum 2): jitted dispatch is asynchronous and
``block_until_ready`` can return early on tunneled backends, so a span
around a bare dispatch measures *dispatch*, not the step. Only a
device→host VALUE fetch (``float(loss)`` / ``np.asarray``) is a reliable
completion barrier. The fit-loop instrumentation keeps its ``float(loss)``
fetch INSIDE the step span for exactly this reason; spans you place around
your own jitted calls must do their own value fetch to mean anything.

Trace-context propagation: every span carries a ``trace_id`` shared with
its whole causal chain and a fresh ``span_id``; :meth:`Tracer.current_span`
exposes the active :class:`SpanContext` so an RPC layer can ship it to the
peer (the paramserver client prefixes flagged ops with it), and
``span(parent=ctx)`` lets the receiving side record a child span under the
REMOTE parent — a merged export then shows client push → server apply as
one chain across processes (docs/OBSERVABILITY.md "Fleet observability").
"""
from __future__ import annotations

import contextlib
import functools
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

__all__ = ["SpanContext", "Tracer", "get_tracer", "new_context"]


class SpanContext(NamedTuple):
    """Identity of one span in one trace. IDs are 63-bit ints (JSON-safe,
    16 hex chars on the wire); ``parent_span_id`` is 0 for a root span."""

    trace_id: int
    span_id: int
    parent_span_id: int = 0


def _new_id() -> int:
    # 63 bits: fits JSON/JS number precision limits and struct "<Q"
    return random.getrandbits(63) | 1       # never 0 (0 = "no parent")


def new_context() -> SpanContext:
    """A fresh root :class:`SpanContext` — for subsystems that mint a
    trace identity per unit of work without opening a thread-bound span
    (the serving batcher stamps one per request at submit time so the
    queue-wait and flush spans recorded later can join it)."""
    return SpanContext(_new_id(), _new_id(), 0)


def _trace_annotation():
    """jax.profiler.TraceAnnotation class, or None when jax is absent.
    Resolved lazily so a metrics-only import never pays for jax."""
    global _ANNOTATION
    if _ANNOTATION is _UNRESOLVED:
        try:
            from jax.profiler import TraceAnnotation
            _ANNOTATION = TraceAnnotation
        # deliberately broad + silent: ANY import failure (absent jax,
        # broken profiler build) means "no device annotations", and trace
        # emission must never raise into the training loop
        except Exception:  # tpulint: disable=EXC001
            _ANNOTATION = None
    return _ANNOTATION


_UNRESOLVED = object()
_ANNOTATION = _UNRESOLVED


class Tracer:
    """Bounded ring buffer of completed host spans.

    ``capacity`` bounds memory: the newest ``capacity`` spans win (a
    steady-state training loop keeps the recent window, which is what a
    ``GET /trace`` snapshot wants). Spans on different threads interleave
    naturally — the export carries ``tid`` so Perfetto lays them out per
    thread, and nesting within a thread is reconstructed from ts/dur
    containment.
    """

    def __init__(self, capacity: int = 8192):
        from .lockwatch import make_lock
        self._lock = make_lock("Tracer._lock")
        self._events = deque(maxlen=int(capacity))
        self._t0 = time.perf_counter()
        self._local = threading.local()     # per-thread span-context stack
        self.dropped = 0                    # ring-buffer overflow count

    # ----------------------------------------------------- span contexts
    def _stack(self) -> List[SpanContext]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span(self) -> Optional[SpanContext]:
        """The innermost open span's context on THIS thread, or None. This
        is what an RPC client ships to the server so the server's handling
        span becomes a child of the in-flight client span."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host",
             parent: Optional[SpanContext] = None, **args):
        """Record one span around the enclosed block; yields the span's
        :class:`SpanContext`. ``args`` become the trace event's ``args``
        (must be JSON-serializable scalars). The trace/parent IDs come from
        the innermost open span on this thread, or from ``parent`` — pass a
        context that arrived over the wire to join a REMOTE trace."""
        ann_cls = _trace_annotation()
        ann = ann_cls(name) if ann_cls is not None else None
        if ann is not None:
            ann.__enter__()
        stack = self._stack()
        up = parent if parent is not None else (stack[-1] if stack else None)
        ctx = SpanContext(up.trace_id if up else _new_id(), _new_id(),
                          up.span_id if up else 0)
        stack.append(ctx)
        start = time.perf_counter()
        try:
            yield ctx
        finally:
            dur = time.perf_counter() - start
            stack.pop()
            if ann is not None:
                ann.__exit__(None, None, None)
            ev = {"name": name, "cat": cat, "ph": "X",
                  "ts": (start - self._t0) * 1e6, "dur": dur * 1e6,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            ev["args"] = {"trace_id": f"{ctx.trace_id:x}",
                          "span_id": f"{ctx.span_id:x}", **args}
            if ctx.parent_span_id:
                ev["args"]["parent_span_id"] = f"{ctx.parent_span_id:x}"
            self._append(ev)

    def record_complete(self, name: str, start: float, dur: float,
                        cat: str = "host",
                        parent: Optional[SpanContext] = None, **args):
        """Record an ALREADY-timed span after the fact — for events only
        detectable at their end (e.g. a jit compile, recognized by the
        cache-size delta once the call returns). ``start`` is the
        ``perf_counter`` value at the event's start, ``dur`` seconds. The
        span is parented under ``parent`` when given (the serving batcher
        parents a request's queue-wait span under the REQUEST's context,
        not the scheduler thread's), else under the innermost OPEN span on
        this thread (a compile detected mid-step nests under the step
        span); either way it does not touch the context stack itself."""
        up = parent if parent is not None else self.current_span()
        ctx = SpanContext(up.trace_id if up else _new_id(), _new_id(),
                          up.span_id if up else 0)
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": (start - self._t0) * 1e6, "dur": dur * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident(),
              "args": {"trace_id": f"{ctx.trace_id:x}",
                       "span_id": f"{ctx.span_id:x}", **args}}
        if ctx.parent_span_id:
            ev["args"]["parent_span_id"] = f"{ctx.parent_span_id:x}"
        self._append(ev)

    def _append(self, ev: Dict):
        with self._lock:
            overflow = len(self._events) == self._events.maxlen
            if overflow:
                self.dropped += 1
            self._events.append(ev)
        if overflow:
            # registry write OUTSIDE the ring lock (scrapes take both)
            from .registry import get_registry
            get_registry().counter(
                "tracer_spans_dropped_total",
                "spans evicted from the trace ring buffer").inc()

    def trace(self, name: Optional[str] = None, cat: str = "host"):
        """Decorator form: ``@tracer.trace()`` spans every call."""
        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapped(*a, **kw):
                with self.span(span_name, cat=cat):
                    return fn(*a, **kw)
            return wrapped
        return deco

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def export(self) -> Dict:
        """Chrome trace-event JSON object (the ``/trace`` payload): load it
        in Perfetto or ``chrome://tracing`` as-is."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)


#: the process-global tracer the fit loops / transport / PS client write to
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER
