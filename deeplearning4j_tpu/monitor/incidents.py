"""Incident plane: automatic blackbox capture at the alert fire edge.

Every drill since the control plane landed ends with "the incident
reconstructs from ``/events`` in seq order" — but only while the process
is alive, only before the bounded rings evict the evidence (the 8192-slot
trace ring, the metrics-history ring, the 8-slot exemplar latch with its
600 s TTL), and only by a human stitching ``/alerts`` + ``/trace`` +
``/history`` + ``/events`` + ``/control`` + ``/probes`` together by
hand. Once the control plane acts on its own signals, the *why* must be
captured automatically and survive the process — or the operator is
debugging a self-driving fleet from amnesiac rings.

:class:`IncidentRecorder` subscribes to the :class:`~deeplearning4j_tpu.
monitor.alerts.AlertEngine` edge stream and, at the *fire* edge — before
any ring evicts — snapshots the full diagnostic state into a bounded
in-memory :class:`Incident`:

- the metrics-history window spanning ``[first PENDING − lookback,
  fire]`` (the rule's own hold-down plus runway, so the breach's onset
  is in the bundle, not just its crossing);
- the exemplar trace's complete span tree, **pinned by copy** from the
  tracer ring — ring wraparound and ``EXEMPLAR_TTL_S`` eviction can
  never hollow out an open incident's bundle;
- flight-recorder events back to the window start, and (at close) every
  event recorded while the incident was open — including each
  ``control_action`` the control plane took under it;
- the firing rule's full alert state, plus every co-firing rule:
  overlapping firing windows **merge** into ONE incident (the chaos
  drill's p99 + burn + shard-down edges are one incident, not three);
- the jit table, the lock census, and the probe/collector snapshots
  when those planes are wired (``sys.modules``-gated — an unused plane
  costs nothing and is never constructed as a side effect).

On resolve (every member rule resolved) the incident closes and — when
``DL4J_TPU_INCIDENT_DIR`` (or ``dump_dir=``) opts in, the
:meth:`FlightRecorder.dump` convention — persists as a content-addressed
JSON bundle ``<id>-<digest16>.dl4jinc`` that reconstructs the whole
incident offline (``incident show`` renders the merged seq-ordered
timeline). A ``record_halt`` crash dump flushes open incidents the same
way with ``status="aborted"``: a process dying mid-incident leaves
evidence on disk rather than nothing.

Threading follows the house shape the lockwatch suite pins: the
subscription callback only appends to a lock-free deque (it runs on the
evaluation thread under ``AlertEngine._eval_lock`` — capture work there
would graft the tracer/history/registry lock trees onto the evaluation
lock); the recorder's ``tick(now=)`` — deterministic test seam, driven
by the ``start(interval_s)``/``stop()`` daemon — drains the deque,
captures with **no lock held** (every source takes its own), and only
the incident-table bookkeeping enters ``IncidentRecorder._lock``, a
leaf with no outgoing edge. Nothing is installed by default: a bare
process has zero recorders and zero threads (tier-1 seed behavior is
untouched until a caller opts in).

Series: ``incidents_open`` gauge, ``incident_captures_total{outcome}``
counter (``captured`` opened a new incident, ``merged`` joined the open
one, ``error`` capture failed), ``incident_capture_ms`` histogram.
Surfaces: ``GET /incidents`` + ``GET /incidents/<id>`` on both server
families, ``monitor --incidents``, ``incident show <path>``. See
docs/OBSERVABILITY.md "Incident plane".
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .lockwatch import make_lock

log = logging.getLogger(__name__)

__all__ = ["Incident", "IncidentRecorder", "get_incident_recorder",
           "abort_open_incidents", "load_bundle", "render_incident_text"]

#: default daemon cadence; tests bypass it entirely via tick()
DEFAULT_INTERVAL_S = 0.5

#: history runway captured BEFORE the first rule's PENDING edge — the
#: onset of the breach, not just its threshold crossing
DEFAULT_LOOKBACK_S = 120.0

#: bounded incident table (oldest CLOSED incidents evicted first)
DEFAULT_MAX_INCIDENTS = 32

#: bundle format tag (bumped on incompatible schema changes)
BUNDLE_FORMAT = "dl4jinc/1"

#: flight-event kinds the ``incident show`` timeline renders (the rest
#: are counted, not printed — a 4096-event window would drown the story)
_TIMELINE_EVENTS = ("alert_firing", "alert_resolved", "control_action",
                    "probe_target_failing", "probe_target_recovered",
                    "incident_open", "incident_closed", "halt",
                    "shard_server_down", "health_problem")


def _open_gauge():
    from .registry import get_registry
    return get_registry().gauge(
        "incidents_open",
        "incidents currently open on the incident recorder (co-firing "
        "rules merge, so this is almost always 0 or 1)")


def _capture_counter(outcome: str):
    from .registry import get_registry
    return get_registry().counter(
        "incident_captures_total",
        "fire-edge evidence captures by outcome (captured = opened a "
        "new incident, merged = joined the open one)", outcome=outcome)


def _capture_hist():
    from .registry import get_registry
    return get_registry().histogram(
        "incident_capture_ms",
        "wall time of one fire-edge evidence capture (history window + "
        "exemplar pin + context blocks), off the serving path")


class Incident:
    """One merged incident: every co-firing rule's evidence under one id.

    Mutated ONLY under the owning recorder's ``_lock`` (the capture
    payloads attached here are built lock-free beforehand); ``bundle``
    is set once at close and immutable afterwards."""

    def __init__(self, incident_id: str, opened_t: float):
        self.id = incident_id
        self.status = "open"              # open | resolved | aborted
        self.opened_t = opened_t
        self.closed_t: Optional[float] = None
        #: rule name → {fired_t, resolved_t, alert, exemplar_trace_id,
        #: exemplar_spans, resolve_detail}
        self.rules: Dict[str, Dict[str, Any]] = {}
        self.window_start: Optional[float] = None
        self.history: List[Tuple[float, dict]] = []
        self.flight_events: List[Dict[str, Any]] = []
        self.open_last_seq = 0            # tail events appended at close
        self.context: Dict[str, Any] = {} # jit table, lock census, ...
        self.captures: List[Dict[str, Any]] = []
        self.bundle: Optional[Dict[str, Any]] = None
        self.path: Optional[str] = None
        self.bundle_bytes: Optional[int] = None

    def row(self) -> Dict[str, Any]:
        """One ``GET /incidents`` summary row."""
        return {"id": self.id, "status": self.status,
                "opened_t": self.opened_t, "closed_t": self.closed_t,
                "rules": sorted(self.rules),
                "captures": len(self.captures),
                "history_samples": len(self.history),
                "flight_events": len(self.flight_events),
                "path": self.path, "bundle_bytes": self.bundle_bytes}


class IncidentRecorder:
    """Subscribes to alert edges, captures at fire, persists at resolve.

    One recorder per process (:func:`get_incident_recorder`); nothing is
    constructed or started implicitly. ``start()`` subscribes to the
    engine's edge stream and runs the tick daemon; ``tick(now=)`` is the
    deterministic seam tests drive instead of sleeping."""

    def __init__(self, engine=None, history=None, *,
                 max_incidents: int = DEFAULT_MAX_INCIDENTS,
                 lookback_s: float = DEFAULT_LOOKBACK_S,
                 dump_dir: Optional[str] = None):
        self._lock = make_lock("IncidentRecorder._lock")
        self._engine = engine
        self._history = history
        self.max_incidents = int(max_incidents)
        self.lookback_s = float(lookback_s)
        self.dump_dir = dump_dir
        # lock-free handoff from the alert-engine fan-out thread: the
        # subscription callback must not take ANY lock (it runs under
        # AlertEngine._eval_lock — a capture there would graft the
        # tracer/history/registry lock trees onto the evaluation lock)
        self._edges: deque = deque(maxlen=1024)
        self._incidents: Dict[str, Incident] = {}   # insertion = age order
        self._open_id: Optional[str] = None
        self._seq = 0
        self.evicted = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.interval_s = DEFAULT_INTERVAL_S
        self.last_tick: Optional[float] = None

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        from .alerts import get_alert_engine
        return get_alert_engine()

    @property
    def history(self):
        if self._history is not None:
            return self._history
        return self.engine.history

    # ----------------------------------------------------------- lifecycle
    def _on_edge(self, event: str, payload: Dict[str, Any]):
        """AlertEngine subscription callback — enqueue only, never
        capture: this runs on the evaluation thread under ``_eval_lock``."""
        self._edges.append((event, payload))

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def start(self, interval_s: Optional[float] = None
              ) -> "IncidentRecorder":
        """Subscribe + start the tick daemon (idempotent)."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="incident-recorder", daemon=True)
            thread = self._thread
        # outside our lock: the engine takes its own
        self.engine.subscribe(self._on_edge)
        thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        """Unsubscribe and join the tick thread. Queued-but-unprocessed
        edges survive in the deque — a later start() resumes them."""
        self.engine.unsubscribe(self._on_edge)
        with self._lock:
            thread, self._thread = self._thread, None
            if thread is not None:
                # inside the lock for the same reason MetricsHistory.stop
                # sets inside: a concurrent start() serializes behind us
                self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)

    def _loop(self):
        self.tick()
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("incident-recorder tick failed")

    def clear(self):
        """Full reset: incidents, queued edges. The open gauge zeroes —
        a cleared recorder must surface as empty, not replay history."""
        with self._lock:
            self._incidents = {}
            self._open_id = None
            self._edges.clear()
            self.evicted = 0
        _open_gauge().set(0.0)

    # ---------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> int:
        """One pass: drain queued alert edges, capture fires, close on
        the final resolve. Returns the number of edges that changed
        incident state this pass."""
        now = float(now) if now is not None else time.time()
        drained: List[Tuple[str, Dict[str, Any]]] = []
        while True:
            try:
                drained.append(self._edges.popleft())
            except IndexError:
                break
        changed = 0
        for event, payload in drained:
            if event == "alert_firing":
                self._capture_fire(payload, now)
                changed += 1
            elif event == "alert_resolved":
                if self._resolve(payload, now):
                    changed += 1
        with self._lock:
            self.last_tick = now
        return changed

    # ----------------------------------------------------- capture (fire)
    def _capture_fire(self, payload: Dict[str, Any], now: float):
        """Snapshot the diagnostic state for one firing edge — all the
        expensive reads run with NO lock held (each source takes its
        own; ours stays a leaf), then the bookkeeping enters the lock."""
        rule = payload.get("rule")
        t0 = time.perf_counter()
        outcome = "captured"
        try:
            evidence = self._snapshot_evidence(payload, now)
        except Exception:
            log.exception("incident capture for rule %r failed", rule)
            evidence = None
            outcome = "error"
        capture_ms = (time.perf_counter() - t0) * 1000.0
        opened = None
        with self._lock:
            inc = (self._incidents.get(self._open_id)
                   if self._open_id else None)
            if inc is None:
                self._seq += 1
                inc = Incident(f"inc-{self._seq:04d}", now)
                self._incidents[inc.id] = inc
                self._open_id = inc.id
                opened = inc.id
                if evidence is not None:
                    inc.window_start = evidence["window_start"]
                    inc.history = evidence["history"]
                    inc.flight_events = evidence["flight_events"]
                    inc.open_last_seq = evidence["last_seq"]
                    inc.context = evidence["context"]
            elif outcome == "captured":
                # overlapping firing windows merge: the chaos drill's
                # p99 + burn + shard-down edges are ONE incident
                outcome = "merged"
            if evidence is not None:
                entry = inc.rules.get(rule)
                if entry is None:
                    entry = {}
                    inc.rules[rule] = entry
                entry.update({
                    "fired_t": now, "resolved_t": None,
                    "severity": payload.get("severity"),
                    "value": payload.get("value"),
                    "detail": payload.get("detail"),
                    "exemplar_trace_id": payload.get("exemplar_trace_id"),
                    "exemplar_spans": evidence["exemplar_spans"],
                    "alert": evidence["alert"],
                })
            inc.captures.append({"rule": rule, "t": now,
                                 "capture_ms": capture_ms,
                                 "outcome": outcome})
            open_count = 1 if self._open_id else 0
            self._evict_locked()
        # metric writes outside the lock (registry takes its own)
        _capture_counter(outcome).inc()
        _capture_hist().observe(capture_ms)
        _open_gauge().set(float(open_count))
        if opened is not None:
            from .flightrec import get_flight_recorder
            get_flight_recorder().record("incident_open", id=opened,
                                         rule=rule)

    def _snapshot_evidence(self, payload: Dict[str, Any], now: float
                           ) -> Dict[str, Any]:
        """The unlocked evidence read for one firing edge."""
        rule = payload.get("rule")
        alert, start = None, now
        for r in self.engine.rules():
            if r.name == rule:
                alert = r.to_dict()
                # pending_since survives into FIRING — the breach's
                # onset, not its threshold crossing, starts the window
                start = r.pending_since or r.firing_since or now
                break
        window_start = start - self.lookback_s
        history = [(t, d) for t, d in self.history.samples()
                   if t >= window_start]
        from .flightrec import get_flight_recorder
        events = get_flight_recorder().events()
        last_seq = int(events[-1]["seq"]) if events else 0
        flight = [e for e in events
                  if float(e.get("t", 0.0)) >= window_start]
        return {
            "window_start": window_start,
            "history": history,
            "flight_events": flight,
            "last_seq": last_seq,
            "alert": alert,
            "exemplar_spans": self._pin_exemplar(
                payload.get("exemplar_trace_id")),
            "context": self._context_blocks(),
        }

    @staticmethod
    def _pin_exemplar(trace_id: Optional[str]) -> List[Dict[str, Any]]:
        """COPY the exemplar trace's spans out of the tracer ring at
        fire time: ring wraparound and the 600 s exemplar TTL must never
        hollow out an open incident's bundle."""
        if not trace_id:
            return []
        from .tracer import get_tracer
        spans = []
        for ev in get_tracer().events():
            args = ev.get("args") or {}
            if args.get("trace_id") == trace_id:
                pinned = dict(ev)
                pinned["args"] = dict(args)
                spans.append(pinned)
        return spans

    @staticmethod
    def _context_blocks() -> Dict[str, Any]:
        """Jit table + lock census always; probe/collector snapshots
        only when those planes are WIRED (lazy global already
        constructed) — never construct a plane as a capture side
        effect. Each block is failure-isolated: one broken source must
        not cost the bundle the others."""
        ctx: Dict[str, Any] = {}
        try:
            from .jitwatch import get_jit_registry
            ctx["jit_table"] = get_jit_registry().table()
        except Exception:
            log.exception("incident capture: jit table read failed")
        try:
            from . import lockwatch
            ctx["lock_census"] = lockwatch.contention_table()
        except Exception:
            log.exception("incident capture: lock census read failed")
        for key, mod_name, attr in (
                ("probes", "deeplearning4j_tpu.monitor.probes",
                 "_PROBER"),
                ("collector", "deeplearning4j_tpu.monitor.collector",
                 "_COLLECTOR")):
            mod = sys.modules.get(mod_name)
            obj = getattr(mod, attr, None) if mod is not None else None
            if obj is None:
                continue
            try:
                ctx[key] = obj.snapshot()
            except Exception:
                log.exception("incident capture: %s snapshot failed", key)
        return ctx

    # --------------------------------------------------- resolve / close
    def _resolve(self, payload: Dict[str, Any], now: float) -> bool:
        rule = payload.get("rule")
        with self._lock:
            inc = (self._incidents.get(self._open_id)
                   if self._open_id else None)
            if inc is None or rule not in inc.rules:
                # a resolve for a rule no incident tracks (e.g. the
                # recorder came up mid-flight) is not an incident edge
                return False
            entry = inc.rules[rule]
            if entry.get("resolved_t") is None:
                entry["resolved_t"] = now
                entry["resolve_detail"] = payload.get("detail")
            if any(e.get("resolved_t") is None
                   for e in inc.rules.values()):
                return True
            # every member rule resolved: the incident closes
            inc.status = "resolved"
            inc.closed_t = now
            self._open_id = None
        self._close(inc, now)
        return True

    def abort_open(self, reason: str = "halt") -> List[str]:
        """Flush any open incident as ``status="aborted"`` — the
        ``record_halt`` crash-dump path: a process dying mid-incident
        leaves evidence on disk rather than nothing. Returns the
        persisted bundle paths (empty without a dump dir)."""
        with self._lock:
            inc = (self._incidents.get(self._open_id)
                   if self._open_id else None)
            if inc is None:
                return []
            inc.status = "aborted"
            inc.closed_t = time.time()
            self._open_id = None
        self._close(inc, inc.closed_t, reason=reason)
        return [inc.path] if inc.path else []

    def _close(self, inc: Incident, now: float, reason: str = "resolved"):
        """Finalize one incident OUTSIDE the lock: append the flight
        tail recorded while it was open, build + persist the bundle,
        then re-enter the lock only to publish the results."""
        from .flightrec import get_flight_recorder
        tail = [e for e in get_flight_recorder().events()
                if int(e.get("seq", 0)) > inc.open_last_seq]
        with self._lock:
            inc.flight_events = inc.flight_events + tail
            bundle = self._bundle_locked(inc)
        persisted = self._persist(inc.id, bundle)
        with self._lock:
            inc.bundle = bundle
            if persisted is not None:
                inc.path, inc.bundle_bytes = persisted
            still_open = self._open_id is not None
        _open_gauge().set(1.0 if still_open else 0.0)
        get_flight_recorder().record(
            "incident_closed", id=inc.id, status=inc.status,
            rules=sorted(inc.rules), path=inc.path, reason=reason)
        log.info("incident %s closed (%s): %d rule(s), %d flight "
                 "event(s)%s", inc.id, inc.status, len(inc.rules),
                 len(inc.flight_events),
                 f", bundle {inc.path}" if inc.path else "")

    @staticmethod
    def _bundle_locked(inc: Incident) -> Dict[str, Any]:
        """The offline-reconstruction schema (caller holds ``_lock``;
        every container is copied out so the bundle never aliases live
        incident state)."""
        return {
            "format": BUNDLE_FORMAT,
            "id": inc.id,
            "status": inc.status,
            "opened_t": inc.opened_t,
            "closed_t": inc.closed_t,
            "window_start": inc.window_start,
            "rules": {n: dict(e) for n, e in inc.rules.items()},
            "history": [[t, d] for t, d in inc.history],
            "flight_events": [dict(e) for e in inc.flight_events],
            "control_actions": [dict(e) for e in inc.flight_events
                                if e.get("event") == "control_action"],
            "context": dict(inc.context),
            "captures": [dict(c) for c in inc.captures],
        }

    def _persist(self, incident_id: str, bundle: Dict[str, Any]
                 ) -> Optional[Tuple[str, int]]:
        """Content-addressed write under the FlightRecorder dump
        convention: explicit ``dump_dir`` beats the
        ``DL4J_TPU_INCIDENT_DIR`` env var; neither → in-memory only. A failed write logs and returns
        None — closing an incident must never die harder because its
        black box had no disk."""
        base = self.dump_dir or os.environ.get("DL4J_TPU_INCIDENT_DIR")
        if not base:
            return None
        payload = json.dumps(bundle, sort_keys=True, default=repr)
        digest = hashlib.sha256(payload.encode("utf-8")).hexdigest()[:16]
        path = os.path.join(base, f"{incident_id}-{digest}.dl4jinc")
        try:
            with open(path, "w") as fh:
                fh.write(payload)
        except OSError as e:
            log.warning("incident bundle write to %s failed: %s", path, e)
            return None
        return path, len(payload)

    # ------------------------------------------------------ bounded table
    def _evict_locked(self):
        """Oldest CLOSED incidents leave first; the open incident is
        evidence-in-progress and only goes when it is the whole table."""
        while len(self._incidents) > self.max_incidents:
            victim = None
            for iid, inc in self._incidents.items():
                if inc.status != "open":
                    victim = iid
                    break
            if victim is None:
                victim = next(iter(self._incidents))
                if victim == self._open_id:
                    self._open_id = None
            del self._incidents[victim]
            self.evicted += 1

    # -------------------------------------------------------------- reading
    def incidents(self) -> List[Incident]:
        with self._lock:
            return list(self._incidents.values())

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /incidents`` payload (always HTTP 200, like
        ``/alerts`` — the incident surface must stay readable exactly
        while an incident is open)."""
        with self._lock:
            rows = [inc.row() for inc in self._incidents.values()]
            open_ids = [self._open_id] if self._open_id else []
            running = (self._thread is not None
                       and self._thread.is_alive())
            last = self.last_tick
            evicted = self.evicted
        return {"incidents": rows, "open": open_ids,
                "max_incidents": self.max_incidents,
                "lookback_s": self.lookback_s, "evicted": evicted,
                "running": running, "evaluated_at": last}

    def bundle(self, incident_id: str) -> Optional[Dict[str, Any]]:
        """The full bundle for ``GET /incidents/<id>``: the persisted
        schema for closed incidents, a provisional copy (no flight
        tail yet) for the open one. ``None`` for unknown ids."""
        with self._lock:
            inc = self._incidents.get(incident_id)
            if inc is None:
                return None
            if inc.bundle is not None:
                return inc.bundle
            return self._bundle_locked(inc)


# ------------------------------------------------------------ bundle I/O
def load_bundle(path: str) -> Dict[str, Any]:
    """Re-load a persisted ``.dl4jinc`` bundle, verifying the content
    address when the filename carries one (``<id>-<digest16>.dl4jinc``)
    — a truncated or edited bundle must fail loudly, not render a
    partial story as the whole one."""
    with open(path, "r") as fh:
        raw = fh.read()
    name = os.path.basename(path)
    if name.endswith(".dl4jinc") and "-" in name:
        want = name[:-len(".dl4jinc")].rsplit("-", 1)[-1]
        if len(want) == 16:
            got = hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]
            if got != want:
                raise ValueError(
                    f"bundle {path} fails its content address "
                    f"({got} != {want}): truncated or edited")
    return json.loads(raw)


def _fmt_t(t: Optional[float], t0: Optional[float]) -> str:
    if t is None:
        return "-"
    if t0 is not None:
        return f"{t - t0:+.2f}s"
    return f"{t:.3f}"


def _render_trace(spans: List[Dict[str, Any]]) -> List[str]:
    """Indent the pinned Chrome-trace spans into a parent→child tree
    (roots = spans whose parent is outside the pinned set)."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for ev in spans:
        sid = (ev.get("args") or {}).get("span_id")
        if sid:
            by_id[sid] = ev
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for ev in spans:
        parent = (ev.get("args") or {}).get("parent_span_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(ev)
        else:
            roots.append(ev)
    lines: List[str] = []

    def walk(ev, depth):
        dur_ms = float(ev.get("dur", 0.0)) / 1000.0
        cat = ev.get("cat", "")
        lines.append(f"    {'  ' * depth}- {ev.get('name')} "
                     f"[{cat}] {dur_ms:.2f}ms")
        sid = (ev.get("args") or {}).get("span_id")
        for child in sorted(children.get(sid, []),
                            key=lambda e: e.get("ts", 0.0)):
            walk(child, depth + 1)

    for root in sorted(roots, key=lambda e: e.get("ts", 0.0)):
        walk(root, 0)
    return lines


def render_incident_text(bundle: Dict[str, Any]) -> str:
    """The ``incident show`` rendering: header, per-rule summary, the
    seq-ordered merged timeline (alert edges → probe outcomes → control
    actions), and each rule's pinned exemplar trace tree inlined."""
    t0 = bundle.get("opened_t")
    lines = [f"# incident {bundle.get('id')} — {bundle.get('status')}"]
    closed = bundle.get("closed_t")
    dur = (f", duration {closed - t0:.2f}s"
           if closed is not None and t0 is not None else "")
    lines.append(f"opened_t={t0} closed_t={closed}{dur}")
    rules = bundle.get("rules") or {}
    lines.append(f"rules ({len(rules)} merged):")
    for name in sorted(rules):
        e = rules[name]
        lines.append(
            f"  {name}  severity={e.get('severity')}  "
            f"fired={_fmt_t(e.get('fired_t'), t0)}  "
            f"resolved={_fmt_t(e.get('resolved_t'), t0)}  "
            f"value={e.get('value')}")
        if e.get("detail"):
            lines.append(f"    detail: {e['detail']}")
    history = bundle.get("history") or []
    if history:
        lines.append(f"history: {len(history)} sample(s) spanning "
                     f"{history[-1][0] - history[0][0]:.1f}s "
                     f"(window_start={bundle.get('window_start')})")
    events = sorted(bundle.get("flight_events") or [],
                    key=lambda e: int(e.get("seq", 0)))
    shown = [e for e in events if e.get("event") in _TIMELINE_EVENTS]
    lines.append(f"timeline ({len(shown)} of {len(events)} flight "
                 f"event(s), seq order):")
    for e in shown:
        kind = e.get("event")
        extra = " ".join(
            f"{k}={v}" for k, v in sorted(e.items())
            if k not in ("t", "seq", "event") and v is not None)
        lines.append(f"  [{e.get('seq')}] {_fmt_t(e.get('t'), t0)} "
                     f"{kind}  {extra}".rstrip())
    actions = bundle.get("control_actions") or []
    if actions:
        lines.append(f"control actions under this incident: "
                     f"{len(actions)}")
    for name in sorted(rules):
        spans = rules[name].get("exemplar_spans") or []
        if not spans:
            continue
        lines.append(f"exemplar trace "
                     f"{rules[name].get('exemplar_trace_id')} "
                     f"(rule {name}, {len(spans)} span(s)):")
        lines.extend(_render_trace(spans))
    return "\n".join(lines)


# --------------------------------------------------------- module globals
#: lazy: a bare process has no recorder object at all — the halt hook
#: and the HTTP endpoints check this before constructing anything
_RECORDER: Optional[IncidentRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_incident_recorder() -> IncidentRecorder:
    """The process-global recorder (constructed on first use; never
    started implicitly)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = IncidentRecorder()
        return _RECORDER


def abort_open_incidents(reason: str = "halt") -> List[str]:
    """Module-level hook ``HealthState.record_halt`` calls via
    ``sys.modules`` (the control-block pattern): flush any open
    incident as an ``aborted`` bundle. No-op when no recorder was ever
    constructed — a bare process pays nothing."""
    rec = _RECORDER
    if rec is None:
        return []
    # drain any queued-but-unprocessed edges first: the halt may be the
    # direct consequence of a firing edge still sitting in the deque
    try:
        rec.tick()
    except Exception:
        log.exception("incident flush tick on halt failed")
    return rec.abort_open(reason=reason)
