"""Scrape-plane fleet collector: pull-based telemetry federation.

``FleetState`` (monitor/fleet.py) is push-shaped: paramserver workers
ship ``OP_TELEMETRY`` reports to their master and the master lands them.
N serving replicas have no master — each is its own registry, trace
ring, and flight recorder, with nothing producing the fleet-scope
signals the control plane and a future front-tier router need
("aggregate error-budget burn", "worst replica p99"). This module is
the pull half:

- :func:`telemetry_snapshot` — the ``GET /telemetry`` payload both
  servers expose (``ui/server.py`` ``JsonRequestHandler._monitor_get``):
  registry dump + trace-ring tail + seq-cursored flight events + health
  + latency-histogram exemplars in ONE round trip.
- :class:`TelemetryCollector` — an opt-in daemon (same lifecycle shape
  as the history sampler and the control plane: idempotent
  ``start(interval_s)``, timed-join ``stop()``, deterministic
  ``tick(now=)`` test seam) that polls each configured
  :class:`ScrapeTarget` over HTTP and lands the reply in a
  :class:`~.fleet.FleetState` via ``record_report`` — so every merged
  surface (``GET /fleet`` Prometheus re-labeling, ``merged_trace``
  Chrome export, liveness folded into ``/healthz``) works identically
  for scraped serving replicas and push-reporting paramserver workers.

Flight-event **cursoring**: each target's first scrape carries no
``since_seq`` — the endpoint answers with ``last_seq`` only (no
events), priming the cursor exactly like
``ControlPlane._prime_cursor``, so a replica's pre-existing incident
history never replays as fresh incidents. Subsequent scrapes pass the
cursor and receive only events recorded since; those are re-recorded
into the LOCAL flight recorder with a ``target=`` field (plus
``origin_seq``/``origin_t``), so event-triggered control policies see
remote incidents as edges.

Closing the loop **upward**: every tick feeds the merged fleet dump
(:meth:`TelemetryCollector.fleet_dump`) into the collector's own
:class:`~.history.MetricsHistory` ring and evaluates its own
:class:`~.alerts.AlertEngine` over it — the existing
``AlertRule``/``BurnRateRule`` machinery computes fleet-scope SLOs
unchanged (``default_fleet_scope_rules``: aggregate burn across
replicas, max-over-replicas windowed p99, ``fleet_target_up`` gaps),
and those edges fan out through ``AlertEngine.subscribe()`` into
``ControlPlane`` policies (``control.policies.fleet_replica_policy``).

Every scrape is itself observed: ``fleet_scrape_duration_ms{target=}``,
``fleet_scrape_errors_total{target=}``, ``fleet_target_up{target=}``;
staleness stays a read-time computation on the fleet table
(``fleet_worker_last_seen_age_s``). Lock discipline: the collector's
``_lock`` is a LEAF — it guards only the target table, cursors and
counters; HTTP scrapes, ``record_report``, history sampling and alert
evaluation all run with no lock held (the lockwatch cross-check in
tests/test_lockwatch.py pins acquisitions > 0 and outgoing edges == 0).

See docs/OBSERVABILITY.md "Scrape plane".
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from .lockwatch import make_lock

log = logging.getLogger(__name__)

__all__ = ["ScrapeTarget", "TelemetryCollector", "telemetry_snapshot",
           "get_collector"]

#: default scrape cadence (seconds) — slower than the history sampler's
#: 2s: a scrape is N HTTP round trips, not one in-process dump
DEFAULT_INTERVAL_S = 5.0

#: per-target HTTP timeout (seconds); a hung replica costs one scrape
#: slot, never the whole tick loop
DEFAULT_TIMEOUT_S = 5.0

#: newest trace-ring tail shipped per /telemetry reply — same sizing as
#: the push path's TELEMETRY_TRACE_EVENTS (paramserver/client.py);
#: consecutive replies overlap, which the fleet merge dedups by
#: (trace_id, span_id, ts)
TELEMETRY_TRACE_EVENTS = 512


def telemetry_snapshot(since_seq: Optional[int] = None,
                       trace_tail: int = TELEMETRY_TRACE_EVENTS) -> dict:
    """The ``GET /telemetry`` payload: everything a fleet collector
    needs from one replica in ONE round trip.

    - ``registry``: the full ``MetricsRegistry.dump()`` wire format.
    - ``trace_events``: the newest ``trace_tail`` Chrome-trace events.
    - ``flight_events``: ``since_seq`` given → only events with
      ``seq > since_seq``; omitted → NONE (the cursor-priming reply —
      a collector must opt into history with ``since_seq=-1``, never
      receive it by accident and replay it as fresh).
    - ``last_seq``: the newest flight-recorder sequence number — the
      cursor the caller passes next time.
    - ``health``: the ``/healthz`` snapshot (liveness folded into the
      same round trip).
    - ``exemplars``: per latency-histogram child, the worst latched
      exemplar trace id — exemplars live only in the live registry, not
      in dumps, and a fleet-scope p99 alert must surface the GUILTY
      replica's trace id.
    """
    from .flightrec import get_flight_recorder
    from .health import get_health
    from .registry import get_registry
    from .tracer import get_tracer

    reg = get_registry()
    dump = reg.dump()
    exemplars: Dict[str, List[dict]] = {}
    for name, fam in dump.items():
        if fam.get("type") != "histogram":
            continue
        rows = []
        for row in fam.get("children", []):
            labels = row.get("labels", {})
            ex = reg.histogram(name, **labels).worst_exemplar()
            if ex:
                rows.append({"labels": labels, "value": ex["value"],
                             "exemplar": ex["exemplar"]})
        if rows:
            exemplars[name] = rows
    rec = get_flight_recorder()
    events = rec.events()
    last_seq = events[-1]["seq"] if events else 0
    fresh = ([e for e in events if e.get("seq", 0) > since_seq]
             if since_seq is not None else [])
    return {
        "registry": dump,
        "trace_events": get_tracer().events()[-int(trace_tail):],
        "flight_events": fresh,
        "last_seq": last_seq,
        "health": get_health().snapshot(),
        "exemplars": exemplars,
    }


class ScrapeTarget:
    """One pull-plane endpoint: a label (the fleet table's worker key —
    series re-label as ``worker=<label>`` on ``/fleet``) and the
    replica's base URL (scheme optional; ``/telemetry`` is appended)."""

    def __init__(self, label: str, url: str):
        self.label = str(label)
        url = str(url)
        if "://" not in url:
            url = f"http://{url}"
        self.url = url.rstrip("/")

    def to_dict(self) -> dict:
        return {"label": self.label, "url": self.url}

    def __repr__(self):
        return f"ScrapeTarget({self.label!r}, {self.url!r})"


class _FleetDumpSource:
    """Registry-shaped adapter (`.dump()`) so the collector's
    :class:`MetricsHistory` samples the MERGED fleet dump instead of the
    process registry — the seam that lets the existing alert machinery
    evaluate fleet-scope SLOs unchanged."""

    def __init__(self, collector: "TelemetryCollector"):
        self._collector = collector

    def dump(self) -> dict:
        return self._collector.fleet_dump()


class TelemetryCollector:
    """Pull-based fleet collector daemon. Opt-in like the history
    sampler and the control plane: construction starts nothing; tests
    drive :meth:`tick` deterministically; production calls
    ``start(interval_s)`` and ``stop()`` timed-joins the thread.

    ``fleet`` defaults to the process-global table (so ``GET /fleet``,
    ``/fleet/trace`` and the ``/healthz`` fleet fold-in serve the
    scraped replicas with zero extra wiring); pass a private
    :class:`~.fleet.FleetState` for isolation. ``history`` and
    ``engine`` default to private instances sampling the merged fleet
    dump — attach fleet-scope rules with
    ``collector.engine.add(*default_fleet_scope_rules(fleet=collector.fleet))``.
    """

    def __init__(self, fleet=None, history=None, engine=None, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 trace_tail: int = TELEMETRY_TRACE_EVENTS):
        from .fleet import get_fleet
        from .history import MetricsHistory
        from .alerts import AlertEngine
        self.fleet = fleet if fleet is not None else get_fleet()
        self.history = (history if history is not None
                        else MetricsHistory(registry=_FleetDumpSource(self)))
        self.engine = (engine if engine is not None
                       else AlertEngine(history=self.history))
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.trace_tail = int(trace_tail)
        self._lock = make_lock("TelemetryCollector._lock")
        self._targets: Dict[str, ScrapeTarget] = {}
        self._cursors: Dict[str, int] = {}
        self._up: Dict[str, bool] = {}
        self._errors: Dict[str, int] = {}
        self._last_scrape_t: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ targets
    def add_target(self, label: str, url: str) -> "TelemetryCollector":
        target = ScrapeTarget(label, url)
        with self._lock:
            self._targets[target.label] = target
        return self

    def remove_target(self, label: str):
        with self._lock:
            self._targets.pop(str(label), None)
            self._cursors.pop(str(label), None)
            self._up.pop(str(label), None)
            self._errors.pop(str(label), None)
            self._last_scrape_t.pop(str(label), None)

    def targets(self) -> List[ScrapeTarget]:
        with self._lock:
            return [self._targets[k] for k in sorted(self._targets)]

    def down_targets(self) -> List[ScrapeTarget]:
        """Targets whose LAST scrape failed (the actuator-side view a
        fleet policy reads — ``control.policies.fleet_replica_policy``)."""
        with self._lock:
            return [self._targets[k] for k in sorted(self._targets)
                    if k in self._up and not self._up[k]]

    # ----------------------------------------------------------- scraping
    def _scrape(self, target: ScrapeTarget,
                cursor: Optional[int]) -> dict:
        """One UNLOCKED HTTP round trip to ``<url>/telemetry``. The
        first scrape for a target has no cursor and therefore gets no
        flight events back — that reply only primes ``last_seq``."""
        path = "/telemetry"
        if cursor is not None:
            path += f"?since_seq={int(cursor)}"
        with urllib.request.urlopen(target.url + path,
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode("utf-8"))

    @staticmethod
    def _scrape_metrics(label: str):
        from .registry import get_registry
        reg = get_registry()
        return (reg.histogram("fleet_scrape_duration_ms",
                              "wall-clock per /telemetry scrape by target",
                              target=label),
                reg.counter("fleet_scrape_errors_total",
                            "failed /telemetry scrapes by target",
                            target=label),
                reg.gauge("fleet_target_up",
                          "1 while the target's last scrape succeeded",
                          target=label))

    def tick(self, now: Optional[float] = None) -> dict:
        """One collection pass (the daemon's beat; also the test seam).

        Scrapes every configured target with NO lock held, lands each
        reply in the fleet table, re-records cursor-fresh remote flight
        events locally, then samples the merged fleet dump into the
        collector's history ring and evaluates the fleet-scope alert
        engine. Returns a per-tick summary (labels scraped, per-target
        scrape ms, errors) so tests and the bench latch exact numbers
        instead of diffing process-global counters."""
        from .flightrec import get_flight_recorder
        t_tick0 = time.perf_counter()
        now = float(now) if now is not None else time.time()
        with self._lock:
            targets = [self._targets[k] for k in sorted(self._targets)]
            cursors = dict(self._cursors)
        scraped: List[str] = []
        errors: Dict[str, str] = {}
        scrape_ms: Dict[str, float] = {}
        for target in targets:
            hist, err_counter, up_gauge = self._scrape_metrics(target.label)
            cursor = cursors.get(target.label)
            t0 = time.perf_counter()
            try:
                doc = self._scrape(target, cursor)
            except Exception as e:      # refused/timeout/bad JSON alike:
                ms = (time.perf_counter() - t0) * 1e3
                hist.observe(ms)        # a down replica is a DATA point,
                scrape_ms[target.label] = ms   # never a collector crash
                err_counter.inc()
                up_gauge.set(0.0)
                errors[target.label] = f"{type(e).__name__}: {e}"
                with self._lock:
                    was_up = self._up.get(target.label)
                    self._up[target.label] = False
                    self._errors[target.label] = \
                        self._errors.get(target.label, 0) + 1
                if was_up is not False:   # edge-triggered, never per-tick
                    get_flight_recorder().record(
                        "fleet_target_down", target=target.label,
                        url=target.url, error=errors[target.label])
                log.warning("fleet scrape of %s (%s) failed: %s",
                            target.label, target.url,
                            errors[target.label])
                continue
            ms = (time.perf_counter() - t0) * 1e3
            hist.observe(ms)
            scrape_ms[target.label] = ms
            up_gauge.set(1.0)
            fresh = list(doc.get("flight_events") or [])
            with self._lock:
                was_up = self._up.get(target.label)
                self._up[target.label] = True
                self._cursors[target.label] = int(doc.get("last_seq") or 0)
                self._last_scrape_t[target.label] = now
            self.fleet.record_report(target.label, {
                "registry": doc.get("registry") or {},
                "trace_events": doc.get("trace_events"),
                "flight_events": fresh or None,
                "exemplars": doc.get("exemplars"),
                "health": doc.get("health"),
            }, append_flight=True)
            if was_up is False:
                get_flight_recorder().record("fleet_target_recovered",
                                             target=target.label,
                                             url=target.url)
            # cursor-fresh remote incidents become LOCAL edges (with
            # provenance) so event-triggered policies see them; the
            # primed cursor guarantees pre-existing history never lands
            for ev in fresh:
                fields = {k: v for k, v in ev.items()
                          if k not in ("t", "seq", "event")}
                get_flight_recorder().record(
                    str(ev.get("event", "fleet_event")),
                    target=target.label, origin_seq=ev.get("seq"),
                    origin_t=ev.get("t"), **fields)
            scraped.append(target.label)
        # upward loop: merged fleet dump -> history ring -> SLO engine
        if targets:
            self.history.sample(now=now)
            self.engine.evaluate(now=now, strict=False)
        return {"t": now, "scraped": scraped, "errors": errors,
                "scrape_ms": scrape_ms,
                "duration_ms": (time.perf_counter() - t_tick0) * 1e3}

    # ------------------------------------------------------- merged dump
    def fleet_dump(self) -> dict:
        """The merged fleet dump the collector's history samples: every
        landed report's series re-labeled ``worker=<label>`` plus the
        synthesized liveness series (``FleetState.merged_dump``), with
        the collector's OWN scrape series grafted in — filtered to the
        CURRENT target set, so a long-lived process registry cannot leak
        a retired target's ``fleet_target_up 0`` into a gap rule."""
        from .registry import get_registry
        dump = self.fleet.merged_dump()
        with self._lock:
            current = set(self._targets)
        reg_dump = get_registry().dump()
        for name in ("fleet_target_up", "fleet_scrape_errors_total",
                     "fleet_scrape_duration_ms"):
            fam = reg_dump.get(name)
            if not fam:
                continue
            rows = [r for r in fam.get("children", [])
                    if r.get("labels", {}).get("target") in current]
            if rows:
                dump[name] = {**{k: v for k, v in fam.items()
                                 if k != "children"}, "children": rows}
        return dump

    def snapshot(self) -> dict:
        """The collector's own state (targets, cursors, liveness) — the
        ``monitor --collect`` / debugging view."""
        with self._lock:
            targets = {
                k: {"url": t.url,
                    "up": self._up.get(k),
                    "cursor": self._cursors.get(k),
                    "errors": self._errors.get(k, 0),
                    "last_scrape_t": self._last_scrape_t.get(k)}
                for k, t in sorted(self._targets.items())}
        return {"interval_s": self.interval_s,
                "timeout_s": self.timeout_s,
                "running": self.running(),
                "targets": targets}

    # ---------------------------------------------------------- lifecycle
    def start(self, interval_s: Optional[float] = None
              ) -> "TelemetryCollector":
        """Start the background scrape loop (idempotent). The thread is
        a daemon AND joined by :meth:`stop` — THR002 discipline."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-collector", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        # first scrape immediately: a rule pack attached at start sees
        # fleet data after one interval, not two
        self._safe_tick()
        while not self._stop.wait(self.interval_s):
            self._safe_tick()

    def _safe_tick(self):
        try:
            self.tick()
        except Exception:
            log.exception("telemetry-collector tick failed")

    def stop(self, timeout: float = 5.0):
        with self._lock:
            thread, self._thread = self._thread, None
            if thread is not None:
                # set the event INSIDE the lock: a concurrent start()
                # serializes behind us and clears it for ITS thread —
                # setting after release could kill the fresh loop on its
                # first wait() (same invariant as MetricsHistory.stop)
                self._stop.set()
        if thread is not None:
            thread.join(timeout)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()


#: lazily-created process-global collector (no thread, no targets until
#: someone configures and starts it — tier-1 suites run with zero
#: collectors); feeds the process-global FleetState so /fleet serves it
_COLLECTOR: Optional[TelemetryCollector] = None
_COLLECTOR_LOCK = threading.Lock()


def get_collector() -> TelemetryCollector:
    global _COLLECTOR
    with _COLLECTOR_LOCK:
        if _COLLECTOR is None:
            _COLLECTOR = TelemetryCollector()
        return _COLLECTOR
