"""Declarative alert rules over metric history: SLOs that act.

The seeing half of the observability stack (PRs 2-9) ends at endpoints a
human must poll; this module is the acting half. Rules are evaluated over
:class:`~deeplearning4j_tpu.monitor.history.MetricsHistory` windows and
run a three-state machine per rule::

    OK --breach--> PENDING --breach held for_seconds--> FIRING
    FIRING --breach clears--> OK (resolved)

- **PENDING** is the hold-down: a single bad sample (one slow scrape, one
  transient queue spike) never pages — the breach must persist for the
  rule's ``for_seconds`` before it fires.
- **FIRING** is edge-triggered: ONE ``alert_firing`` flight-recorder
  event, one health problem (``kind="alert"`` — lands on ``/healthz``
  like every watchdog), and ``alerts_firing{rule=}`` set to 1. Resolution
  mirrors it (``alert_resolved`` event, gauge back to 0).
- A firing latency alert carries an **exemplar trace id** — the worst
  recent sample's trace latched by the serving latency histogram
  (``LatencyHistogram.record(..., exemplar=)``) — so the responder jumps
  from the alert straight to the offending request on ``GET /trace``
  instead of guessing from an aggregate.

Rule types:

- :class:`ThresholdRule` — one metric, one comparison: current value,
  windowed rate, windowed max, or windowed quantile vs a threshold.
- :class:`BurnRateRule` — multi-window SLO burn (the SRE playbook):
  *availability* (1 − bad/total must stay ≥ the SLO target; the error
  budget burn rate must exceed ``burn_factor`` on BOTH the short and the
  long window to breach — short confirms it is still happening, long
  confirms it is not noise) and *latency* (windowed p99 over target on
  both windows).
- :class:`HealthRule` — training stall/divergence/NaN read from the
  existing :func:`~deeplearning4j_tpu.monitor.health.get_health` state
  (the watchdog already classifies; this turns its problems into
  stateful, resolvable alerts).
- :class:`FleetStalenessRule` — workers stale on the fleet table.

``action`` mirrors the TrainingHealthListener contract: ``"warn"``
(default) records the problem, ``"halt"`` additionally requests the
graceful training stop via ``HealthState.record_halt``, ``"raise"``
raises :class:`AlertError` out of a *synchronous* ``evaluate`` (the
sampler thread and the HTTP endpoints evaluate with ``strict=False``,
which downgrades raise to warn — an alert must never kill the sampler).

``default_serving_rules`` / ``default_training_rules`` /
``default_fleet_rules`` are the shipped rule packs; nothing is installed
by default (tier-1 suites run with zero rules and therefore zero alerts).
See docs/OBSERVABILITY.md "Alerting & SLOs".
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .lockwatch import make_lock
from .history import MetricsHistory, get_history

log = logging.getLogger(__name__)

__all__ = ["AlertError", "AlertRule", "ThresholdRule", "BurnRateRule",
           "HealthRule", "FleetStalenessRule", "AlertEngine",
           "get_alert_engine", "default_serving_rules",
           "default_training_rules", "default_fleet_rules",
           "default_fleet_scope_rules", "default_probe_rules",
           "default_rules"]

OK, PENDING, FIRING = "OK", "PENDING", "FIRING"

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class AlertError(RuntimeError):
    """Raised by a strict ``AlertEngine.evaluate`` when a rule with
    ``action="raise"`` fires. ``rule`` names the offender."""

    def __init__(self, rule: str, message: str):
        super().__init__(message)
        self.rule = rule


class AlertRule:
    """One named rule: subclasses implement :meth:`check`; the engine owns
    the OK/PENDING/FIRING state machine, hold-down, and event fan-out."""

    ACTIONS = ("warn", "raise", "halt")

    def __init__(self, name: str, *, for_seconds: float = 0.0,
                 severity: str = "page", action: str = "warn",
                 description: str = ""):
        if action not in self.ACTIONS:
            raise ValueError(f"action must be one of {self.ACTIONS}, "
                             f"got {action!r}")
        self.name = str(name)
        self.for_seconds = float(for_seconds)
        self.severity = str(severity)
        self.action = action
        self.description = description
        # state machine (engine-owned, engine-lock-guarded)
        self.state = OK
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.fired_count = 0
        self.last_value: Optional[float] = None
        self.last_detail: str = ""
        self.last_exemplar: Optional[str] = None

    def check(self, history: MetricsHistory, now: float
              ) -> Tuple[bool, Optional[float], str, Optional[str]]:
        """(breached, observed value, human detail, exemplar trace id)."""
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.name,
            "state": self.state,
            "severity": self.severity,
            "action": self.action,
            "for_seconds": self.for_seconds,
            "description": self.description,
            "pending_since": self.pending_since,
            "firing_since": self.firing_since,
            "fired_count": self.fired_count,
            "value": self.last_value,
            "detail": self.last_detail,
            "exemplar_trace_id": self.last_exemplar,
        }


class ThresholdRule(AlertRule):
    """``mode``: ``"value"`` (newest sample), ``"rate"`` (counter
    increase/s over ``window_s``), ``"max"`` (gauge max over the window),
    or ``"quantile"`` (windowed histogram quantile ``q``, in the family's
    unit). A metric with no data does not breach — absence of traffic is
    not an incident for a threshold rule."""

    def __init__(self, name: str, metric: str, *, threshold: float,
                 op: str = ">", mode: str = "value", window_s: float = 60.0,
                 q: float = 0.99, labels: Optional[Dict[str, str]] = None,
                 agg: str = "sum",
                 exemplar_lookup: Optional[
                     Callable[[], Optional[str]]] = None,
                 detail_lookup: Optional[Callable[[], str]] = None,
                 **kw):
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
        if mode not in ("value", "rate", "max", "quantile"):
            raise ValueError(f"unknown mode {mode!r}")
        if agg not in ("sum", "max", "min"):
            raise ValueError(f"agg must be sum|max|min, got {agg!r}")
        self.metric = metric
        #: optional breach-time annotation seams (the probe rules use
        #: them: a deadman/mismatch breach should name the guilty target
        #: and carry a trace id resolvable on THAT replica's /trace) —
        #: ``exemplar_lookup() -> trace id``, ``detail_lookup() -> str``
        #: appended to the numeric detail; both failure-isolated
        self.exemplar_lookup = exemplar_lookup
        self.detail_lookup = detail_lookup
        self.threshold = float(threshold)
        self.op = op
        self.mode = mode
        self.window_s = float(window_s)
        self.q = float(q)
        self.labels = dict(labels) if labels else None
        #: child aggregation for value/max modes: "sum" across matching
        #: children, or "max" (worst single child — the right reading
        #: when the threshold is a PER-child cap, e.g. queue depth vs
        #: one model's admission cap)
        self.agg = agg

    def _observe(self, history: MetricsHistory, now: float
                 ) -> Optional[float]:
        if self.mode == "value":
            return history.current(self.metric, self.labels, agg=self.agg)
        if self.mode == "rate":
            # rate normalizes by the ACTUAL sample span, so it stays
            # honest on a young ring — no coverage guard needed
            return history.rate(self.metric, self.window_s, self.labels,
                                now=now)
        if not history.covers(self.window_s, now=now):
            # max/quantile over an uncovered window would silently
            # describe a shorter span — the same dishonesty the
            # burn-rate windows guard against
            return None
        if self.mode == "max":
            return history.max_over(self.metric, self.window_s, self.labels,
                                    now=now, agg=self.agg)
        return history.quantile_over(self.metric, self.q, self.window_s,
                                     self.labels, now=now)

    def check(self, history, now):
        v = self._observe(history, now)
        if v is None:
            return False, None, f"{self.metric}: no data", None
        breached = _OPS[self.op](v, self.threshold)
        what = {"value": self.metric,
                "rate": f"rate({self.metric})/s",
                "max": f"max_{self.window_s:g}s({self.metric})",
                "quantile": f"p{int(self.q * 100)}({self.metric})"}[self.mode]
        detail = f"{what} = {v:.6g} {self.op} {self.threshold:g}"
        exemplar = None
        if breached:
            if self.detail_lookup is not None:
                try:
                    extra = self.detail_lookup()
                    if extra:
                        detail += f" — {extra}"
                except Exception:
                    log.exception("detail lookup for rule %r failed",
                                  self.name)
            if self.exemplar_lookup is not None:
                try:
                    exemplar = self.exemplar_lookup()
                except Exception:
                    log.exception("exemplar lookup for rule %r failed",
                                  self.name)
        return breached, v, detail, exemplar


class BurnRateRule(AlertRule):
    """Multi-window SLO burn rate.

    ``kind="availability"``: availability = 1 − bad/total over a window
    (``bad_labels`` rows of ``total_metric`` — the serving default counts
    ``outcome`` in ``error``/``deadline``, the 5xx outcomes). Burn rate =
    (bad/total) / (1 − slo); breach when burn > ``burn_factor`` on BOTH
    windows. With the defaults (slo 0.999, factor 14.4, 60s/300s) a full
    outage fires in ~one minute while a 0.1% error trickle never does —
    exactly the SRE multiwindow table.

    ``kind="latency"``: windowed p-``q`` of ``latency_metric`` over
    ``target_ms`` on BOTH windows; the exemplar is the worst latched
    trace id of the latency histogram (requests route it via the serving
    batcher).

    ``per_label`` (latency kind): evaluate the windowed quantile
    SEPARATELY for each observed value of that label — "max over
    replicas" instead of "quantile of the merged fleet histogram", the
    fleet-scope reading where one slow replica must not be averaged
    away by N healthy ones. Breach when ANY value breaches on both
    windows; the detail names the guilty label value.

    ``exemplar_lookup``: ``fn(guilty_label_value_or_None) -> trace id``
    replaces the live-registry exemplar read — fleet-scope rules
    evaluate over a MERGED history whose exemplars live on the remote
    replicas; the fleet table stores what ``/telemetry`` shipped
    (``FleetState.worst_exemplar``)."""

    def __init__(self, name: str, *, kind: str = "availability",
                 slo: float = 0.999, burn_factor: float = 14.4,
                 windows: Sequence[float] = (60.0, 300.0),
                 total_metric: str = "serving_requests_total",
                 total_labels: Optional[Dict[str, str]] = None,
                 bad_labels: Optional[Sequence[Dict[str, str]]] = None,
                 latency_metric: str = "serving_request_latency_ms",
                 latency_labels: Optional[Dict[str, str]] = None,
                 target_ms: float = 250.0, q: float = 0.99,
                 min_requests: float = 1.0,
                 per_label: Optional[str] = None,
                 exemplar_lookup: Optional[
                     Callable[[Optional[str]], Optional[str]]] = None,
                 **kw):
        super().__init__(name, **kw)
        if kind not in ("availability", "latency"):
            raise ValueError(f"kind must be availability|latency, "
                             f"got {kind!r}")
        self.kind = kind
        self.slo = float(slo)
        self.burn_factor = float(burn_factor)
        self.windows = tuple(float(w) for w in windows)
        self.total_metric = total_metric
        self.total_labels = dict(total_labels) if total_labels else None
        self.bad_labels = ([dict(b) for b in bad_labels] if bad_labels
                           else [{"outcome": "error"},
                                 {"outcome": "deadline"}])
        self.latency_metric = latency_metric
        self.latency_labels = dict(latency_labels) if latency_labels \
            else None
        self.target_ms = float(target_ms)
        self.q = float(q)
        self.min_requests = float(min_requests)
        self.per_label = per_label
        self.exemplar_lookup = exemplar_lookup

    def _bad_delta(self, history, window, now) -> float:
        total = 0.0
        for bl in self.bad_labels:
            labels = dict(self.total_labels or {})
            labels.update(bl)
            d = history.delta(self.total_metric, window, labels, now=now)
            if d:
                total += d
        return total

    def _availability(self, history, now):
        budget = max(1.0 - self.slo, 1e-9)
        burns = []
        for w in self.windows:
            if not history.covers(w, now=now):
                # a ring younger than the window would make the long
                # window equal to the short one — the multiwindow
                # protection must not degenerate to a single window
                return False, None, (f"history does not cover the "
                                     f"{w:g}s window yet"), None
            total = history.delta(self.total_metric, w, self.total_labels,
                                  now=now)
            if total is None or total < self.min_requests:
                return False, None, (f"error budget: <{self.min_requests:g} "
                                     f"requests in {w:g}s window"), None
            ratio = self._bad_delta(history, w, now) / max(total, 1.0)
            burns.append(ratio / budget)
        breached = all(b > self.burn_factor for b in burns)
        detail = (f"error-budget burn "
                  + "/".join(f"{b:.1f}x@{w:g}s"
                             for b, w in zip(burns, self.windows))
                  + f" vs {self.burn_factor:g}x (slo {self.slo})")
        return breached, max(burns), detail, None

    def _exemplar(self, guilty: Optional[str]) -> Optional[str]:
        if self.exemplar_lookup is not None:
            try:
                return self.exemplar_lookup(guilty)
            except Exception:
                log.exception("exemplar lookup for rule %r failed",
                              self.name)
                return None
        return self._worst_trace()

    def _per_label_values(self, history) -> List[str]:
        """Observed values of ``per_label`` in the NEWEST sample's
        latency family (restricted to ``latency_labels``) — the replica
        roster the per-replica quantiles iterate."""
        samples = history.samples()
        if not samples:
            return []
        from .history import _match
        fam = samples[-1][1].get(self.latency_metric) or {}
        values = set()
        for row in fam.get("children", []):
            labels = row.get("labels", {})
            if not _match(labels, self.latency_labels):
                continue
            v = labels.get(self.per_label)
            if v is not None:
                values.add(v)
        return sorted(values)

    def _latency(self, history, now):
        for w in self.windows:
            if not history.covers(w, now=now):
                return False, None, (f"history does not cover the "
                                     f"{w:g}s window yet"), None
        if self.per_label is None:
            ps = []
            for w in self.windows:
                p = history.quantile_over(self.latency_metric, self.q, w,
                                          self.latency_labels, now=now)
                if p is None:
                    return False, None, (f"p{int(self.q * 100)}: no "
                                         f"samples in {w:g}s window"), None
                ps.append(p)
            breached = all(p > self.target_ms for p in ps)
            exemplar = self._exemplar(None) if breached else None
            detail = (f"p{int(self.q * 100)} "
                      + "/".join(f"{p:.1f}ms@{w:g}s"
                                 for p, w in zip(ps, self.windows))
                      + f" vs target {self.target_ms:g}ms")
            return breached, max(ps), detail, exemplar
        # per-label (fleet-scope): the quantile is computed per value of
        # per_label and the rule reads the WORST one — a merged-histogram
        # quantile would let N fast replicas dilute one slow replica
        # below the target (the exact failure mode a router cares about)
        worst = None          # (peak_p, value_breached, label, ps)
        for v in self._per_label_values(history):
            labels = {**(self.latency_labels or {}), self.per_label: v}
            ps = []
            for w in self.windows:
                p = history.quantile_over(self.latency_metric, self.q, w,
                                          labels, now=now)
                if p is None:
                    ps = None       # idle on this window: not a breach,
                    break           # not a candidate for "worst" either
                ps.append(p)
            if ps is None:
                continue
            breached = all(p > self.target_ms for p in ps)
            peak = max(ps)
            # breaching values outrank non-breaching ones — the guilty
            # replica named in the detail must actually be a breacher
            rank = (breached, peak)
            if worst is None or rank > (worst[1], worst[0]):
                worst = (peak, breached, v, ps)
        if worst is None:
            return False, None, (f"p{int(self.q * 100)}: no "
                                 f"{self.per_label} series with samples "
                                 f"in window"), None
        peak, breached, guilty, ps = worst
        exemplar = self._exemplar(guilty) if breached else None
        detail = (f"worst {self.per_label}={guilty} p{int(self.q * 100)} "
                  + "/".join(f"{p:.1f}ms@{w:g}s"
                             for p, w in zip(ps, self.windows))
                  + f" vs target {self.target_ms:g}ms")
        return breached, peak, detail, exemplar

    def _worst_trace(self) -> Optional[str]:
        """Worst latched exemplar across the latency histogram's matching
        children — read from the LIVE registry (exemplars are local, not
        part of the history dumps)."""
        from .registry import get_registry
        reg = get_registry()
        dump = reg.dump().get(self.latency_metric)
        if not dump:
            return None
        from .history import _match
        worst = None
        for row in dump.get("children", []):
            labels = row.get("labels", {})
            if not _match(labels, self.latency_labels):
                continue
            child = reg.histogram(self.latency_metric, **labels)
            ex = child.worst_exemplar()
            if ex and (worst is None or ex["value"] > worst["value"]):
                worst = ex
        return worst["exemplar"] if worst else None

    def check(self, history, now):
        return (self._availability(history, now) if self.kind ==
                "availability" else self._latency(history, now))


class HealthRule(AlertRule):
    """Training health as a stateful alert. ``kind="stall"`` breaches when
    iterations have happened but the last one is older than
    ``stall_after_s``; ``kind="problem"`` breaches while a
    ``health_problem`` flight-recorder event whose kind matches
    ``problem_kinds`` (divergence / nan / retrace — the watchdog already
    classified it) was recorded within the trailing ``within_s``. Flight
    events carry timestamps, so the alert RESOLVES once the problems age
    out — the health snapshot's 8-slot problem ring is append-only for
    the process lifetime (and shared with every other problem source), so
    reading it directly would either never resolve or resolve spuriously
    on eviction."""

    def __init__(self, name: str, *, kind: str = "stall",
                 stall_after_s: float = 120.0,
                 problem_kinds: Sequence[str] = ("nan", "divergence"),
                 within_s: float = 300.0, **kw):
        super().__init__(name, **kw)
        if kind not in ("stall", "problem"):
            raise ValueError(f"kind must be stall|problem, got {kind!r}")
        self.kind = kind
        self.stall_after_s = float(stall_after_s)
        self.problem_kinds = tuple(problem_kinds)
        self.within_s = float(within_s)

    def check(self, history, now):
        if self.kind == "stall":
            from .health import get_health
            snap = get_health().snapshot()
            age = snap.get("last_iteration_age_s")
            if age is None:
                return False, None, "no training iterations yet", None
            return (age > self.stall_after_s, age,
                    f"last iteration {age:.1f}s ago "
                    f"(stall_after={self.stall_after_s:g}s)", None)
        from .flightrec import get_flight_recorder
        hits = [e for e in get_flight_recorder().events()
                if e.get("event") == "health_problem"
                and e.get("kind") in self.problem_kinds
                and now - e.get("t", 0.0) <= self.within_s]
        return (bool(hits), float(len(hits)),
                (f"{hits[-1].get('kind')}: {hits[-1].get('message')}"
                 if hits else
                 f"no {'/'.join(self.problem_kinds)} problems in the "
                 f"last {self.within_s:g}s"), None)


class FleetStalenessRule(AlertRule):
    """Workers stale on the fleet table (no OP_TELEMETRY report within the
    fleet's staleness horizon) — only meaningful on the process where
    reports land (the paramserver server)."""

    def __init__(self, name: str, *, min_stale: int = 1, **kw):
        super().__init__(name, **kw)
        self.min_stale = int(min_stale)

    def check(self, history, now):
        from .fleet import get_fleet
        live = get_fleet().liveness()
        stale = live.get("stale", [])
        if not live.get("workers"):
            return False, None, "no fleet workers reporting", None
        return (len(stale) >= self.min_stale, float(len(stale)),
                f"stale workers: {sorted(stale)}" if stale
                else "all workers fresh", None)


class AlertEngine:
    """Holds rules, drives their state machines, fans out events.

    One engine per process (:func:`get_alert_engine`), sharing the global
    :class:`MetricsHistory`. ``attach()`` registers the engine on the
    history sampler so every tick evaluates; the ``/alerts`` endpoints
    additionally evaluate at request time so a snapshot is never staler
    than the scrape that asked for it."""

    def __init__(self, history: Optional[MetricsHistory] = None):
        self._lock = make_lock("AlertEngine._lock")
        # serializes whole evaluation passes INCLUDING their event
        # fan-out, and remove()/clear()'s closing edges: without it a
        # sampler-tick evaluate and a request-time /alerts evaluate (or a
        # concurrent remove) could emit alert_resolved before the queued
        # alert_firing, stranding the gauge at 1 with no owner. Ordered
        # strictly before _lock; never held while a rule fires an
        # exception into the caller (release happens in the finally).
        self._eval_lock = make_lock("AlertEngine._eval_lock")
        self._history = history
        self._rules: Dict[str, AlertRule] = {}
        self._listeners: List[Callable[[str, Dict[str, Any]], None]] = []
        self._attached = False
        self.last_evaluated: Optional[float] = None

    @property
    def history(self) -> MetricsHistory:
        return self._history if self._history is not None else get_history()

    # ------------------------------------------------------------- rules
    def add(self, *rules: AlertRule) -> "AlertEngine":
        with self._lock:
            for r in rules:
                if r.name in self._rules:
                    raise ValueError(f"alert rule {r.name!r} already "
                                     f"registered")
                self._rules[r.name] = r
        return self

    def _resolve_dangling(self, name: str):
        """A FIRING rule leaving the engine (remove/clear) must not leave
        an unmatched ``alert_firing`` edge: zero the gauge, record the
        closing ``alert_resolved``, AND deliver the same edge to every
        subscribed listener — a controller tracking the incident must see
        it close, not keep a cooldown latched for a rule that no longer
        exists. Runs under ``_eval_lock`` (the remove/clear callers hold
        it), so no listener can observe a firing edge for the deleted
        rule after this returns."""
        AlertEngine._gauge(name).set(0.0)
        from .flightrec import get_flight_recorder
        get_flight_recorder().record("alert_resolved", rule=name,
                                     detail="rule removed from engine")
        self._notify("alert_resolved", {
            "rule": name, "severity": None, "value": None,
            "detail": "rule removed from engine",
            "exemplar_trace_id": None})

    def remove(self, name: str):
        with self._eval_lock:      # never interleave with an in-flight
            with self._lock:       # evaluation's transition fan-out
                rule = self._rules.pop(name, None)
                was_firing = rule is not None and rule.state == FIRING
            if was_firing:
                self._resolve_dangling(name)

    def rules(self) -> List[AlertRule]:
        with self._lock:
            return [self._rules[n] for n in sorted(self._rules)]

    def clear(self):
        with self._eval_lock:
            with self._lock:
                rules, self._rules = list(self._rules.values()), {}
                firing = [r.name for r in rules if r.state == FIRING]
            for name in firing:
                self._resolve_dangling(name)

    def attach(self) -> "AlertEngine":
        """Evaluate on every history sampler tick (idempotent)."""
        with self._lock:
            if self._attached:
                return self
            self._attached = True
        self.history.add_listener(lambda _h: self.evaluate(strict=False))
        return self

    # ---------------------------------------------------------- listeners
    def subscribe(self, fn: Callable[[str, Dict[str, Any]], None]
                  ) -> "AlertEngine":
        """Register ``fn(event, payload)`` for every firing/resolved edge.

        ``event`` is ``"alert_firing"`` or ``"alert_resolved"``; the
        payload mirrors the flight-recorder record (``rule``,
        ``severity``, ``value``, ``detail``, ``exemplar_trace_id``).
        Delivery runs outside ``_lock`` but inside ``_eval_lock``, so a
        listener sees edges in the exact order the state machine emitted
        them — and, crucially for controllers, ``remove()``/``clear()``
        deliver the closing resolved edge under the same lock, so no
        firing callback for a deleted rule can trail the removal. This
        replaces controllers polling :meth:`snapshot` (which sees levels,
        not edges, and so cannot distinguish one long incident from N).
        Listener errors are logged, never fatal. Idempotent per ``fn``."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)
        return self

    def unsubscribe(self, fn: Callable[[str, Dict[str, Any]], None]):
        """Remove a subscribed listener (no-op when absent). An edge
        fan-out already in flight may still deliver to ``fn`` once —
        callers that need a hard cut synchronize on their own state, as
        :class:`~deeplearning4j_tpu.control.plane.ControlPlane` does."""
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _notify(self, event: str, payload: Dict[str, Any]):
        """Listener fan-out OUTSIDE ``_lock`` (listeners run arbitrary
        actuator code and take their own locks — THR004 discipline)."""
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event, dict(payload))
            except Exception:
                log.exception("alert listener %r failed on %s(%s)",
                              fn, event, payload.get("rule"))

    # --------------------------------------------------------- evaluation
    @staticmethod
    def _gauge(name: str):
        from .registry import get_registry
        return get_registry().gauge(
            "alerts_firing", "alert rules currently FIRING (1) by rule",
            rule=name)

    def evaluate(self, now: Optional[float] = None,
                 strict: bool = True) -> List[Dict[str, Any]]:
        """One evaluation pass over every rule; returns the snapshot rows.
        ``strict=False`` (sampler/endpoints) downgrades ``action="raise"``
        to a warning — background evaluation must never throw."""
        now = float(now) if now is not None else time.time()
        history = self.history
        with self._eval_lock:
            return self._evaluate_locked(now, history, strict)

    def _evaluate_locked(self, now: float, history: MetricsHistory,
                         strict: bool) -> List[Dict[str, Any]]:
        transitions: List[Tuple[AlertRule, str]] = []
        with self._lock:
            rules = list(self._rules.values())
            self.last_evaluated = now
        raise_after: Optional[AlertError] = None
        for rule in rules:
            try:
                breached, value, detail, exemplar = rule.check(history, now)
            except Exception:
                log.exception("alert rule %r check failed", rule.name)
                continue
            with self._lock:
                if self._rules.get(rule.name) is not rule:
                    # removed (or replaced) while its check ran: firing
                    # now would strand the gauge/health problem with no
                    # registered owner to ever resolve them
                    continue
                rule.last_value = value
                rule.last_detail = detail
                if exemplar is not None:
                    rule.last_exemplar = exemplar
                if breached:
                    if rule.state == OK:
                        rule.state = PENDING
                        rule.pending_since = now
                    if (rule.state == PENDING
                            and now - rule.pending_since
                            >= rule.for_seconds):
                        rule.state = FIRING
                        rule.firing_since = now
                        rule.fired_count += 1
                        transitions.append((rule, "alert_firing"))
                else:
                    if rule.state == FIRING:
                        transitions.append((rule, "alert_resolved"))
                    if rule.state != OK:
                        rule.state = OK
                        rule.pending_since = None
                        rule.firing_since = None
                        # the exemplar belongs to THIS incident: a later
                        # firing with no fresh exemplar must not surface
                        # a trace id from hours ago that no longer
                        # resolves (EXEMPLAR_TTL_S's point, end to end)
                        rule.last_exemplar = None
        for rule, event in transitions:
            err = self._fire(rule, event)
            if err is not None and raise_after is None:
                raise_after = err
        if strict and raise_after is not None:
            raise raise_after
        return self.snapshot()["alerts"]

    def _fire(self, rule: AlertRule, event: str) -> Optional[AlertError]:
        """Event fan-out OUTSIDE the engine lock (flight recorder, health
        and registry each take their own locks — holding ours across them
        would hand THR004 a real finding)."""
        from .flightrec import get_flight_recorder
        firing = event == "alert_firing"
        self._gauge(rule.name).set(1.0 if firing else 0.0)
        get_flight_recorder().record(
            event, rule=rule.name, severity=rule.severity,
            value=rule.last_value, detail=rule.last_detail,
            exemplar_trace_id=rule.last_exemplar if firing else None)
        self._notify(event, {
            "rule": rule.name, "severity": rule.severity,
            "value": rule.last_value, "detail": rule.last_detail,
            "exemplar_trace_id": rule.last_exemplar if firing else None})
        if not firing:
            log.info("alert resolved: %s (%s)", rule.name, rule.last_detail)
            return None
        msg = (f"alert {rule.name} FIRING: {rule.last_detail}"
               + (f" — exemplar trace {rule.last_exemplar}"
                  if rule.last_exemplar else ""))
        log.warning("%s", msg)
        from .health import get_health
        get_health().record_problem("alert", msg)
        if rule.action == "halt":
            get_health().record_halt(msg)
        elif rule.action == "raise":
            return AlertError(rule.name, msg)
        return None

    # ------------------------------------------------------------ reading
    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, r in self._rules.items()
                          if r.state == FIRING)

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /alerts`` payload (always HTTP 200 — an alerting
        endpoint that 503s while alerting would blind the prober exactly
        when it matters)."""
        with self._lock:
            rows = [self._rules[n].to_dict() for n in sorted(self._rules)]
            evaluated = self.last_evaluated
        return {"alerts": rows,
                "firing": [r["rule"] for r in rows
                           if r["state"] == FIRING],
                "pending": [r["rule"] for r in rows
                            if r["state"] == PENDING],
                "evaluated_at": evaluated}


# ------------------------------------------------------- default rule packs
#: default hold-down for the shipped rule packs: a breach must persist
#: this long before paging, so one transient sample (a queue blip, a
#: single slow scrape) never fires — the state-machine invariant the
#: module docstring promises. Pass for_seconds=0.0 for instant-fire
#: (tests, demos).
DEFAULT_FOR_SECONDS = 30.0


def default_serving_rules(model: Optional[str] = None, *,
                          slo: float = 0.999, burn_factor: float = 14.4,
                          windows: Sequence[float] = (60.0, 300.0),
                          p99_target_ms: float = 250.0,
                          queue_cap: int = 256,
                          queue_frac: float = 0.8,
                          reject_rate_per_s: float = 1.0,
                          for_seconds: float = DEFAULT_FOR_SECONDS
                          ) -> List[AlertRule]:
    """The serving pack: error-budget burn, p99 breach, queue saturation,
    reject rate. ``model=None`` aggregates across hosted models."""
    labels = {"model": model} if model else None
    suffix = f"/{model}" if model else ""
    return [
        BurnRateRule(f"serving_error_burn{suffix}", kind="availability",
                     slo=slo, burn_factor=burn_factor, windows=windows,
                     total_labels=labels, for_seconds=for_seconds,
                     description="5xx error-budget burn on both windows"),
        BurnRateRule(f"serving_p99_breach{suffix}", kind="latency",
                     target_ms=p99_target_ms, windows=windows,
                     latency_labels=labels, for_seconds=for_seconds,
                     description="windowed p99 over target on both windows"),
        ThresholdRule(f"serving_queue_saturation{suffix}",
                      "serving_queue_examples", labels=labels,
                      threshold=queue_frac * queue_cap, op=">=",
                      mode="value", agg="max", for_seconds=for_seconds,
                      severity="ticket",
                      description="a batcher queue near its admission cap "
                                  "(queued EXAMPLES vs max_queue_examples "
                                  "— same unit as admission; worst single "
                                  "model, the cap is per-model)"),
        ThresholdRule(f"serving_reject_rate{suffix}",
                      "serving_requests_total",
                      labels={**(labels or {}), "outcome": "rejected"},
                      threshold=reject_rate_per_s, op=">", mode="rate",
                      window_s=windows[0], for_seconds=for_seconds,
                      severity="ticket",
                      description="sustained admission rejects (429s)"),
    ]


def default_training_rules(stall_after_s: float = 120.0,
                           for_seconds: float = DEFAULT_FOR_SECONDS
                           ) -> List[AlertRule]:
    return [
        HealthRule("training_stall", kind="stall",
                   stall_after_s=stall_after_s, for_seconds=for_seconds,
                   description="training iterations stopped arriving"),
        HealthRule("training_divergence", kind="problem",
                   problem_kinds=("nan", "divergence"),
                   for_seconds=for_seconds,
                   description="watchdog NaN/divergence problems present"),
    ]


def default_fleet_rules(for_seconds: float = DEFAULT_FOR_SECONDS
                        ) -> List[AlertRule]:
    return [
        FleetStalenessRule("fleet_worker_stale", for_seconds=for_seconds,
                           severity="ticket",
                           description="worker missed its telemetry "
                                       "interval on /fleet"),
    ]


def default_fleet_scope_rules(*, fleet=None, slo: float = 0.999,
                              burn_factor: float = 14.4,
                              windows: Sequence[float] = (60.0, 300.0),
                              p99_target_ms: float = 250.0,
                              per_label: str = "worker",
                              for_seconds: float = DEFAULT_FOR_SECONDS
                              ) -> List[AlertRule]:
    """The scrape-plane pack, evaluated against a history ring fed by
    :meth:`TelemetryCollector.fleet_dump` (where every series carries a
    ``worker=<label>`` re-label):

    - ``fleet_error_burn`` — error-budget burn on the SUM across
      replicas (one replica's 5xx storm burns the shared budget);
    - ``fleet_p99_worst_replica`` — windowed p99 per replica, rule
      reads the worst one (``per_label``), exemplar resolved from the
      guilty replica's scraped exemplar table;
    - ``fleet_target_down`` — any configured scrape target failing
      (min over ``fleet_target_up`` gauges below 1).
    """
    if fleet is None:
        from .fleet import get_fleet
        fleet = get_fleet()
    return [
        BurnRateRule("fleet_error_burn", kind="availability",
                     slo=slo, burn_factor=burn_factor, windows=windows,
                     for_seconds=for_seconds,
                     description="aggregate 5xx error-budget burn "
                                 "across scraped replicas"),
        BurnRateRule("fleet_p99_worst_replica", kind="latency",
                     target_ms=p99_target_ms, windows=windows,
                     per_label=per_label, for_seconds=for_seconds,
                     exemplar_lookup=lambda w: fleet.worst_exemplar(
                         "serving_request_latency_ms", w),
                     description="worst single replica's windowed p99 "
                                 "over target on both windows"),
        ThresholdRule("fleet_target_down", "fleet_target_up",
                      threshold=1.0, op="<", mode="value", agg="min",
                      for_seconds=for_seconds, severity="page",
                      description="a configured scrape target is not "
                                  "answering /telemetry"),
    ]


def default_probe_rules(prober=None, *, slo: float = 0.999,
                        burn_factor: float = 14.4,
                        windows: Sequence[float] = (60.0, 300.0),
                        p99_target_ms: float = 500.0,
                        deadman_s: float = 60.0,
                        for_seconds: float = DEFAULT_FOR_SECONDS
                        ) -> List[AlertRule]:
    """The probe-plane pack (attach to ``prober.engine``, which samples
    the registry where the probe SLIs land):

    - ``probe_availability_burn`` — error-budget burn over
      ``probe_requests_total`` where EVERY non-ok outcome is bad: a
      wrong answer (mismatch) burns the budget exactly like a 5xx;
    - ``probe_p99_client`` — client-observed windowed p99 per target
      (the latency the FRONT DOOR sees, network included), worst target
      read via ``per_label``;
    - ``probe_mismatch`` — ANY mismatch in the short window pages
      immediately: correctness has no error budget;
    - ``probe_deadman`` — ``probe_last_success_age_s`` over
      ``deadman_s``: only a CORRECT answer resets it, so a replica
      answering quickly but wrongly still trips it.

    ``prober`` (optional) wires breach-time annotations: mismatch and
    deadman breaches name the guilty target and carry the failing
    probe's own trace id — resolvable on that replica's ``/trace``."""
    ex = prober.last_failure_trace if prober is not None else None
    why = prober.failure_detail if prober is not None else None
    return [
        BurnRateRule("probe_availability_burn", kind="availability",
                     slo=slo, burn_factor=burn_factor, windows=windows,
                     total_metric="probe_requests_total",
                     bad_labels=[{"outcome": "error"},
                                 {"outcome": "timeout"},
                                 {"outcome": "mismatch"}],
                     for_seconds=for_seconds,
                     description="synthetic-probe error-budget burn "
                                 "(any non-ok outcome is bad)"),
        BurnRateRule("probe_p99_client", kind="latency",
                     latency_metric="probe_latency_ms",
                     target_ms=p99_target_ms, windows=windows,
                     per_label="target", for_seconds=for_seconds,
                     description="worst target's client-observed probe "
                                 "p99 over target on both windows"),
        ThresholdRule("probe_mismatch", "probe_requests_total",
                      threshold=0.0, op=">", mode="rate",
                      window_s=windows[0],
                      labels={"outcome": "mismatch"},
                      for_seconds=for_seconds, severity="page",
                      exemplar_lookup=ex, detail_lookup=why,
                      description="a probed replica returned an answer "
                                  "diverging from its golden set"),
        ThresholdRule("probe_deadman", "probe_last_success_age_s",
                      threshold=deadman_s, op=">", mode="value",
                      agg="max", for_seconds=for_seconds, severity="page",
                      exemplar_lookup=ex, detail_lookup=why,
                      description="a probe target has not answered "
                                  "correctly within the deadman window"),
    ]


def default_rules(*, stall_after_s: float = 120.0,
                  for_seconds: float = DEFAULT_FOR_SECONDS,
                  **serving_kw) -> List[AlertRule]:
    """Every shipped pack (serving aggregated across models + training +
    fleet) — the one-call setup for a monitored process. ``for_seconds``
    and ``stall_after_s`` apply across packs; the remaining keywords go
    to :func:`default_serving_rules`."""
    return (default_serving_rules(for_seconds=for_seconds, **serving_kw)
            + default_training_rules(stall_after_s=stall_after_s,
                                     for_seconds=for_seconds)
            + default_fleet_rules(for_seconds=for_seconds))


#: the process-global engine the endpoints/CLI serve — empty (no rules,
#: nothing evaluating) until someone adds rules and attaches/evaluates
_ENGINE = AlertEngine()


def get_alert_engine() -> AlertEngine:
    return _ENGINE
