"""jitwatch: compilation & device-memory observability for every jit.

The two dominant invisible costs on an XLA device are **recompilation**
(shape/dtype churn silently re-tracing a step — the classic "training
mysteriously 10x slower" failure) and **device memory** (donation and
sharding decisions live or die by peak HBM). Neither shows up in step
timings: a retrace storm just makes every step slow, and an OOM arrives
long after the allocation decisions that caused it. This module makes
both first-class monitor citizens:

- :func:`monitored_jit` — the package-wide replacement for bare
  ``jax.jit`` (tpulint rule JAX003 enforces the migration stays
  complete). Per named function it records compile count vs call count
  (cache-miss ratio), compile wall-time (``jit_compile_seconds``
  histogram + ``jit_compiles_total{fn=}`` / ``jit_calls_total{fn=}``
  series), a ``compile/<name>`` tracer span (compiles appear on
  ``/trace`` and the merged fleet trace, parented under the step span
  they interrupted), and on-compile ``cost_analysis`` capture (flops /
  bytes / peak memory per compiled variant, via ``compat.cost_analysis``
  — the same numbers ``utils.profiling.step_cost`` reports).
- the **retrace-storm detector**: ``RETRACE_THRESHOLD`` compiles of the
  same wrapper within ``RETRACE_WINDOW`` seconds records a health
  problem and a ``retrace_storm`` flight-recorder event naming the
  function and the argument-signature delta that triggered the retrace
  (the runbook: read the delta, pad/bucket your batch shapes —
  docs/OBSERVABILITY.md "Compilation & memory"). ``TrainingHealthListener``
  drains :meth:`JitRegistry.drain_storms` per iteration to apply its
  warn/raise/halt action.
- :func:`sample_device_memory` — ``device_memory_in_use_bytes{device=}``
  / ``device_memory_peak_bytes{device=}`` / ``device_live_buffers``
  gauges, sampled on every ``/metrics`` scrape and at step-span close,
  degrading gracefully on backends without memory stats (CPU's
  ``memory_stats()`` is None; the live-buffer count still works).
- :func:`profile_report` — the step-anatomy view behind ``GET /profile``
  and ``monitor --profile``: the per-fn jit table, the memory gauges,
  and the step/ETL timing split merged into one JSON+text report.

Hot-path cost per monitored call: two counter increments, two
``perf_counter`` reads, and one C++-side jit-cache-size probe — all the
expensive work (signatures, spans) happens only on a compile, which is
already a multi-ms event, and the cost_analysis re-lower runs on a
background worker thread so it never extends the training call that
triggered the compile.
"""
from __future__ import annotations

import functools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..compilecache.cache import (claim_persistent_hit as _cc_claim_hit,
                                  enabled as _cc_enabled,
                                  hits_count as _cc_hits_count)

log = logging.getLogger(__name__)

__all__ = ["monitored_jit", "MonitoredJit", "JitRegistry",
           "get_jit_registry", "sample_device_memory",
           "maybe_sample_device_memory", "wait_cost_captures",
           "profile_report", "render_profile_text",
           "RETRACE_THRESHOLD", "RETRACE_WINDOW"]

#: compiles of ONE wrapper instance within RETRACE_WINDOW seconds that
#: count as a retrace storm. Per instance, not per name: fifty networks
#: each compiling their own "mln/step" once is healthy; one network
#: compiling its step three times in a minute is shape churn.
RETRACE_THRESHOLD = int(os.environ.get("DL4J_TPU_RETRACE_THRESHOLD", "3"))
RETRACE_WINDOW = float(os.environ.get("DL4J_TPU_RETRACE_WINDOW", "60"))

#: "0" skips the on-compile cost_analysis capture (it re-lowers the
#: function abstractly — cheap next to the compile it annotates, but not
#: free on very large graphs)
_COST_CAPTURE = os.environ.get("DL4J_TPU_JITWATCH_COST", "1") \
    not in ("0", "false", "")


# ------------------------------------------------------------- signatures
def _leaf_sig(x) -> str:
    """One leaf's cache identity: ``f32[16,4]`` for array-likes (shape
    metadata survives buffer donation — only the data is freed), repr for
    static/python leaves."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            return f"{dtype.name}[{','.join(str(int(d)) for d in shape)}]"
        # exotic dtype/shape objects (symbolic dims, custom dtypes):
        # the repr fallback below IS the answer, nothing to log
        except Exception:  # tpulint: disable=EXC001
            pass
    r = repr(x)
    return r if len(r) <= 40 else r[:37] + "..."


def _signature(args, kwargs) -> Tuple[Tuple[Tuple[str, str], ...], str]:
    """((keypath, leaf-sig), ...) plus the treedef repr — the abstract
    identity jax's jit cache keys on, path-labeled so a retrace delta can
    name the argument that changed."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten_with_path((args,
                                                            dict(kwargs)))
    sig = tuple((jax.tree_util.keystr(kp), _leaf_sig(leaf))
                for kp, leaf in leaves)
    return sig, str(treedef)


def _sig_delta(old, new) -> str:
    """Human-readable diff between two signatures: WHICH arguments changed
    shape/dtype (the retrace-storm runbook's first question)."""
    if old is None:
        return "first compile"
    o, n = dict(old[0]), dict(new[0])
    diffs = [f"{k}: {o[k]} -> {n[k]}" for k in n if k in o and o[k] != n[k]]
    added = [k for k in n if k not in o]
    removed = [k for k in o if k not in n]
    if added:
        diffs.append(f"+{len(added)} new leaves ({added[0]}, ...)"
                     if len(added) > 1 else f"new leaf {added[0]}")
    if removed:
        diffs.append(f"-{len(removed)} leaves")
    if not diffs:
        return ("tree structure changed" if old[1] != new[1]
                else "signature unchanged (static-argument retrace)")
    head = "; ".join(diffs[:4])
    if len(diffs) > 4:
        head += f" (+{len(diffs) - 4} more)"
    return head


def _abstractify(x):
    """Array-likes → ShapeDtypeStruct for a data-free re-lower (donated
    inputs are already dead by the time a compile is detected); python
    scalars and other statics pass through concretely."""
    import jax
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return x


# ------------------------------------------------------------- registry
class _FnStats:
    """Per-NAME aggregate (instances of the same named fn pool here)."""

    __slots__ = ("name", "compiles", "compile_seconds", "variants",
                 "last_cost", "last_delta", "storms", "persistent_hits")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.compile_seconds = 0.0
        self.variants: Dict[str, Dict[str, Any]] = {}
        self.last_cost: Optional[Dict[str, float]] = None
        self.last_delta: Optional[str] = None
        self.storms = 0
        #: compiles of this fn that the persistent on-disk cache served
        #: (compilecache/ — still in-process jit-cache misses, but disk
        #: reads rather than XLA work; the hit/miss split keeps the
        #: bimodal jit_compile_seconds distribution honest)
        self.persistent_hits = 0


class JitRegistry:
    """Process-global table of monitored jit functions: per-fn compile /
    call / cost aggregates (:meth:`table` is the ``/profile`` jit block)
    and the pending retrace-storm queue ``TrainingHealthListener`` drains
    to apply its action."""

    def __init__(self):
        from .lockwatch import make_lock
        self._lock = make_lock("JitRegistry._lock")
        self._stats: Dict[str, _FnStats] = {}
        self._pending_storms: List[Dict[str, Any]] = []

    def stats(self, name: str) -> _FnStats:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _FnStats(name)
            return st

    def note_compile(self, name: str, seconds: float, sig_key: str,
                     delta: str, persistent_hit: bool = False):
        st = self.stats(name)
        with self._lock:
            st.compiles += 1
            st.compile_seconds += seconds
            st.last_delta = delta
            if persistent_hit:
                st.persistent_hits += 1
            var = st.variants.setdefault(sig_key, {"compiles": 0})
            var["compiles"] += 1
            var["compile_seconds"] = round(
                var.get("compile_seconds", 0.0) + seconds, 4)

    def note_cost(self, name: str, sig_key: str,
                  cost: Dict[str, float]):
        """Landing point for the async cost worker (may arrive any time
        after the compile it describes)."""
        st = self.stats(name)
        with self._lock:
            var = st.variants.setdefault(sig_key, {"compiles": 0})
            var["cost"] = cost
            st.last_cost = cost

    def report_storm(self, name: str, count: int, delta: str):
        msg = (f"retrace storm: jit fn {name!r} compiled {count} times "
               f"within {RETRACE_WINDOW:.0f}s — argument-signature churn "
               f"({delta}); pad or bucket the offending shapes "
               f"(docs/OBSERVABILITY.md, 'Compilation & memory')")
        # thread affinity: detection runs synchronously inside the
        # training call, on the fit thread — the listener driving THAT
        # fit runs iteration_done on the same thread, so "thread" lets
        # it act only on its own model's storms (health.py)
        info = {"t": time.time(), "fn": name, "count": count,
                "window_s": RETRACE_WINDOW, "signature_delta": delta,
                "message": msg, "thread": threading.get_ident()}
        with self._lock:
            self._stats.setdefault(name, _FnStats(name)).storms += 1
            self._pending_storms.append(info)
            del self._pending_storms[:-32]    # bounded, newest win
        log.warning("jitwatch %s", msg)
        # flight recorder first (the delta is the forensic payload), then
        # the health problem (visible on /healthz without any listener)
        from .flightrec import get_flight_recorder
        get_flight_recorder().record("retrace_storm", fn=name, count=count,
                                     window_s=RETRACE_WINDOW,
                                     signature_delta=delta)
        from .health import get_health
        get_health().record_problem("retrace", msg)

    def drain_storms(self) -> List[Dict[str, Any]]:
        """Pop the pending storms (listener action seam)."""
        with self._lock:
            out, self._pending_storms = self._pending_storms, []
        return out

    def requeue_storms(self, storms: List[Dict[str, Any]]):
        """Put drained storms back (a listener drained storms belonging
        to ANOTHER fit thread — its own listener must still see them).
        Original timestamps are kept, so arm-time filtering and the
        bounded queue still expire them."""
        if not storms:
            return
        with self._lock:
            self._pending_storms.extend(storms)
            del self._pending_storms[:-32]

    def table(self) -> Dict[str, Dict[str, Any]]:
        """{name: {calls, compiles, cache_miss_ratio, compile_seconds,
        variants, flops, bytes_accessed, peak_memory_bytes, ...}} — the
        jit block of the step-anatomy report."""
        from .registry import get_registry
        # read through the snapshot, never through handle lookups: a
        # /profile scrape must not materialize empty children for fns
        # that never ran (the lazy-handles principle, _metric_handles)
        snap = get_registry().snapshot()

        def fn_row(metric, name):
            for r in snap.get(metric, []):
                if r["labels"].get("fn") == name:
                    return r
            return None

        with self._lock:
            stats = list(self._stats.items())
        out: Dict[str, Dict[str, Any]] = {}
        for name, st in sorted(stats):
            calls_row = fn_row("jit_calls_total", name)
            calls = int(calls_row["value"]) if calls_row else 0
            row: Dict[str, Any] = {
                "calls": calls,
                "compiles": st.compiles,
                "cache_miss_ratio": (round(st.compiles / calls, 4)
                                     if calls else None),
                "compile_seconds": round(st.compile_seconds, 4),
                "variants": len(st.variants),
                "storms": st.storms,
                # the hit/miss split (compilecache/): of `compiles`, how
                # many were disk-cache hits vs true XLA compiles — a
                # fleet warm from a shared cache dir shows compiles ==
                # persistent_cache_hits and near-zero compile_seconds
                "persistent_cache_hits": st.persistent_hits,
                "true_compiles": st.compiles - st.persistent_hits,
            }
            cs_row = fn_row("jit_compile_seconds", name)
            cs = cs_row.get("summary") if cs_row else None
            if cs:
                # honest per-fn compile-latency quantiles: the histogram
                # rides the unit="s" bucket geometry (sub-100ms compiles
                # no longer collapse into one bucket)
                row["compile_s"] = {k: round(v, 4)
                                    for k, v in cs.items()}
            if st.last_cost:
                row.update(st.last_cost)
            if st.last_delta:
                row["last_signature_delta"] = st.last_delta
            out[name] = row
        return out

    def clear(self):
        with self._lock:
            self._stats.clear()
            self._pending_storms.clear()


_JIT_REGISTRY = JitRegistry()


def get_jit_registry() -> JitRegistry:
    return _JIT_REGISTRY


# -------------------------------------------------------------- wrapper
class MonitoredJit:
    """``jax.jit`` plus the bookkeeping above. Calls pass straight
    through; compile detection is a jit-cache-size delta (falling back to
    a shadow signature set on jax builds without ``_cache_size``), so the
    compiled path pays no tracing, hashing, or locking beyond two counter
    bumps."""

    def __init__(self, fn, name: Optional[str] = None, **jit_kwargs):
        import jax
        self._fn = fn
        self.name = name or getattr(fn, "__qualname__",
                                    getattr(fn, "__name__", "jit_fn"))
        from .lockwatch import make_lock
        self._jit = jax.jit(fn, **jit_kwargs)
        self._lock = make_lock("MonitoredJit._lock")
        self.calls = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        self._last_sig = None
        self._seen_sigs = set()           # fallback-mode shadow cache
        self._seen_cache_size = 0         # compiles claimed so far
        self._compile_times = deque(maxlen=max(RETRACE_THRESHOLD, 8))
        self._handles = None
        self._phit_handle = None      # jit_persistent_cache_hits_total —
                                      # created on the FIRST disk hit only
                                      # (lazy-handles principle: processes
                                      # without the cache never materialize
                                      # the series)
        self._lowerings: Dict[Any, Any] = {}   # cached_lowering memo
        self._has_cache_size = hasattr(self._jit, "_cache_size")
        functools.update_wrapper(self, fn, updated=())

    def _metric_handles(self):
        # lazy: importing a module full of decorated steps must not
        # populate /metrics with never-called fn labels
        if self._handles is None:
            from .registry import get_registry
            reg = get_registry()
            self._handles = (
                reg.counter("jit_calls_total",
                            "calls into monitored jit functions",
                            fn=self.name),
                reg.counter("jit_compiles_total",
                            "XLA compilations (jit cache misses)",
                            fn=self.name),
                reg.histogram("jit_compile_seconds",
                              "wall-clock seconds per jit compilation "
                              "(trace+compile, first-call latency)",
                              unit="s", fn=self.name),
            )
        return self._handles

    def __call__(self, *args, **kwargs):
        calls_c, compiles_c, hist = self._metric_handles()
        calls_c.inc()
        with self._lock:
            self.calls += 1
        # persistent-cache attribution window (compilecache/): snapshot
        # the disk-hit counter before the call so a detected compile can
        # be classified hit-vs-miss precisely. Both reads are lock-free
        # (flag + GIL-atomic int) — cache on or off, the hot path takes
        # no lock for this
        phits0 = _cc_hits_count() if _cc_enabled() else None
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        dur = time.perf_counter() - t0
        if self._has_cache_size:
            # claim-the-delta: N threads racing through one compile all
            # observe the same grown cache, but only the first to take
            # the lock claims it — no double-counted compiles, no
            # spurious retrace storm from a thread pile-up, and only
            # the claimer's wall-time lands in the histogram
            compiled = False
            after = self._jit._cache_size()
            if after > self._seen_cache_size:
                with self._lock:
                    if after > self._seen_cache_size:
                        self._seen_cache_size = after
                        compiled = True
            sig = None
        else:
            sig = self._safe_signature(args, kwargs)
            key = sig[0] if sig else None
            with self._lock:
                compiled = key not in self._seen_sigs
                self._seen_sigs.add(key)
        if compiled:
            try:
                phit = (phits0 is not None
                        and _cc_claim_hit(phits0))
                self._record_compile(args, kwargs, t0, dur, sig,
                                     compiles_c, hist, phit)
            except Exception as e:
                # observability must never fail the training step it
                # observes — degrade to the bare counters
                log.debug("jitwatch: compile bookkeeping for %s failed: %r",
                          self.name, e)
        return out

    def _safe_signature(self, args, kwargs):
        try:
            return _signature(args, kwargs)
        except Exception as e:
            log.debug("jitwatch: signature of %s failed: %r", self.name, e)
            return None

    def _record_compile(self, args, kwargs, t0, dur, sig, compiles_c, hist,
                        phit: bool = False):
        if sig is None:
            sig = self._safe_signature(args, kwargs)
        compiles_c.inc()
        hist.observe(dur)          # seconds (the metric name carries units)
        if phit:
            # this "compile" was a persistent-cache disk read, not XLA
            # work (claimed in __call__ against the pre-call hit window)
            if self._phit_handle is None:
                from .registry import get_registry
                self._phit_handle = get_registry().counter(
                    "jit_persistent_cache_hits_total",
                    "jit compiles served from the persistent on-disk "
                    "compile cache (disk reads, not XLA compiles)",
                    fn=self.name)
            self._phit_handle.inc()
        delta = _sig_delta(self._last_sig, sig) if sig else "unknown"
        now = time.time()
        with self._lock:
            self.compiles += 1
            self.compile_seconds += dur
            self._last_sig = sig
            self._compile_times.append(now)
            recent = [t for t in self._compile_times
                      if now - t <= RETRACE_WINDOW]
            storm = len(recent) >= RETRACE_THRESHOLD
            if storm:
                self._compile_times.clear()   # re-arm: a sustained storm
                                              # re-fires every N compiles
        # the compile happened inside whatever span is open on this thread
        # (usually the step span), so parent it there — step anatomy shows
        # the compile eating the step it interrupted
        from .tracer import get_tracer
        get_tracer().record_complete(f"compile/{self.name}", t0, dur,
                                     cat="compile", fn=self.name,
                                     signature_delta=delta)
        sig_key = ";".join(f"{k}={v}" for k, v in sig[0]) if sig else "?"
        reg = get_jit_registry()
        reg.note_compile(self.name, dur, sig_key, delta,
                         persistent_hit=phit)
        if _COST_CAPTURE:
            _submit_cost_capture(self._jit, self.name, sig_key,
                                 args, kwargs)
        if storm:
            reg.report_storm(self.name, len(recent), delta)

    # ------------------------------------------------- jit API passthrough
    def lower(self, *args, **kwargs):
        """AOT lowering passthrough (``utils.profiling.step_cost`` seam)."""
        return self._jit.lower(*args, **kwargs)

    def cached_lowering(self, *args, **kwargs):
        """:meth:`lower`, memoized by abstract argument signature.

        ``jax.jit.lower`` re-TRACES on every call even when the same
        signature's executable is already compiled — fine for a one-off
        export, wasteful for repeated cost analysis over the same shapes
        (``utils.profiling.step_cost`` used to pay a full second trace
        per call). Bounded memo (the signature set of any analysis
        caller is tiny); falls through to a live lower when the
        signature cannot be computed."""
        sig = self._safe_signature(args, kwargs)
        key = sig[0] if sig else None
        if key is not None:
            with self._lock:
                got = self._lowerings.get(key)
            if got is not None:
                return got
        lowered = self._jit.lower(*args, **kwargs)
        if key is not None:
            with self._lock:
                self._lowerings[key] = lowered
                while len(self._lowerings) > 16:   # bounded, oldest out
                    self._lowerings.pop(next(iter(self._lowerings)))
        return lowered

    @property
    def cache_miss_ratio(self) -> Optional[float]:
        with self._lock:
            return self.compiles / self.calls if self.calls else None

    def __repr__(self):
        return (f"MonitoredJit({self.name!r}, calls={self.calls}, "
                f"compiles={self.compiles})")


def monitored_jit(fn=None, name: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` with compile observability (see module docstring).

    Use exactly like ``jax.jit`` — ``monitored_jit(step, name="mln/step",
    donate_argnums=(0, 2))`` — or as a decorator factory::

        @monitored_jit(name="nlp/hs_step", donate_argnums=(0, 1))
        def _hs_step(...): ...

    ``name`` labels every metric/span/flight event; it defaults to the
    function's qualname but SHOULD be set to a stable ``area/fn`` slug so
    dashboards survive refactors.
    """
    if fn is None:
        return functools.partial(monitored_jit, name=name, **jit_kwargs)
    return MonitoredJit(fn, name=name, **jit_kwargs)


# ---------------------------------------------------- async cost capture
# Single-thread ThreadPoolExecutor, NOT a bare daemon thread: a daemon
# thread mid-XLA-compile when the interpreter finalizes aborts the whole
# process ("terminate called without an active exception" — seen in the
# multiprocess worker tests). Executor threads are JOINED at interpreter
# shutdown; _cancel_pending_captures (registered BEFORE the executor
# module's own shutdown hook) cancels not-yet-started captures first, so
# exit waits only for the one in-flight compile, never the whole queue.
_COST_WORKER_LOCK = threading.Lock()
_COST_EXECUTOR = None
_COST_FUTURES: deque = deque()
_COST_MAX_PENDING = 16
_COST_SHUTDOWN = False


def _cancel_pending_captures():
    global _COST_SHUTDOWN
    _COST_SHUTDOWN = True
    with _COST_WORKER_LOCK:
        futures = list(_COST_FUTURES)
        _COST_FUTURES.clear()
    for f in futures:
        f.cancel()


def _ensure_cost_executor():
    global _COST_EXECUTOR
    with _COST_WORKER_LOCK:
        if _COST_EXECUTOR is None:
            # import (and thereby let concurrent.futures install its
            # join-at-shutdown hook) FIRST, then register our canceller:
            # threading._shutdown runs _threading_atexits in REVERSED
            # registration order, so the later-registered canceller runs
            # before the executor's join — pending captures are cancelled
            # and exit waits only for the one in-flight compile
            from concurrent.futures import ThreadPoolExecutor
            # never shutdown() explicitly BY DESIGN: concurrent.futures
            # joins this worker at interpreter exit, and the canceller
            # registered below trims the queue first — see the comment
            # block above (a daemon thread here SIGABRTs mid-compile)
            _COST_EXECUTOR = ThreadPoolExecutor(  # tpulint: disable=RES001
                max_workers=1, thread_name_prefix="jitwatch-cost")
            try:
                threading._register_atexit(_cancel_pending_captures)
            # private API absent (older python): the atexit fallback below
            # IS the handling — exit then waits for queued captures too
            except Exception:  # tpulint: disable=EXC001
                import atexit
                atexit.register(_cancel_pending_captures)
        return _COST_EXECUTOR


def _submit_cost_capture(jitted, name: str, sig_key: str, args, kwargs):
    """Queue an XLA cost_analysis capture for the variant just compiled.
    The abstract signature (ShapeDtypeStructs — no data, donation-safe) is
    built eagerly on the calling thread; the expensive lower+compile runs
    on the worker, so cost capture never extends the training call that
    triggered the compile. Bounded: a retrace storm must not queue
    unbounded recompilation work — overflow drops the capture (the compile
    counters/spans already landed)."""
    if _COST_SHUTDOWN:
        return
    try:
        import jax
        a_args, a_kwargs = jax.tree_util.tree_map(_abstractify,
                                                  (args, dict(kwargs)))
    except Exception as e:
        log.debug("jitwatch: abstractify for %s failed: %r", name, e)
        return
    ex = _ensure_cost_executor()
    with _COST_WORKER_LOCK:
        while _COST_FUTURES and _COST_FUTURES[0].done():
            _COST_FUTURES.popleft()
        if len(_COST_FUTURES) >= _COST_MAX_PENDING:
            log.debug("jitwatch: cost queue full, dropping capture for %s",
                      name)
            return
    try:
        fut = ex.submit(_capture_cost_task, jitted, name, sig_key,
                        a_args, a_kwargs)
    except RuntimeError:      # executor already shut down (interpreter exit)
        return
    with _COST_WORKER_LOCK:
        _COST_FUTURES.append(fut)


def _capture_cost_task(jitted, name, sig_key, a_args, a_kwargs):
    try:
        # the abstract re-lower below re-compiles the variant that just
        # compiled — with the persistent cache on, that is a guaranteed
        # disk hit, and it must not enter the hit-attribution pool (a
        # FOREGROUND compile racing this worker would claim it and read
        # as a disk hit it never had) — compilecache.suppress_events
        from ..compilecache.cache import suppress_events
        with suppress_events():
            _capture_cost_now(jitted, name, sig_key, a_args, a_kwargs)
    except Exception as e:
        log.debug("jitwatch: cost capture for %s failed: %r", name, e)


def _capture_cost_now(jitted, name: str, sig_key: str, a_args, a_kwargs):
    """Worker body: abstract re-lower + compile + cost_analysis /
    memory_analysis. Best-effort by contract — sharded/exotic signatures
    that refuse the abstract re-lower simply report no cost."""
    compiled = jitted.lower(*a_args, **a_kwargs).compile()
    from ..compat import cost_analysis
    ca = cost_analysis(compiled)
    cost = {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    try:
        ma = compiled.memory_analysis()
        peak = sum(float(getattr(ma, k, 0) or 0)
                   for k in ("temp_size_in_bytes",
                             "argument_size_in_bytes",
                             "output_size_in_bytes"))
        if peak:
            cost["peak_memory_bytes"] = peak
    # older jax builds lack Compiled.memory_analysis — the flops/bytes
    # cost block above is still the full answer
    except Exception:  # tpulint: disable=EXC001
        pass
    get_jit_registry().note_cost(name, sig_key, cost)


def wait_cost_captures(timeout: float = 10.0) -> bool:
    """Block until every queued cost capture has landed (tests and
    snapshot-then-exit CLI paths want deterministic flops). Returns False
    on timeout — the report is then merely missing its newest cost rows."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with _COST_WORKER_LOCK:
            pending = [f for f in _COST_FUTURES if not f.done()]
        if not pending:
            return True
        time.sleep(0.01)
    return False


# --------------------------------------------------------- device memory
def sample_device_memory(registry=None) -> Dict[str, Any]:
    """Sample per-device allocator stats + the process live-buffer count
    into gauges; returns the same data as a dict (the ``/profile`` memory
    block). Backends without ``memory_stats()`` (CPU) just skip the byte
    gauges — the sampler never raises."""
    out: Dict[str, Any] = {"devices": {}, "live_buffers": None}
    try:
        import jax
        from .registry import get_registry
        reg = registry if registry is not None else get_registry()
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            # documented graceful degradation: backends without
            # allocator stats (CPU) skip the byte gauges entirely
            except Exception:  # tpulint: disable=EXC001
                stats = None
            if not stats:
                continue
            dev = f"{d.platform}:{d.id}"
            row = out["devices"].setdefault(dev, {})
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                reg.gauge("device_memory_in_use_bytes",
                          "device bytes currently allocated",
                          device=dev).set(float(in_use))
                row["bytes_in_use"] = int(in_use)
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                reg.gauge("device_memory_peak_bytes",
                          "peak device bytes over the process lifetime",
                          device=dev).set(float(peak))
                row["peak_bytes_in_use"] = int(peak)
            limit = stats.get("bytes_limit")
            if limit:
                row["bytes_limit"] = int(limit)
        n = len(jax.live_arrays())
        reg.gauge("device_live_buffers",
                  "live jax arrays held by this process").set(float(n))
        out["live_buffers"] = n
    except Exception as e:
        log.debug("jitwatch: device memory sample failed: %r", e)
    return out


#: per-step sampling throttle (seconds): the fit loops sample at step-span
#: close, but jax.live_arrays() is O(live buffers) — once a second is
#: plenty for a gauge and keeps the hot loop honest
_SAMPLE_INTERVAL = float(os.environ.get("DL4J_TPU_MEMSAMPLE_INTERVAL", "1.0"))
_LAST_SAMPLE = [0.0]


def maybe_sample_device_memory():
    """Throttled :func:`sample_device_memory` for per-step call sites: at
    most one sample per ``DL4J_TPU_MEMSAMPLE_INTERVAL`` seconds (default
    1.0; scrape-time sampling on ``/metrics`` stays unthrottled)."""
    now = time.monotonic()
    if now - _LAST_SAMPLE[0] < _SAMPLE_INTERVAL:
        return
    _LAST_SAMPLE[0] = now
    sample_device_memory()


# ----------------------------------------------------------- step anatomy
def _snap_value(snap, metric) -> Optional[float]:
    """Sum of a snapshot family's scalar children (None when absent)."""
    rows = snap.get(metric, [])
    return sum(r.get("value", 0) for r in rows) if rows else None


def _snap_summary(snap, metric) -> Optional[Dict[str, float]]:
    """First child's histogram summary from a snapshot (None when absent)."""
    rows = snap.get(metric, [])
    return rows[0].get("summary") if rows else None


def profile_report() -> Dict[str, Any]:
    """The step-anatomy report (``GET /profile`` / ``monitor --profile``):
    per-fn jit table + device memory + the step/ETL timing split, merged
    from the monitor registry — one view answering "where does a step's
    wall-clock actually go: compute, compile, or ETL?"."""
    from .registry import get_registry
    snap = get_registry().snapshot()

    def value(metric):
        return _snap_value(snap, metric)

    def summary(metric):
        return _snap_summary(snap, metric)

    return {
        "jit": get_jit_registry().table(),
        "memory": sample_device_memory(),
        "steps": {
            "iterations": value("training_iterations_total"),
            "examples": value("training_examples_total"),
            "step_ms": summary("training_step_ms"),
            "etl_ms": summary("training_etl_ms"),
        },
        "pipeline": _pipeline_block(snap),
        "training": _training_block(snap),
        "serving": _serving_block(snap),
        "mesh": _mesh_block(),
        "locks": _locks_block(),
        "control": _control_block(),
        "trends": _trends_block(),
    }


def _mesh_block() -> Dict[str, Any]:
    """Active parallel topologies (parallel/mesh.py registry): per style
    the mesh axis names/extents, device count, steps built, and
    sharded-vs-replicated model-state leaf counts — what topology is this
    process's training/inference actually running on. Read through
    sys.modules so a process that never imported the parallel substrate
    pays nothing (and reports an honest empty block)."""
    import sys as _sys
    mod = _sys.modules.get("deeplearning4j_tpu.parallel.mesh")
    if mod is None:
        return {}
    try:
        return mod.mesh_block()
    except Exception as e:      # pragma: no cover - defensive scrape path
        log.debug("jitwatch: mesh block failed: %r", e)
        return {}


def _control_block() -> Dict[str, Any]:
    """Control-plane summary (control/plane.py): policy count, active
    cooldowns, total actions, last action. Read through sys.modules like
    the mesh block — a process that never imported the control plane
    pays nothing and reports an honest empty block."""
    import sys as _sys
    mod = _sys.modules.get("deeplearning4j_tpu.control.plane")
    if mod is None:
        return {}
    try:
        return mod.control_block()
    except Exception as e:      # pragma: no cover - defensive scrape path
        log.debug("jitwatch: control block failed: %r", e)
        return {}


#: the trends block's comparison horizons (seconds): "now vs 1m vs 5m"
_TREND_WINDOWS = (60.0, 300.0)


def _trends_block() -> Dict[str, Any]:
    """Now-vs-1m-vs-5m movement of the load-bearing series, read from the
    metric history ring (monitor/history.py). Empty until the history
    sampler has at least two samples — the block answers "is it getting
    WORSE", which a single snapshot cannot. Gauges compare the current
    value against the value at each horizon; counters report the delta
    over each horizon; latency reports the WINDOWED p99 (bucket-count
    deltas — only the samples inside the window); memory peak reports the
    windowed max."""
    from .history import get_history
    hist = get_history()
    if len(hist) < 2:
        return {}

    def tol(w):
        # honesty guard: a value only counts as "w seconds ago" when a
        # sample landed within a quarter-window (or a couple of sampler
        # intervals) of that horizon — a 15s-old ring must answer the
        # 5m question with None, never with a 15s-old value mislabeled
        return max(w * 0.25, 2 * hist.interval_s)

    def covers(w):
        # windowed math only when the window is actually covered (the
        # shared MetricsHistory.covers guard — the alert engine applies
        # the same one to its burn-rate windows)
        return hist.covers(w, tolerance_s=tol(w))

    def ago(metric, w):
        at = hist.at_age(w, tolerance_s=tol(w))
        return hist.value_of(at[1], metric) if at else None

    def gauge_row(metric):
        row = {"now": hist.current(metric)}
        for w in _TREND_WINDOWS:
            row[f"{w:g}s_ago"] = ago(metric, w)
        return row

    def delta_row(metric):
        row = {"total": hist.current(metric)}
        for w in _TREND_WINDOWS:
            row[f"{w:g}s_delta"] = (hist.delta(metric, w)
                                    if covers(w) else None)
        return row

    p99 = {}
    for w in _TREND_WINDOWS:
        p99[f"{w:g}s_p99_ms"] = (hist.quantile_over(
            "serving_request_latency_ms", 0.99, w) if covers(w) else None)
    peak = {"now": hist.current("device_memory_peak_bytes")}
    for w in _TREND_WINDOWS:
        peak[f"{w:g}s_max"] = (hist.max_over("device_memory_peak_bytes", w)
                               if covers(w) else None)
    return {
        "window_s": list(_TREND_WINDOWS),
        "serving_qps": gauge_row("serving_qps"),
        "serving_p99_ms": p99,
        "serving_queue_depth": gauge_row("serving_queue_depth"),
        "jit_compiles": delta_row("jit_compiles_total"),
        "device_memory_peak_bytes": peak,
    }


def _locks_block() -> Dict[str, Any]:
    """Lock-contention table (monitor/lockwatch.py): per instrumented lock
    the acquisition count and exact wait/held mean/max, plus the observed
    inversion count. Empty unless lockwatch is enabled
    (``DL4J_TPU_LOCKWATCH=1``) and instrumented locks actually ran."""
    from .lockwatch import contention_table
    return contention_table()


def _serving_block(snap) -> Dict[str, Any]:
    """Per-model serving anatomy (serving/ tier, docs/SERVING.md): request
    outcomes, latency summary (p50/p95/p99/max — the serving histograms
    are ms-valued, so bucket quantiles are honest here), trailing-window
    QPS, batch-size distribution (mean real examples per flush — how well
    continuous batching is coalescing), and current queue depth. Built
    purely from the registry snapshot, so the block also renders for a
    remote dump. Empty dict until serving traffic flows."""
    per: Dict[str, Dict[str, Any]] = {}

    def row(model):
        return per.setdefault(model, {})

    for r in snap.get("serving_requests_total", []):
        m = r["labels"].get("model", "?")
        row(m).setdefault("requests", {})[
            r["labels"].get("outcome", "?")] = r.get("value")
    for r in snap.get("serving_request_latency_ms", []):
        m = r["labels"].get("model", "?")
        if r.get("summary"):
            row(m)["latency_ms"] = r["summary"]
    for r in snap.get("serving_batch_examples", []):
        m = r["labels"].get("model", "?")
        s = r.get("summary")
        if s:
            # the histogram stores EXAMPLE COUNTS in its value slots, so
            # mean/max/n are exact; its bucket quantiles are not
            # meaningful for counts and are dropped
            row(m)["batch_examples"] = {"mean": round(s["mean_ms"], 2),
                                        "max": s["max_ms"],
                                        "n": int(s["n"])}
    for fam, key in (("serving_queue_depth", "queue_depth"),
                     ("serving_qps", "qps")):
        for r in snap.get(fam, []):
            row(r["labels"].get("model", "?"))[key] = r.get("value")
    for fam, key in (("serving_pad_ms", "pad_ms"),
                     ("serving_transfer_ms", "transfer_ms")):
        # the ISSUE-11 flush-time split: batch assembly vs host<->device
        # movement, per flush — read next to latency_ms to see how much
        # of the tail is data plane rather than compute
        for r in snap.get(fam, []):
            if r.get("summary"):
                row(r["labels"].get("model", "?"))[key] = {
                    "mean": round(r["summary"]["mean_ms"], 4),
                    "p99": r["summary"]["p99_ms"],
                    "n": int(r["summary"]["n"])}
    hits: Dict[str, float] = {}
    misses: Dict[str, float] = {}
    for fam, acc in (("serving_cache_hits_total", hits),
                     ("serving_cache_misses_total", misses)):
        for r in snap.get(fam, []):
            acc[r["labels"].get("model", "?")] = r.get("value") or 0.0
    for m in set(hits) | set(misses):
        h, miss = hits.get(m, 0.0), misses.get(m, 0.0)
        row(m)["cache"] = {
            "hits": int(h), "misses": int(miss),
            "hit_rate": (round(h / (h + miss), 4) if h + miss else None)}
    return per


def _training_block(snap) -> Dict[str, Any]:
    """Paramserver hot-loop phase anatomy (paramserver/training.py +
    overlap.py): per-phase latency summaries (compute / d2h / encode /
    push), the wall step time, and whether the latency-hiding comms
    pipeline is on. ``hidden_ms_total`` is Σ phase totals − wall total —
    positive means comms genuinely ran UNDER the compute (real overlap),
    while the sync loop reads at or below zero (phases stack end to
    end). Empty until a paramserver master has stepped."""
    phases: Dict[str, Any] = {}
    phase_total = 0.0
    for r in snap.get("train_step_phase_ms", []):
        s = r.get("summary")
        if not s:
            continue
        phases[r["labels"].get("phase", "?")] = {
            "mean": round(s["mean_ms"], 3), "p95": s["p95_ms"],
            "max": s["max_ms"], "n": int(s["n"])}
        phase_total += s["mean_ms"] * s["n"]
    if not phases:
        return {}
    out: Dict[str, Any] = {"phase_ms": phases,
                           "phase_ms_total": round(phase_total, 3)}
    wall = _snap_summary(snap, "train_step_wall_ms")
    if wall:
        wall_total = wall["mean_ms"] * wall["n"]
        out["wall_ms"] = {"mean": round(wall["mean_ms"], 3),
                          "p95": wall["p95_ms"], "max": wall["max_ms"],
                          "n": int(wall["n"])}
        out["wall_ms_total"] = round(wall_total, 3)
        out["hidden_ms_total"] = round(phase_total - wall_total, 3)
    ov = _snap_value(snap, "train_overlap_active")
    out["overlap_active"] = bool(ov)
    return out


def _pipeline_block(snap) -> Dict[str, Any]:
    """Input-pipeline anatomy (datasets/prefetch.py): queue depth, the
    residual blocking wait, bytes fed, and the compute/ETL overlap split —
    ``etl_fraction`` near 0 means prefetch+put-ahead hid the ETL behind
    device compute; near 1 means the accelerator starves on input."""
    # input_wait_seconds rides the unit="s" bucket geometry (PR 10), so
    # its p50/p95 are honest bucket quantiles now — the PR-6 exact-only
    # workaround (mean/max) is superseded
    w = _snap_summary(snap, "input_wait_seconds")
    out: Dict[str, Any] = {
        "queue_depth": _snap_value(snap, "input_queue_depth"),
        "batches": _snap_value(snap, "input_batches_total"),
        "bytes_total": _snap_value(snap, "input_bytes_total"),
        "wait_seconds": (None if not w else
                         {"mean_s": round(w["mean_s"], 6),
                          "p50_s": round(w["p50_s"], 6),
                          "p95_s": round(w["p95_s"], 6),
                          "max_s": round(w["max_s"], 6),
                          "n": int(w["n"])}),
    }
    etl = _snap_summary(snap, "training_etl_ms")
    step = _snap_summary(snap, "training_step_ms")
    if etl and step:
        etl_total = etl["mean_ms"] * etl["n"]
        step_total = step["mean_ms"] * step["n"]
        out["etl_ms_total"] = round(etl_total, 3)
        out["step_ms_total"] = round(step_total, 3)
        if etl_total + step_total > 0:
            out["etl_fraction"] = round(
                etl_total / (etl_total + step_total), 4)
    return out


def render_profile_text(report: Dict[str, Any]) -> str:
    """Plain-text rendering of :func:`profile_report` for terminals."""
    lines = ["# jit (per named function)"]
    jit = report.get("jit") or {}
    if jit:
        # disk = persistent_cache_hits (compilecache/): of `compiles`,
        # how many were on-disk cache reads rather than true XLA work
        lines.append(f"{'fn':<28} {'calls':>8} {'compiles':>8} "
                     f"{'disk':>6} {'miss':>7} {'compile_s':>10} "
                     f"{'gflops':>10} {'peak_mb':>8}")
        for name, r in jit.items():
            miss = r.get("cache_miss_ratio")
            flops = r.get("flops")
            peak = r.get("peak_memory_bytes")
            lines.append(
                f"{name:<28} {r['calls']:>8} {r['compiles']:>8} "
                f"{r.get('persistent_cache_hits', 0):>6} "
                f"{miss if miss is not None else '-':>7} "
                f"{r['compile_seconds']:>10} "
                f"{round(flops / 1e9, 3) if flops else '-':>10} "
                f"{round(peak / 1e6, 1) if peak else '-':>8}")
            if r.get("storms"):
                lines.append(f"  !! {r['storms']} retrace storm(s); last "
                             f"delta: {r.get('last_signature_delta')}")
    else:
        lines.append("(no monitored jit activity yet)")
    lines.append("")
    lines.append("# device memory")
    mem = report.get("memory") or {}
    for dev, row in (mem.get("devices") or {}).items():
        lines.append(f"{dev}: in_use={row.get('bytes_in_use')} "
                     f"peak={row.get('peak_bytes_in_use')} "
                     f"limit={row.get('bytes_limit')}")
    if not mem.get("devices"):
        lines.append("(backend reports no memory stats)")
    lines.append(f"live_buffers: {mem.get('live_buffers')}")
    lines.append("")
    lines.append("# steps")
    steps = report.get("steps") or {}
    lines.append(f"iterations={steps.get('iterations')} "
                 f"examples={steps.get('examples')}")
    for k in ("step_ms", "etl_ms"):
        s = steps.get(k)
        if s:
            lines.append(f"{k}: mean={s.get('mean_ms'):.3f} "
                         f"p50={s.get('p50_ms'):.3f} "
                         f"p95={s.get('p95_ms'):.3f} n={int(s.get('n', 0))}")
    pipe = report.get("pipeline") or {}
    if any(v is not None for v in pipe.values()):
        lines.append("")
        lines.append("# pipeline")
        lines.append(f"queue_depth={pipe.get('queue_depth')} "
                     f"batches={pipe.get('batches')} "
                     f"bytes_total={pipe.get('bytes_total')}")
        w = pipe.get("wait_seconds")
        if w:
            lines.append(f"wait_s: mean={w.get('mean_s'):.4f} "
                         f"p50={w.get('p50_s', 0.0):.4f} "
                         f"p95={w.get('p95_s', 0.0):.4f} "
                         f"max={w.get('max_s'):.4f} n={int(w.get('n', 0))}")
        if pipe.get("etl_fraction") is not None:
            lines.append(f"etl_fraction={pipe['etl_fraction']} "
                         f"(etl {pipe.get('etl_ms_total')} ms / step "
                         f"{pipe.get('step_ms_total')} ms)")
    training = report.get("training") or {}
    if training:
        lines.append("")
        lines.append("# training (paramserver hot-loop phases)")
        lines.append(f"overlap_active={training.get('overlap_active')}")
        for p in ("compute", "d2h", "encode", "push"):
            r = (training.get("phase_ms") or {}).get(p)
            if r:
                lines.append(f"{p}: mean={r['mean']:.3f} "
                             f"p95={r['p95']:.3f} max={r['max']:.3f} "
                             f"n={r['n']}")
        w = training.get("wall_ms")
        if w:
            lines.append(f"wall: mean={w['mean']:.3f} p95={w['p95']:.3f} "
                         f"max={w['max']:.3f} n={w['n']}")
        if training.get("hidden_ms_total") is not None:
            lines.append(f"hidden_ms_total={training['hidden_ms_total']} "
                         f"(sum of phases {training.get('phase_ms_total')}"
                         f" ms - wall {training.get('wall_ms_total')} ms)")
    serving = report.get("serving") or {}
    if serving:
        lines.append("")
        lines.append("# serving (per hosted model)")
        lines.append(f"{'model':<20} {'ok':>8} {'rej':>6} {'dl':>5} "
                     f"{'err':>5} {'qps':>7} {'p50_ms':>8} {'p99_ms':>8} "
                     f"{'batch':>6} {'queue':>6} {'cache':>6} "
                     f"{'pad_ms':>7} {'xfer_ms':>8}")
        for name, r in sorted(serving.items()):
            req = r.get("requests", {})
            lat = r.get("latency_ms") or {}
            bat = r.get("batch_examples") or {}
            cache = r.get("cache") or {}
            rate = cache.get("hit_rate")
            lines.append(
                f"{name:<20} {int(req.get('ok', 0)):>8} "
                f"{int(req.get('rejected', 0)):>6} "
                f"{int(req.get('deadline', 0)):>5} "
                f"{int(req.get('error', 0)):>5} "
                f"{round(r.get('qps', 0.0), 1):>7} "
                f"{round(lat.get('p50_ms', 0.0), 2):>8} "
                f"{round(lat.get('p99_ms', 0.0), 2):>8} "
                f"{round(bat.get('mean', 0.0), 1):>6} "
                f"{int(r.get('queue_depth', 0) or 0):>6} "
                f"{rate if rate is not None else '-':>6} "
                f"{(r.get('pad_ms') or {}).get('mean', '-'):>7} "
                f"{(r.get('transfer_ms') or {}).get('mean', '-'):>8}")
    meshes = report.get("mesh") or {}
    if meshes:
        lines.append("")
        lines.append("# mesh (active parallel topologies)")
        lines.append(f"{'style':<28} {'axes':<28} {'devs':>5} "
                     f"{'steps':>6} {'sharded':>8} {'repl':>6} {'zero':>5}")
        for style, r in meshes.items():
            axes = "×".join(f"{a}={n}" for a, n in
                            (r.get("axes") or {}).items()) or "-"
            lines.append(
                f"{style:<28} {axes:<28} {r.get('devices', 0):>5} "
                f"{r.get('steps', 0):>6} {r.get('sharded_leaves', 0):>8} "
                f"{r.get('replicated_leaves', 0):>6} "
                f"{'yes' if r.get('zero') else 'no':>5}")
    locks = report.get("locks") or {}
    if locks:
        lines.append("")
        lines.append("# locks (lockwatch contention)")
        inv = locks.get("_inversions", {}).get("count")
        if inv:
            lines.append(f"  !! {inv} lock-order inversion(s) observed — "
                         f"see the flight recorder")
        lines.append(f"{'lock':<40} {'acq':>8} {'wait_mean_s':>12} "
                     f"{'wait_max_s':>11} {'held_mean_s':>12} "
                     f"{'held_max_s':>11}")
        for name, r in locks.items():
            if name == "_inversions":
                continue
            lines.append(
                f"{name:<40} {r['acquisitions']:>8} "
                f"{r['wait_s_mean']:>12} {r['wait_s_max']:>11} "
                f"{r['held_s_mean']:>12} {r['held_s_max']:>11}")
    control = report.get("control") or {}
    if control:
        lines.append("")
        lines.append("# control (closed-loop control plane)")
        lines.append(f"policies={control.get('policies', 0)} "
                     f"running={'yes' if control.get('running') else 'no'} "
                     f"cooldowns_active={control.get('cooldowns_active', 0)} "
                     f"pending={control.get('pending', 0)} "
                     f"actions_total={control.get('actions_total', 0)}")
        last = control.get("last_action")
        if last:
            lines.append(f"last_action: policy={last.get('policy')} "
                         f"action={last.get('action')} "
                         f"outcome={last.get('outcome')} "
                         f"rule={last.get('rule')} "
                         f"exemplar={last.get('exemplar_trace_id')}")
    trends = report.get("trends") or {}
    if trends:
        lines.append("")
        lines.append("# trends (now vs 1m/5m — monitor/history.py)")
        for key, row in trends.items():
            if key == "window_s":
                continue
            cells = " ".join(
                f"{k}={round(v, 3) if isinstance(v, float) else v}"
                for k, v in row.items())
            lines.append(f"{key}: {cells}")
    return "\n".join(lines) + "\n"
