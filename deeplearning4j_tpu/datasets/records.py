"""Record readers and the record→DataSet bridge (the DataVec seam).

TPU-native equivalent of the reference's DataVec integration
(``datasets/datavec/RecordReaderDataSetIterator.java:52``,
``RecordReaderMultiDataSetIterator``, sequence variants, and the DataVec
``RecordReader``/``CSVRecordReader`` the reference consumes as an external
dependency — SURVEY.md §2.2 "DataVec bridge").

A record is a list of values (floats or strings); a sequence record is a list
of records (one per time step). Readers iterate records; the iterators batch
records into ``DataSet``s, splitting the label column(s) out, exactly like the
reference (label index, numPossibleLabels, regression flag).
"""
from __future__ import annotations

import csv
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator


# -------------------------------------------------------------------- readers
class RecordReader:
    """DataVec ``RecordReader`` protocol: iterate lists of values."""

    def __iter__(self) -> Iterator[List]:
        self.reset()
        return self

    def __next__(self) -> List:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec ``CollectionRecordReader``)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [list(r) for r in records]
        self._pos = 0

    def __next__(self):
        if self._pos >= len(self._records):
            raise StopIteration
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class CSVRecordReader(RecordReader):
    """CSV file reader (DataVec ``CSVRecordReader``): ``skip_lines`` header rows,
    custom delimiter; numeric fields parsed to float, others kept as str."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self._path = path
        self._skip = skip_lines
        self._delim = delimiter
        self._rows = None
        self._pos = 0

    def _load(self):
        with open(self._path, newline="") as f:
            rows = list(csv.reader(f, delimiter=self._delim))
        self._rows = [self._parse(r) for r in rows[self._skip:] if r]

    @staticmethod
    def _parse(row):
        out = []
        for v in row:
            try:
                out.append(float(v))
            except ValueError:
                out.append(v.strip())
        return out

    def __next__(self):
        if self._rows is None:
            self._load()
        if self._pos >= len(self._rows):
            raise StopIteration
        r = self._rows[self._pos]
        self._pos += 1
        return r

    def reset(self):
        if self._rows is None:
            self._load()
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (DataVec ``CSVSequenceRecordReader``); the
    reader is given a list of file paths and yields [T, cols] sequences."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self._paths = list(paths)
        self._skip = skip_lines
        self._delim = delimiter
        self._pos = 0

    def __next__(self):
        if self._pos >= len(self._paths):
            raise StopIteration
        path = self._paths[self._pos]
        self._pos += 1
        with open(path, newline="") as f:
            rows = list(csv.reader(f, delimiter=self._delim))
        return [CSVRecordReader._parse(r) for r in rows[self._skip:] if r]

    def reset(self):
        self._pos = 0


# ------------------------------------------------------------------ iterators
class RecordReaderDataSetIterator(DataSetIterator):
    """Reference ``RecordReaderDataSetIterator.java:52``: batches records,
    splits features vs label column.

    - classification: ``label_index`` column holds the class id →
      one-hot [b, num_classes]
    - regression: ``regression=True``; label columns
      [label_index, label_index_to] stay float
    - no labels: ``label_index=None`` → features only
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self._reader = reader
        self._batch = int(batch_size)
        self._label_index = label_index
        self._num_classes = num_classes
        self._regression = regression
        self._label_index_to = (label_index if label_index_to is None
                                else label_index_to)
        self._it = None

    def __iter__(self):
        self.reset()
        return self

    def reset(self):
        self._reader.reset()
        self._it = iter(self._reader)

    def batch(self):
        return self._batch

    def _split(self, rec):
        if self._label_index is None:
            return [float(v) for v in rec], None
        lo, hi = self._label_index, self._label_index_to
        label = rec[lo:hi + 1]
        feats = list(rec[:lo]) + list(rec[hi + 1:])
        return [float(v) for v in feats], [float(v) for v in label]

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        feats, labels = [], []
        for _ in range(self._batch):
            try:
                rec = next(self._it)
            except StopIteration:
                break
            f, l = self._split(rec)
            feats.append(f)
            if l is not None:
                labels.append(l)
        if not feats:
            raise StopIteration
        f = np.asarray(feats, np.float32)
        if not labels:
            return DataSet(f, None)
        if self._regression:
            return DataSet(f, np.asarray(labels, np.float32))
        if self._num_classes is None:
            # per-batch inference of the width would give inconsistent label
            # shapes across batches (reference makes numPossibleLabels
            # mandatory for classification for the same reason)
            raise ValueError("num_classes is required for classification "
                             "(label_index set, regression=False)")
        idx = np.asarray(labels, np.int64)[:, 0]
        return DataSet(f, np.eye(self._num_classes, dtype=np.float32)[idx])


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Reference ``SequenceRecordReaderDataSetIterator``: batches sequence
    records into [b, T, f] with per-step labels; unequal lengths are padded and
    masked (reference ``AlignmentMode.ALIGN_END`` ≈ our left-aligned padding +
    mask semantics)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 num_classes: Optional[int], label_index: int,
                 regression: bool = False):
        self._reader = reader
        self._batch = int(batch_size)
        self._num_classes = num_classes
        self._label_index = label_index
        self._regression = regression
        self._it = None

    def __iter__(self):
        self.reset()
        return self

    def reset(self):
        self._reader.reset()
        self._it = iter(self._reader)

    def batch(self):
        return self._batch

    def __next__(self) -> DataSet:
        if self._it is None:
            self.reset()
        seqs = []
        for _ in range(self._batch):
            try:
                seqs.append(next(self._it))
            except StopIteration:
                break
        if not seqs:
            raise StopIteration
        li = self._label_index
        T = max(len(s) for s in seqs)
        f_dim = len(seqs[0][0]) - 1
        b = len(seqs)
        feats = np.zeros((b, T, f_dim), np.float32)
        mask = np.zeros((b, T), np.float32)
        if self._regression:
            labels = np.zeros((b, T, 1), np.float32)
        else:
            n = self._num_classes
            labels = np.zeros((b, T, n), np.float32)
        for i, seq in enumerate(seqs):
            for t, rec in enumerate(seq):
                lab = rec[li]
                row = list(rec[:li]) + list(rec[li + 1:])
                feats[i, t] = row
                mask[i, t] = 1.0
                if self._regression:
                    labels[i, t, 0] = float(lab)
                else:
                    labels[i, t, int(lab)] = 1.0
        return DataSet(feats, labels, features_mask=mask, labels_mask=mask)
