"""Iterator wrappers: async prefetch, early termination, multiple epochs,
synthetic benchmark data.

TPU-native equivalents of reference ``deeplearning4j-nn/.../datasets/iterator/``:
``AsyncDataSetIterator`` (background prefetch thread, ``AsyncDataSetIterator.java``),
``EarlyTerminationDataSetIterator``, ``MultipleEpochsIterator``, and
``BenchmarkDataSetIterator`` (synthetic input benchmarking,
``iterator/impl/BenchmarkDataSetIterator.java``). Prefetch overlaps host ETL with
device compute; the device transfer itself happens in the jitted step.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .dataset import DataSet, DataSetIterator
from ..monitor import get_registry


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded buffer (reference
    ``AsyncDataSetIterator``; default queue depth 2 per device as in
    ``MultiLayerNetwork.java:1160``)."""

    _STOP = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 2):
        self._base = base
        self._size = max(2, queue_size)
        self._queue = None
        self._thread = None
        self._stop_event = None
        self._exc = None

    def _worker(self, q, stop):
        """Worker owns its queue + stop token so a reset() cannot leak stale
        batches into a new epoch's queue (the old worker only ever writes to
        the queue it was born with, and exits at the stop signal)."""
        try:
            for ds in self._base:
                while not stop.is_set():
                    try:
                        q.put(ds, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except Exception as e:  # propagate to consumer
            self._exc = e
        finally:
            while not stop.is_set():
                try:
                    q.put(self._STOP, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        self.reset()
        return self

    def reset(self):
        if self._thread is not None and self._thread.is_alive():
            self._stop_event.set()
            self._thread.join(timeout=5)
        self._queue = queue.Queue(maxsize=self._size)
        self._stop_event = threading.Event()
        self._exc = None
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._queue, self._stop_event),
                                        daemon=True)
        self._thread.start()

    def __next__(self):
        if self._queue is None:
            self.reset()
        t0 = time.perf_counter()
        while True:
            # bounded get + liveness check: a worker that dies WITHOUT
            # managing to enqueue its stop token (hard thread death, an
            # error inside the finally) must re-raise on the consumer
            # thread, not park fit on queue.get() forever
            try:
                item = self._queue.get(timeout=0.2)
                break
            except queue.Empty:
                if self._thread is not None and self._thread.is_alive():
                    continue            # slow producer, not a dead one
                # TOCTOU guard: the worker may have enqueued its final
                # batch or stop token and exited between the timeout and
                # the liveness check — drain once before declaring a crash
                try:
                    item = self._queue.get_nowait()
                    break
                except queue.Empty:
                    pass
                if self._exc is not None:
                    raise self._exc
                raise RuntimeError(
                    "AsyncDataSetIterator: prefetch worker died without "
                    "delivering a batch or a stop token")
        if item is self._STOP:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        # monitor seam: how long the training loop actually WAITED for data
        # (≈0 when prefetch keeps up — a growing histogram tail means ETL,
        # not the device, is the bottleneck)
        reg = get_registry()
        reg.histogram("dataset_next_ms",
                      "blocking wait in AsyncDataSetIterator.next").observe(
            (time.perf_counter() - t0) * 1e3)
        reg.counter("dataset_batches_total",
                    "minibatches served by AsyncDataSetIterator").inc()
        return item

    def batch(self):
        return self._base.batch()


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per epoch (reference
    ``EarlyTerminationDataSetIterator.java``)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches
        self._count = 0

    def __iter__(self):
        self._base.reset()
        self._count = 0
        return self

    def __next__(self):
        if self._count >= self._max:
            raise StopIteration
        self._count += 1
        return next(self._base)

    def reset(self):
        self._base.reset()
        self._count = 0

    def batch(self):
        return self._base.batch()


class MultipleEpochsIterator(DataSetIterator):
    """Replays the base iterator N times as one pass (reference
    ``MultipleEpochsIterator.java``)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self._base = base
        self._epochs = epochs
        self._epoch = 0
        self._it = None

    def __iter__(self):
        self._epoch = 0
        self._it = iter(self._base)
        return self

    def __next__(self):
        while True:
            try:
                if self._it is None:
                    self._it = iter(self._base)
                return next(self._it)
            except StopIteration:
                self._epoch += 1
                if self._epoch >= self._epochs:
                    raise
                self._it = iter(self._base)

    def reset(self):
        self._epoch = 0
        self._it = None

    def batch(self):
        return self._base.batch()


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed-shape batches for benchmarking (reference
    ``BenchmarkDataSetIterator.java``): one batch is materialized and re-served,
    so ETL cost ~0 and device throughput is isolated."""

    def __init__(self, feature_shape, num_classes, num_batches, seed=42,
                 label_shape=None):
        rng = np.random.default_rng(seed)
        self._features = rng.standard_normal(feature_shape).astype(np.float32)
        b = feature_shape[0]
        if label_shape is not None:
            self._labels = rng.standard_normal(label_shape).astype(np.float32)
        else:
            idx = rng.integers(0, num_classes, size=b)
            self._labels = np.eye(num_classes, dtype=np.float32)[idx]
        self._num = num_batches
        self._pos = 0

    def __iter__(self):
        self._pos = 0
        return self

    def __next__(self):
        if self._pos >= self._num:
            raise StopIteration
        self._pos += 1
        return DataSet(self._features, self._labels)

    def reset(self):
        self._pos = 0

    def batch(self):
        return int(self._features.shape[0])
