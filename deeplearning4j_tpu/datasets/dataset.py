"""DataSet container and iterator protocol.

TPU-native equivalent of ND4J's ``DataSet``/``MultiDataSet`` and the
``DataSetIterator`` interfaces the reference trains from (SURVEY.md §2.1 "Async
data iterators", L4). Arrays are host numpy; device transfer happens once per
step inside the jitted train step (with donation), replacing the reference's
device-affinity buffering (``MagicQueue``).
"""
from __future__ import annotations

import numpy as np
from typing import List, Optional, Sequence


def _array_key(a):
    """Identity key for the device-residency cache. The cache RETAINS the
    keyed host arrays (``_cached_device_put`` stores them alongside the key),
    so a live key's ``id``/data pointer cannot be recycled by the allocator —
    identity compare is therefore sound; reassignment (the normalizer
    contract) always misses."""
    if a is None:
        return None
    # jax.Array (and other duck-typed arrays) lack __array_interface__ —
    # id + shape/dtype still pins identity because the key's array is retained
    data_ptr = getattr(a, "__array_interface__", {"data": (0,)})["data"][0]
    return (id(a), data_ptr, tuple(a.shape), str(a.dtype))


def _put(a):
    import jax.numpy as jnp
    return None if a is None else jnp.asarray(a)


def _cached_device_put(container, build, retain):
    """Shared CacheMode.DEVICE machinery: rebuild the device tuple only when
    the container's ``_device_key()`` changes. ``retain`` is the tuple of
    host arrays the key describes — kept alive on the container so freed-
    memory id reuse can never alias a stale key."""
    key = container._device_key()
    if getattr(container, "_dev_key", None) != key:
        container._dev = build()
        container._dev_key = key
        container._dev_retained = retain
    return container._dev


class DataSet:
    """features/labels (+ optional masks). Masks follow reference semantics:
    features_mask/labels_mask are [batch, T] 0/1 arrays for sequence data."""

    def __init__(self, features, labels=None, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)
        # True when served by a fetcher's synthetic fallback (zero-egress
        # stand-in data) — accuracy measured on it is meaningless and callers
        # can assert on the flag (fetchers also log a warning)
        self.synthetic = False

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    numExamples = num_examples

    # ------------------------------------------------- device residency
    def _device_key(self):
        return (_array_key(self.features), _array_key(self.labels),
                _array_key(self.features_mask), _array_key(self.labels_mask))

    def device_arrays(self):
        """``CacheMode.DEVICE`` (reference ``nn/conf/CacheMode.java``):
        transfer features/labels/masks to the device ONCE and reuse the
        HBM-resident copies across fits/epochs — repeated fits of the same
        DataSet skip the host→device transfer entirely (which dominates
        small-step training over a slow host link). The cache is keyed on
        the arrays' identity + data pointer, so normalizers (which reassign
        ``ds.features``) invalidate it; in-place writes into the SAME buffer
        are not detected — reassign or construct a new DataSet instead."""
        return _cached_device_put(
            self, lambda: (_put(self.features), _put(self.labels),
                           _put(self.features_mask), _put(self.labels_mask)),
            (self.features, self.labels, self.features_mask,
             self.labels_mask))

    def get_features(self):
        return self.features

    def get_labels(self):
        return self.labels

    def split_test_and_train(self, n_train: int):
        a = DataSet(self.features[:n_train],
                    None if self.labels is None else self.labels[:n_train],
                    None if self.features_mask is None else self.features_mask[:n_train],
                    None if self.labels_mask is None else self.labels_mask[:n_train])
        b = DataSet(self.features[n_train:],
                    None if self.labels is None else self.labels[n_train:],
                    None if self.features_mask is None else self.features_mask[n_train:],
                    None if self.labels_mask is None else self.labels_mask[n_train:])
        return a, b

    splitTestAndTrain = split_test_and_train

    def shuffle(self, seed=None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(xs):
            if any(x is None for x in xs):
                return None
            return np.concatenate(xs, axis=0)
        return DataSet(cat([d.features for d in datasets]),
                       cat([d.labels for d in datasets]),
                       _cat_masks([d.features_mask for d in datasets],
                                  [d.features for d in datasets]),
                       _cat_masks([d.labels_mask for d in datasets],
                                  [d.labels for d in datasets]))

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        out = []
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(DataSet(
                self.features[sl],
                None if self.labels is None else self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl]))
        return out


class MultiDataSet:
    """Multi-input/multi-output container (ND4J ``MultiDataSet``), consumed by
    ``ComputationGraph.fit``."""

    def __init__(self, features, labels, features_masks=None, labels_masks=None):
        self.features = [np.asarray(f) for f in _as_list(features)]
        self.labels = [np.asarray(l) for l in _as_list(labels)]
        self.features_masks = (None if features_masks is None
                               else [None if m is None else np.asarray(m)
                                     for m in _as_list(features_masks)])
        self.labels_masks = (None if labels_masks is None
                             else [None if m is None else np.asarray(m)
                                   for m in _as_list(labels_masks)])

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    def _device_key(self):
        def ks(seq):
            return (None if seq is None
                    else tuple(_array_key(a) for a in seq))
        return (ks(self.features), ks(self.labels), ks(self.features_masks),
                ks(self.labels_masks))

    def device_arrays(self):
        """``CacheMode.DEVICE`` for the multi-stream container — see
        :meth:`DataSet.device_arrays`."""
        def puts(seq):
            return None if seq is None else tuple(_put(a) for a in seq)
        return _cached_device_put(
            self, lambda: (puts(self.features), puts(self.labels),
                           puts(self.features_masks),
                           puts(self.labels_masks)),
            (tuple(self.features), tuple(self.labels),
             None if self.features_masks is None else tuple(self.features_masks),
             None if self.labels_masks is None else tuple(self.labels_masks)))

    @staticmethod
    def merge(datasets: Sequence["MultiDataSet"]) -> "MultiDataSet":
        """Concatenate example-wise, stream by stream (ND4J
        ``MultiDataSet.merge`` role). Mixed mask presence across the merged
        sets synthesizes all-ones masks for the unmasked ones — dropping the
        mask stream entirely would silently train on padding."""
        def cat_streams(streams):
            if any(s is None for s in streams):
                return None
            n = len(streams[0])
            return [np.concatenate([s[i] for s in streams], axis=0)
                    for i in range(n)]

        def cat_mask_streams(mask_lists, data_lists):
            if all(m is None for m in mask_lists):
                return None
            n = len(data_lists[0])
            out = []
            for i in range(n):
                masks = [None if ml is None else ml[i] for ml in mask_lists]
                data = [dl[i] for dl in data_lists]
                out.append(_cat_masks(masks, data))
            return out

        return MultiDataSet(
            cat_streams([d.features for d in datasets]),
            cat_streams([d.labels for d in datasets]),
            cat_mask_streams([d.features_masks for d in datasets],
                             [d.features for d in datasets]),
            cat_mask_streams([d.labels_masks for d in datasets],
                             [d.labels for d in datasets]))


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _cat_masks(masks, data):
    """Concatenate per-example masks; when presence is mixed, missing masks
    become all-ones shaped after their data's leading mask dims (so merged
    batches don't lose masking for the sets that have it)."""
    if all(m is None for m in masks):
        return None
    ndim = next(m.ndim for m in masks if m is not None)
    filled = [m if m is not None else np.ones(np.asarray(d).shape[:ndim],
                                              np.float32)
              for m, d in zip(masks, data)]
    return np.concatenate(filled, axis=0)


class DataSetIterator:
    """Iterator protocol (ND4J ``DataSetIterator``): python-iterable + reset()."""

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        pass

    def batch(self) -> int:
        raise NotImplementedError

    def async_supported(self) -> bool:
        return True

    def concurrent_pull_supported(self) -> bool:
        """True when ``__next__`` is safe to call from MULTIPLE prefetch
        workers at once (``datasets/prefetch.py``): required for a slow
        *source* (disk decode, network fetch) to parallelize, not just a
        slow transform. Default False — most iterators hold unguarded
        position state. Opt in only when the iterator serializes its own
        bookkeeping and tolerates best-effort ordering at the stream
        tail."""
        return False


class ListDataSetIterator(DataSetIterator):
    """Reference ``ListDataSetIterator``: iterate a pre-built list of DataSets."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None and len(datasets) == 1:
            datasets = datasets[0].batch_by(batch_size)
        self._data = list(datasets)
        self._pos = 0
        self._batch = batch_size or (self._data[0].num_examples() if self._data else 0)

    def __next__(self):
        if self._pos >= len(self._data):
            raise StopIteration
        d = self._data[self._pos]
        self._pos += 1
        return d

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batch


class ExistingDataSetIterator(DataSetIterator):
    """Wraps any python iterable of DataSets."""

    def __init__(self, iterable):
        self._iterable = iterable
        self._it = None

    def __iter__(self):
        self._it = iter(self._iterable)
        return self

    def __next__(self):
        if self._it is None:
            self._it = iter(self._iterable)
        return next(self._it)

    def reset(self):
        self._it = iter(self._iterable)

    def batch(self):
        return -1
