"""Concrete dataset iterators: MNIST, EMNIST, IRIS, CIFAR.

TPU-native equivalents of reference ``deeplearning4j-core/.../datasets/iterator/impl/``
(``MnistDataSetIterator``, ``EmnistDataSetIterator``, ``IrisDataSetIterator``,
``CifarDataSetIterator``). Constructor shapes mirror the reference; data comes
from :mod:`.fetchers` (local files or deterministic synthetic fallback).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .dataset import DataSet, DataSetIterator
from .fetchers import (MnistDataFetcher, EmnistDataFetcher, IrisDataFetcher,
                       CifarDataFetcher, LFWDataFetcher, TinyImageNetFetcher)


class _ArrayIterator(DataSetIterator):
    """Minibatch iterator over in-memory feature/label arrays."""

    def __init__(self, features, labels, batch_size: int,
                 num_examples: Optional[int] = None, synthetic: bool = False):
        n = len(features) if num_examples is None else min(num_examples,
                                                           len(features))
        self._features = features[:n]
        self._labels = labels[:n]
        self._batch = int(batch_size)
        self._pos = 0
        self._synthetic = bool(synthetic)

    def __next__(self) -> DataSet:
        if self._pos >= len(self._features):
            raise StopIteration
        sl = slice(self._pos, self._pos + self._batch)
        self._pos += self._batch
        ds = DataSet(self._features[sl], self._labels[sl])
        ds.synthetic = self._synthetic  # loud stand-in-data marker
        return ds

    def reset(self):
        self._pos = 0

    def batch(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return len(self._features)

    totalExamples = total_examples

    def num_outcomes(self) -> int:
        return int(self._labels.shape[-1])


class MnistDataSetIterator(_ArrayIterator):
    """Reference ``MnistDataSetIterator(batch, numExamples, binarize, train,
    shuffle, rngSeed)``."""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 binarize: bool = False, train: bool = True,
                 shuffle: bool = True, seed: int = 123, **fetcher_kw):
        f = MnistDataFetcher(train=train, binarize=binarize, shuffle=shuffle,
                             seed=seed, **fetcher_kw)
        self.fetcher = f
        super().__init__(f.features, f.labels, batch, num_examples,
                         synthetic=f.is_synthetic)


class EmnistDataSetIterator(_ArrayIterator):
    def __init__(self, split: str, batch: int,
                 num_examples: Optional[int] = None, train: bool = True,
                 shuffle: bool = True, seed: int = 123, **fetcher_kw):
        f = EmnistDataFetcher(split=split, train=train, shuffle=shuffle,
                              seed=seed, **fetcher_kw)
        self.fetcher = f
        super().__init__(f.features, f.labels, batch, num_examples,
                         synthetic=f.is_synthetic)


class IrisDataSetIterator(_ArrayIterator):
    """Reference ``IrisDataSetIterator(batch, numExamples)``."""

    def __init__(self, batch: int = 150, num_examples: int = 150):
        f = IrisDataFetcher()
        super().__init__(f.features, f.labels, batch, num_examples)


class CifarDataSetIterator(_ArrayIterator):
    """Reference ``CifarDataSetIterator``; features NCHW [b, 3, 32, 32]."""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 123, **fetcher_kw):
        f = CifarDataFetcher(train=train, seed=seed, **fetcher_kw)
        self.fetcher = f
        super().__init__(f.features, f.labels, batch, num_examples,
                         synthetic=f.is_synthetic)


class LFWDataSetIterator(_ArrayIterator):
    """Reference ``LFWDataSetIterator`` (``LFWDataFetcher.java:1``); features
    NCHW [b, 3, H, W]."""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 seed: int = 123, **fetcher_kw):
        f = LFWDataFetcher(seed=seed, **fetcher_kw)
        self.fetcher = f
        super().__init__(f.features, f.labels, batch, num_examples,
                         synthetic=f.is_synthetic)


class TinyImageNetDataSetIterator(_ArrayIterator):
    """Reference ``TinyImageNetDataSetIterator``; 200-class 64×64 RGB."""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 seed: int = 123, **fetcher_kw):
        f = TinyImageNetFetcher(seed=seed, **fetcher_kw)
        self.fetcher = f
        super().__init__(f.features, f.labels, batch, num_examples,
                         synthetic=f.is_synthetic)
