"""Kafka wire-protocol codec + NDArray client.

Closes the protocol-compatibility gap the round-3 review flagged: the
reference ships ``NDArrayKafkaClient``
(``dl4j-streaming/.../streaming/kafka/NDArrayKafkaClient.java:1``) pushing
base64 NDArrays through real Kafka topics, while this build's
``datasets/streaming.py`` speaks its own length-prefixed framing. This
module implements the actual Kafka protocol pieces needed to interoperate
with a real broker — no third-party Kafka library (none is baked into the
image), just the byte formats:

- :func:`crc32c` — Castagnoli CRC (table-based), the checksum RecordBatch
  v2 requires (verified against the published test vectors).
- varint/zigzag codecs (Kafka's record-level integer encoding).
- :class:`RecordBatch` — the modern (magic=2) on-disk/on-wire record batch:
  encode/decode with per-record varint framing, headers, and the crc32c
  over attributes→records.
- Request builders/parsers for Produce v3 and Fetch v4 (the first protocol
  versions that carry RecordBatch v2, still accepted by modern brokers),
  plus the 4-byte-size request framing. Metadata/leader discovery is NOT
  implemented: the client talks to the bootstrap broker only, which must be
  (or proxy to) the partition leader — the single-broker shape the
  reference's embedded-Kafka tests used.
- :class:`NDArrayKafkaClient` — the reference client's contract
  (``publish(ndarray)`` / ``poll()``) over a raw socket using the codecs
  above; array payloads ride as ``streaming.NDArrayMessage`` record values.

The codec layer is fully unit-tested (round trips + CRC vectors). The
socket client is exercised against an in-repo stub speaking the same
framing — a live-broker integration needs a deployment with Kafka, which
this zero-egress image cannot host (honest seam, same status as
provisioning).
"""
from __future__ import annotations

import io
import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import DataSet, DataSetIterator

# ------------------------------------------------------------------- crc32c
_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLES = [[0] * 256 for _ in range(8)]
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLES[0][_i] = _c
for _k in range(1, 8):
    for _i in range(256):
        _p = _CRC32C_TABLES[_k - 1][_i]
        _CRC32C_TABLES[_k][_i] = _CRC32C_TABLES[0][_p & 0xFF] ^ (_p >> 8)


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) — RecordBatch v2's checksum. Slice-by-8 table
    walk (8 bytes per loop iteration — the pure-Python constant matters:
    tensor payloads are MBs). Matches the published vectors
    (crc32c(b"123456789") == 0xE3069283)."""
    t = _CRC32C_TABLES
    crc = ~crc & 0xFFFFFFFF
    n = len(data)
    i = 0
    while n - i >= 8:
        lo = crc ^ int.from_bytes(data[i:i + 4], "little")
        hi = int.from_bytes(data[i + 4:i + 8], "little")
        crc = (t[7][lo & 0xFF] ^ t[6][(lo >> 8) & 0xFF]
               ^ t[5][(lo >> 16) & 0xFF] ^ t[4][(lo >> 24) & 0xFF]
               ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF]
               ^ t[1][(hi >> 16) & 0xFF] ^ t[0][(hi >> 24) & 0xFF])
        i += 8
    while i < n:
        crc = (crc >> 8) ^ t[0][(crc ^ data[i]) & 0xFF]
        i += 1
    return ~crc & 0xFFFFFFFF


# ----------------------------------------------------------- varint / zigzag
def zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: io.BytesIO, value: int):
    """Kafka varint: zigzag then LEB128."""
    v = zigzag_encode(value) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_varint(buf: io.BytesIO) -> int:
    shift, result = 0, 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise EOFError("varint truncated")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return zigzag_decode(result)
        shift += 7


# --------------------------------------------------------------- primitives
def _i8(v):
    return struct.pack(">b", v)


def _i16(v):
    return struct.pack(">h", v)


def _i32(v):
    return struct.pack(">i", v)


def _i64(v):
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.b = io.BytesIO(data)

    def i8(self):
        return struct.unpack(">b", self.b.read(1))[0]

    def i16(self):
        return struct.unpack(">h", self.b.read(2))[0]

    def i32(self):
        return struct.unpack(">i", self.b.read(4))[0]

    def i64(self):
        return struct.unpack(">q", self.b.read(8))[0]

    def u32(self):
        return struct.unpack(">I", self.b.read(4))[0]

    def string(self):
        n = self.i16()
        return None if n < 0 else self.b.read(n).decode()

    def bytes_(self):
        n = self.i32()
        return None if n < 0 else self.b.read(n)

    def raw(self, n):
        return self.b.read(n)


# ------------------------------------------------------------ RecordBatch v2
class Record:
    """One record inside a v2 batch."""

    def __init__(self, value: bytes, key: Optional[bytes] = None,
                 headers: Sequence[Tuple[str, bytes]] = (),
                 timestamp_delta: int = 0, offset_delta: int = 0):
        self.value = value
        self.key = key
        self.headers = list(headers)
        self.timestamp_delta = timestamp_delta
        self.offset_delta = offset_delta

    def encode(self) -> bytes:
        body = io.BytesIO()
        body.write(_i8(0))                       # attributes (unused)
        write_varint(body, self.timestamp_delta)
        write_varint(body, self.offset_delta)
        if self.key is None:
            write_varint(body, -1)
        else:
            write_varint(body, len(self.key))
            body.write(self.key)
        if self.value is None:
            write_varint(body, -1)
        else:
            write_varint(body, len(self.value))
            body.write(self.value)
        write_varint(body, len(self.headers))
        for hk, hv in self.headers:
            kb = hk.encode()
            write_varint(body, len(kb))
            body.write(kb)
            write_varint(body, len(hv))
            body.write(hv)
        payload = body.getvalue()
        out = io.BytesIO()
        write_varint(out, len(payload))
        out.write(payload)
        return out.getvalue()

    @classmethod
    def decode(cls, buf: io.BytesIO) -> "Record":
        length = read_varint(buf)
        body = io.BytesIO(buf.read(length))
        body.read(1)                             # attributes
        ts_delta = read_varint(body)
        off_delta = read_varint(body)
        klen = read_varint(body)
        key = body.read(klen) if klen >= 0 else None
        vlen = read_varint(body)
        value = body.read(vlen) if vlen >= 0 else None  # None = tombstone
        n_headers = read_varint(body)
        headers = []
        for _ in range(n_headers):
            hklen = read_varint(body)
            hk = body.read(hklen).decode()
            hvlen = read_varint(body)
            hv = body.read(hvlen) if hvlen >= 0 else b""
            headers.append((hk, hv))
        return cls(value, key, headers, ts_delta, off_delta)


class RecordBatch:
    """Kafka message-format v2 batch (magic byte 2) — the format every
    broker since 0.11 stores and ships. Layout (all big-endian):

    baseOffset i64 | batchLength i32 | partitionLeaderEpoch i32 | magic i8 |
    crc u32 (crc32c of everything after it) | attributes i16 |
    lastOffsetDelta i32 | baseTimestamp i64 | maxTimestamp i64 |
    producerId i64 | producerEpoch i16 | baseSequence i32 |
    recordCount i32 | records…
    """

    MAGIC = 2

    def __init__(self, records: List[Record], base_offset: int = 0,
                 base_timestamp: int = 0, last_offset_delta: Optional[int] = None,
                 attributes: int = 0):
        self.records = records
        self.base_offset = base_offset
        self.base_timestamp = base_timestamp
        # may exceed len(records)-1 on compacted batches; consumers must
        # advance by it, not by the surviving record count
        self.last_offset_delta = (len(records) - 1 if last_offset_delta is None
                                  else last_offset_delta)
        self.attributes = attributes

    @property
    def is_control(self) -> bool:
        """Transaction-marker batches (attributes bit 5): skip, never
        decode their payloads."""
        return bool(self.attributes & 0x20)

    @property
    def next_offset(self) -> int:
        return self.base_offset + self.last_offset_delta + 1

    def encode(self) -> bytes:
        # brokers validate record offsets: producer batches get sequential
        # deltas 0..n-1 (consistent with lastOffsetDelta). A synthetic
        # compacted batch (caller-set larger delta) keeps its own deltas.
        if self.last_offset_delta == len(self.records) - 1:
            for i, r in enumerate(self.records):
                r.offset_delta = i
        recs = b"".join(r.encode() for r in self.records)
        after_crc = io.BytesIO()
        after_crc.write(_i16(self.attributes))
        after_crc.write(_i32(max(0, self.last_offset_delta)))
        after_crc.write(_i64(self.base_timestamp))
        after_crc.write(_i64(self.base_timestamp))
        after_crc.write(_i64(-1))                            # producerId
        after_crc.write(_i16(-1))                            # producerEpoch
        after_crc.write(_i32(-1))                            # baseSequence
        after_crc.write(_i32(len(self.records)))
        after_crc.write(recs)
        tail = after_crc.getvalue()
        crc = crc32c(tail)
        # batchLength counts from partitionLeaderEpoch (exclusive of
        # baseOffset+batchLength themselves)
        body = _i32(-1) + _i8(self.MAGIC) + struct.pack(">I", crc) + tail
        return _i64(self.base_offset) + _i32(len(body)) + body

    @classmethod
    def decode(cls, data: bytes, verify_crc: bool = True) -> "RecordBatch":
        r = _Reader(data)
        base_offset = r.i64()
        batch_len = r.i32()
        body = r.raw(batch_len)
        br = _Reader(body)
        br.i32()                                             # leaderEpoch
        magic = br.i8()
        if magic != cls.MAGIC:
            raise ValueError(f"unsupported message-format magic {magic} "
                             f"(only v2 RecordBatch is implemented)")
        crc = br.u32()
        tail = body[9:]
        if verify_crc and crc32c(tail) != crc:
            raise ValueError("RecordBatch crc32c mismatch (corrupt batch)")
        tr = _Reader(tail)
        attributes = tr.i16()
        last_delta = tr.i32()
        base_ts = tr.i64()
        tr.i64()                                             # maxTimestamp
        tr.i64()                                             # producerId
        tr.i16()                                             # producerEpoch
        tr.i32()                                             # baseSequence
        n = tr.i32()
        buf = io.BytesIO(tail[tr.b.tell():])
        records = [Record.decode(buf) for _ in range(n)]
        return cls(records, base_offset, base_ts,
                   last_offset_delta=last_delta, attributes=attributes)


# ------------------------------------------------------------- request codec
API_PRODUCE = 0
API_FETCH = 1
API_METADATA = 3
API_VERSIONS = 18


def request_frame(api_key: int, api_version: int, correlation_id: int,
                  client_id: str, body: bytes) -> bytes:
    """4-byte-size framed Kafka request with the classic (v1) header."""
    header = (_i16(api_key) + _i16(api_version) + _i32(correlation_id)
              + _string(client_id))
    payload = header + body
    return _i32(len(payload)) + payload


def produce_request(topic: str, partition: int, batch: RecordBatch,
                    acks: int = 1, timeout_ms: int = 10000) -> bytes:
    """Produce v3 body (first version carrying RecordBatch v2)."""
    rec = batch.encode()
    return (_string(None)                       # transactional_id
            + _i16(acks) + _i32(timeout_ms)
            + _i32(1) + _string(topic)
            + _i32(1) + _i32(partition) + _bytes(rec))


def parse_produce_response(body: bytes) -> Dict:
    r = _Reader(body)
    n_topics = r.i32()
    out = {}
    for _ in range(n_topics):
        topic = r.string()
        n_parts = r.i32()
        parts = {}
        for _ in range(n_parts):
            pid = r.i32()
            err = r.i16()
            base_offset = r.i64()
            log_append_time = r.i64()
            parts[pid] = {"error_code": err, "base_offset": base_offset,
                          "log_append_time": log_append_time}
        out[topic] = parts
    r.i32()                                      # throttle_time_ms
    return out


def fetch_request(topic: str, partition: int, offset: int,
                  max_bytes: int = 1 << 20, max_wait_ms: int = 500) -> bytes:
    """Fetch v4 body (first version returning RecordBatch v2)."""
    return (_i32(-1)                             # replica_id (consumer)
            + _i32(max_wait_ms) + _i32(1)        # min_bytes
            + _i32(max_bytes) + _i8(0)           # isolation_level
            + _i32(1) + _string(topic)
            + _i32(1) + _i32(partition) + _i64(offset) + _i32(max_bytes))


def parse_fetch_response(body: bytes) -> Dict:
    r = _Reader(body)
    r.i32()                                      # throttle_time_ms
    n_topics = r.i32()
    out = {}
    for _ in range(n_topics):
        topic = r.string()
        n_parts = r.i32()
        parts = {}
        for _ in range(n_parts):
            pid = r.i32()
            err = r.i16()
            high_watermark = r.i64()
            r.i64()                              # last_stable_offset
            n_aborted = r.i32()
            for _ in range(max(0, n_aborted)):
                r.i64()
                r.i64()
            recs = r.bytes_()
            batches = []
            buf = recs or b""
            pos = 0
            while pos + 12 <= len(buf):
                blen = struct.unpack(">i", buf[pos + 8:pos + 12])[0]
                end = pos + 12 + blen
                if end > len(buf):
                    break                        # truncated trailing batch
                batches.append(RecordBatch.decode(buf[pos:end]))
                pos = end
            parts[pid] = {"error_code": err,
                          "high_watermark": high_watermark,
                          "batches": batches}
        out[topic] = parts
    return out


# --------------------------------------------------------------- the client
class NDArrayKafkaClient:
    """The reference ``NDArrayKafkaClient`` contract over the raw protocol:
    ``publish(arrays)`` produces one record whose value is the
    ``streaming.NDArrayMessage`` payload; ``poll()`` fetches and decodes
    records from the current offset. One socket, one topic-partition —
    the shape the reference's Camel route used."""

    def __init__(self, bootstrap: str, topic: str, partition: int = 0,
                 client_id: str = "dl4j-tpu", timeout: float = 10.0):
        host, _, port = bootstrap.partition(":")
        self._addr = (host, int(port or 9092))
        self.topic = topic
        self.partition = partition
        self.client_id = client_id
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._correlation = 0
        self.offset = 0

    # -- plumbing ---------------------------------------------------------
    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, self.timeout)
        return self._sock

    def _roundtrip(self, api_key: int, api_version: int, body: bytes) -> bytes:
        self._correlation += 1
        s = self._conn()
        s.sendall(request_frame(api_key, api_version, self._correlation,
                                self.client_id, body))
        size_raw = self._recv_exact(4)
        size = struct.unpack(">i", size_raw)[0]
        payload = self._recv_exact(size)
        corr = struct.unpack(">i", payload[:4])[0]
        if corr != self._correlation:
            raise IOError(f"correlation id mismatch: {corr} != "
                          f"{self._correlation}")
        return payload[4:]

    def _recv_exact(self, n: int) -> bytes:
        s = self._conn()
        chunks = []
        while n:
            c = s.recv(n)
            if not c:
                raise ConnectionError("broker closed connection")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    # -- API --------------------------------------------------------------
    def publish(self, arrays) -> int:
        """Produce one record carrying the NDArrayMessage payload; returns
        the record's base offset as assigned by the broker."""
        from .streaming import NDArrayMessage

        import time
        if isinstance(arrays, np.ndarray):
            arrays = [arrays]
        batch = RecordBatch([Record(NDArrayMessage.encode(arrays))],
                            base_timestamp=int(time.time() * 1000))
        resp = parse_produce_response(self._roundtrip(
            API_PRODUCE, 3, produce_request(self.topic, self.partition,
                                            batch)))
        part = resp[self.topic][self.partition]
        if part["error_code"]:
            raise IOError(f"Kafka produce error {part['error_code']} for "
                          f"{self.topic}/{self.partition}")
        return part["base_offset"]

    def poll(self) -> List[List[np.ndarray]]:
        """Fetch records from the current offset, decode each value as an
        NDArrayMessage; advances the consumer offset."""
        from .streaming import NDArrayMessage

        resp = parse_fetch_response(self._roundtrip(
            API_FETCH, 4, fetch_request(self.topic, self.partition,
                                        self.offset)))
        part = resp[self.topic][self.partition]
        if part["error_code"]:
            raise IOError(f"Kafka fetch error {part['error_code']} for "
                          f"{self.topic}/{self.partition}")
        out = []
        for batch in part["batches"]:
            if not batch.is_control:             # skip transaction markers
                for rec in batch.records:
                    # the broker returns the WHOLE batch containing the
                    # fetch offset: records before self.offset were already
                    # delivered (mid-batch offsets happen after compaction
                    # rewrites batch boundaries) — consumer contract says
                    # discard them
                    if batch.base_offset + rec.offset_delta < self.offset:
                        continue
                    if rec.value is not None:    # skip tombstones
                        out.append(NDArrayMessage.decode(rec.value))
            # advance by lastOffsetDelta, NOT the surviving record count —
            # compacted batches otherwise re-fetch forever
            self.offset = max(self.offset, batch.next_offset)
        return out

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class KafkaDataSetIterator(DataSetIterator):
    """``DataSetIterator`` over a real Kafka topic: each record's value is an
    ``NDArrayMessage`` of (features, labels[, masks]) — the reference's
    record→DataSet conversion role (``streaming/conversion``) against the
    real wire protocol instead of the in-process broker. Polls until
    ``num_batches`` (None → until a poll returns nothing after
    ``max_empty_polls`` tries)."""

    def __init__(self, client: NDArrayKafkaClient,
                 num_batches: Optional[int] = None, convert=None,
                 max_empty_polls: int = 3):
        self.client = client
        self.num_batches = num_batches
        self.convert = convert
        self.max_empty_polls = max_empty_polls
        self._queue: List = []
        self._seen = 0

    def __next__(self):
        if self.num_batches is not None and self._seen >= self.num_batches:
            raise StopIteration
        empty = 0
        while not self._queue:
            msgs = self.client.poll()
            if msgs:
                self._queue.extend(msgs)
                break
            empty += 1
            if empty >= self.max_empty_polls:
                raise StopIteration
        parts = self._queue.pop(0)
        self._seen += 1
        if self.convert is not None:
            return self.convert(parts)
        return DataSet(*parts[:4])

    def reset(self):
        self._seen = 0  # the topic offset does not rewind; counting restarts
