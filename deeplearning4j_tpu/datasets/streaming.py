"""Streaming ingestion/serving: NDArray pub/sub + streaming iterators.

TPU-native equivalent of reference ``dl4j-streaming`` (SURVEY.md §2.4
"Streaming", 1537 LoC): ``NDArrayKafkaClient``/``NDArrayPublisher``/
``NDArrayConsumer`` (``streaming/kafka/``), the record→NDArray/DataSet
conversion functions (``streaming/conversion/``) and the Camel serving route
(``routes/DL4jServeRouteBuilder.java`` — consume features, run the model,
publish predictions).

Kafka/Camel are JVM-era infrastructure; the seam that matters is *arrays in
flight feeding training/serving*. Here:

 - :class:`NDArrayMessage` — little-endian wire codec for numpy arrays
   (dtype tag + rank + dims + raw bytes), the counterpart of the reference's
   base64 NDArray payloads (``conversion/NDArrayType``).
 - :class:`StreamingBroker` — in-process topic broker over TCP with the
   length-prefixed framing of ``parallel/transport.py``; plays the embedded
   Kafka role of the reference's tests. A real deployment would point the
   publisher/consumer at any broker speaking the same framing.
 - :class:`NDArrayPublisher` / :class:`NDArrayConsumer` — publish/subscribe
   numpy arrays (tuples of arrays = one message with multiple parts,
   matching ``publish(INDArray[])``).
 - :class:`StreamingDataSetIterator` — adapts a consumer of
   (features, labels) messages into the ``DataSetIterator`` seam, so
   ``net.fit`` trains straight off the stream with the existing async
   prefetch machinery.
 - :class:`ServingRoute` — the DL4jServeRouteBuilder equivalent: consume
   feature arrays from one topic, run ``net.output``, publish predictions to
   another.

For interop with REAL Kafka brokers, ``datasets/kafka.py`` implements the
actual Kafka wire protocol (RecordBatch v2 + crc32c, Produce v3 / Fetch v4)
and an :class:`~deeplearning4j_tpu.datasets.kafka.NDArrayKafkaClient`
carrying these same ``NDArrayMessage`` payloads as record values.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator

__all__ = ["NDArrayMessage", "StreamingBroker", "NDArrayPublisher",
           "NDArrayConsumer", "StreamingDataSetIterator", "ServingRoute",
           "StreamIdleTimeout"]


class StreamIdleTimeout(TimeoutError):
    """Timeout that fired BETWEEN frames (no bytes consumed) — safe to retry.
    A plain TimeoutError from ``receive`` means bytes of a frame were already
    consumed; retrying would desync the framed stream."""


# ------------------------------------------------------------------ wire codec
class NDArrayMessage:
    """Multi-part numpy array wire codec. Frame = u32 part count, then per
    part: u8 dtype tag, u8 rank, u64 dims[rank], raw bytes."""

    _DTYPES = [np.dtype(np.float32), np.dtype(np.float64),
               np.dtype(np.int32), np.dtype(np.int64),
               np.dtype(np.uint8), np.dtype(np.bool_), np.dtype(np.float16),
               np.dtype(np.int8), np.dtype(np.int16), np.dtype(np.uint16),
               np.dtype(np.uint32), np.dtype(np.uint64)]
    _TAG = {d: i for i, d in enumerate(_DTYPES)}

    @classmethod
    def encode(cls, arrays: Sequence[np.ndarray]) -> bytes:
        if isinstance(arrays, np.ndarray):
            arrays = [arrays]
        out = [struct.pack("<I", len(arrays))]
        for a in arrays:
            a = np.asarray(a)
            if a.ndim and not a.flags["C_CONTIGUOUS"]:
                # ascontiguousarray only when needed: it promotes 0-d arrays
                # to 1-d, breaking scalar round-trips
                a = np.ascontiguousarray(a)
            if a.dtype not in cls._TAG:
                # a wire codec must not silently change dtype
                raise ValueError(f"NDArrayMessage: unsupported dtype "
                                 f"{a.dtype}; supported: "
                                 f"{[str(d) for d in cls._DTYPES]}")
            out.append(struct.pack("<BB", cls._TAG[a.dtype], a.ndim))
            out.append(struct.pack(f"<{max(a.ndim, 1)}q",
                                   *(a.shape or (a.size,))))
            out.append(a.tobytes())
        return b"".join(out)

    @classmethod
    def decode(cls, data: bytes) -> List[np.ndarray]:
        (n,) = struct.unpack_from("<I", data, 0)
        off = 4
        out = []
        for _ in range(n):
            tag, rank = struct.unpack_from("<BB", data, off)
            off += 2
            dims = struct.unpack_from(f"<{max(rank, 1)}q", data, off)
            off += 8 * max(rank, 1)
            dt = cls._DTYPES[tag]
            count = int(np.prod(dims[:rank])) if rank else int(dims[0])
            nbytes = count * dt.itemsize
            arr = np.frombuffer(data[off:off + nbytes], dt)
            off += nbytes
            # rank 0 round-trips to a scalar shape (), not (1,)
            out.append(arr.reshape(dims[:rank] if rank else ()))
        return out


# framing shared with the SHARED_GRADIENTS update wire — one format, one
# implementation (parallel/transport.py)
from ..parallel.transport import send_frame as _send_frame  # noqa: E402
from ..parallel.transport import recv_frame as _recv_frame  # noqa: E402

#: zero-length payload = end-of-stream control frame: a closing publisher
#: sends it and the broker fans it out, so subscribers see a clean end
#: instead of blocking until their socket times out
_EOS = b""


# ---------------------------------------------------------------------- broker
class StreamingBroker:
    """Topic broker: clients send ``SUB <topic>`` or ``PUB <topic>`` control
    frames, then publishers stream message frames which the broker fans out
    to every subscriber of that topic (at-most-once, the reference test
    cluster's semantics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.address = f"{host}:{self._srv.getsockname()[1]}"
        self._subs: Dict[str, List[socket.socket]] = {}
        # per-subscriber send locks: two publishers on one topic fan out from
        # different threads, and interleaved sendall() on the same socket
        # would corrupt the subscriber's frame stream
        self._send_locks: Dict[socket.socket, threading.Lock] = {}
        # active publishers per topic: EOS reaches subscribers only when the
        # LAST publisher of a topic closes — one departing publisher must not
        # end the stream for a topic others are still feeding
        self._pubs: Dict[str, int] = {}
        from ..monitor.lockwatch import make_lock
        self._lock = make_lock("StreamingBroker._lock")
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                s, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._client_loop, args=(s,),
                             daemon=True).start()

    def _client_loop(self, s: socket.socket):
        hello = _recv_frame(s)
        if hello is None:
            s.close()
            return
        mode, _, topic = hello.decode("utf-8").partition(" ")
        if mode == "SUB":
            with self._lock:
                self._subs.setdefault(topic, []).append(s)
                from ..monitor.lockwatch import make_lock
                self._send_locks[s] = make_lock("StreamingBroker._send_locks")
            return  # frames are pushed by publishers; socket stays open
        with self._lock:
            self._pubs[topic] = self._pubs.get(topic, 0) + 1
        while True:  # PUB
            try:
                frame = _recv_frame(s)
            except OSError:  # abrupt publisher disconnect (incl. resets)
                frame = None
            if frame == _EOS or frame is None:
                with self._lock:
                    self._pubs[topic] = self._pubs.get(topic, 1) - 1
                    last = self._pubs[topic] <= 0
                # forward EOS only on an EXPLICIT close of the last
                # publisher; an abrupt disconnect stays loud (subscribers
                # time out instead of "finishing" a truncated stream)
                if frame == _EOS and last:
                    self._fanout(topic, _EOS)
                s.close()
                return
            self._fanout(topic, frame)

    def _fanout(self, topic: str, frame: bytes):
        with self._lock:
            targets = [(t, self._send_locks[t])
                       for t in self._subs.get(topic, ())]
        for t, lock in targets:
            try:
                with lock:
                    _send_frame(t, frame)
            except OSError:
                with self._lock:
                    if t in self._subs.get(topic, ()):
                        self._subs[topic].remove(t)
                    self._send_locks.pop(t, None)

    def close(self):
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for socks in self._subs.values():
                for s in socks:
                    try:
                        s.close()
                    except OSError:
                        pass


def _connect(address: str) -> socket.socket:
    host, _, port = address.rpartition(":")
    return socket.create_connection((host, int(port)), timeout=30.0)


class NDArrayPublisher:
    """Reference ``NDArrayPublisher`` (``streaming/kafka/NDArrayPublisher.java:23``,
    ``publish(INDArray)``/``publish(INDArray[])``)."""

    def __init__(self, address: str, topic: str):
        self._sock = _connect(address)
        _send_frame(self._sock, f"PUB {topic}".encode("utf-8"))

    def publish(self, arrays):
        _send_frame(self._sock, NDArrayMessage.encode(arrays))

    def close(self, end_stream: bool = True):
        """``end_stream`` sends the EOS control frame first, giving
        subscribers a clean end-of-stream (None from ``receive``) instead of
        an eventual timeout."""
        if end_stream:
            try:
                _send_frame(self._sock, _EOS)
            except OSError:
                pass
        self._sock.close()


class NDArrayConsumer:
    """Reference ``NDArrayConsumer``: blocking array receive from a topic."""

    def __init__(self, address: str, topic: str, timeout: float = 30.0):
        self._sock = _connect(address)
        self._sock.settimeout(timeout)
        _send_frame(self._sock, f"SUB {topic}".encode("utf-8"))

    def _recv_idle_aware(self) -> Optional[bytes]:
        """One frame; distinguishes idle (no bytes consumed → safe to retry)
        from a mid-frame stall (stream desynced → fatal)."""
        try:
            first = self._sock.recv(8)
        except socket.timeout:
            raise StreamIdleTimeout(
                f"no message within {self._sock.gettimeout()}s — producer "
                f"idle or stalled (safe to retry)")
        if not first:
            return None  # orderly close
        buf = bytearray(first)
        while len(buf) < 8:
            chunk = self._sock.recv(8 - len(buf))
            if not chunk:
                raise ConnectionError("peer closed mid-header")
            buf.extend(chunk)
        (n,) = struct.unpack("<q", bytes(buf))
        payload = bytearray()
        while len(payload) < n:
            chunk = self._sock.recv(n - len(payload))
            if not chunk:
                raise ConnectionError("peer closed mid-frame")
            payload.extend(chunk)
        return bytes(payload)

    def receive(self) -> Optional[List[np.ndarray]]:
        """Next message's arrays; None only on CLEAN stream end (the last
        publisher's EOS frame or an orderly socket close). An idle/stalled
        producer raises StreamIdleTimeout (retryable — no bytes consumed); a
        timeout or close mid-frame raises TimeoutError/ConnectionError
        (fatal: the framed stream is desynced). Silently treating failures
        as end-of-stream would let training finish "successfully" on a
        truncated stream."""
        try:
            frame = self._recv_idle_aware()
        except StreamIdleTimeout:
            raise
        except socket.timeout:
            raise TimeoutError("timeout mid-frame — framed stream desynced")
        except OSError as e:
            raise ConnectionError(f"stream connection lost: {e}") from e
        if frame is None or frame == _EOS:
            return None
        return NDArrayMessage.decode(frame)

    getINDArray = receive

    def close(self):
        self._sock.close()


# ------------------------------------------------------------------- iterators
class StreamingDataSetIterator(DataSetIterator):
    """DataSetIterator over an array stream: each message is (features,
    labels[, features_mask, labels_mask]). ``num_batches`` bounds the stream
    (None → iterate until the producer closes). The conversion-function role
    of the reference's ``streaming/conversion`` is the optional ``convert``
    hook mapping raw message parts to a DataSet."""

    def __init__(self, consumer: NDArrayConsumer,
                 num_batches: Optional[int] = None,
                 convert: Optional[Callable[[List[np.ndarray]], DataSet]] = None):
        self.consumer = consumer
        self.num_batches = num_batches
        self.convert = convert
        self._seen = 0

    def __next__(self) -> DataSet:
        if self.num_batches is not None and self._seen >= self.num_batches:
            raise StopIteration
        parts = self.consumer.receive()
        if parts is None:
            raise StopIteration
        self._seen += 1
        if self.convert is not None:
            return self.convert(parts)
        return DataSet(*parts[:4])

    def reset(self):
        self._seen = 0  # a stream cannot rewind; counting restarts

    def async_supported(self):
        return True  # prefetch thread overlaps H2D with the network


class ServingRoute:
    """Reference ``routes/DL4jServeRouteBuilder.java``: consume feature
    arrays, run the model, publish predictions. ``run(max_messages=N)``
    processes N messages then returns; ``max_messages=None`` serves until
    the stream ends. ``start`` runs the same loop on a daemon thread; a
    fatal error is stored on ``self.error`` (and re-raised by ``check``)
    rather than dying silently inside the thread. Idle timeouts are NOT
    fatal — gaps between requests are normal for a serving endpoint."""

    def __init__(self, net, consumer: NDArrayConsumer,
                 publisher: NDArrayPublisher):
        self.net = net
        self.consumer = consumer
        self.publisher = publisher
        self.served = 0
        self.error: Optional[BaseException] = None

    def run(self, max_messages: Optional[int] = None):
        is_graph = hasattr(self.net, "_as_multi")  # ComputationGraph
        while max_messages is None or self.served < max_messages:
            try:
                parts = self.consumer.receive()
                if parts is None:
                    return  # clean end of the request stream
                if is_graph:
                    out = self.net.output(*parts)   # multi-input graphs
                elif len(parts) > 1:
                    # MLN: (features, mask) message shape
                    out = self.net.output(parts[0], mask=parts[1])
                else:
                    out = self.net.output(parts[0])
                outs = out if isinstance(out, (list, tuple)) else [out]
                self.publisher.publish([np.asarray(o) for o in outs])
                self.served += 1
            except StreamIdleTimeout:
                continue  # idle between requests — keep serving
            except Exception as e:  # noqa: BLE001 — surfaced via check()
                # ANY fatal error (desync, decode, inference shape mismatch)
                # is stored, not swallowed by the daemon thread
                self.error = e
                return

    def check(self):
        """Re-raise a fatal serving error captured on the daemon thread."""
        if self.error is not None:
            raise self.error

    def start(self, max_messages: Optional[int] = None) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(max_messages,),
                             daemon=True)
        t.start()
        return t
