"""Data normalizers with fit/transform/revert and serialization.

TPU-native equivalent of the ND4J normalizers the reference consumes everywhere
(``NormalizerStandardize``, ``NormalizerMinMaxScaler``,
``ImagePreProcessingScaler`` — external nd4j-api classes, persisted into model
zips as ``normalizer.bin`` by ``util/ModelSerializer.java:41``).
"""
from __future__ import annotations

import io
import json
from typing import Optional

import numpy as np


class Normalizer:
    """Base: fit on an iterator or arrays, transform/revert DataSets in place."""

    TYPE = "base"
    _REGISTRY = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        Normalizer._REGISTRY[cls.TYPE] = cls

    # -------------------------------------------------------------- fitting
    def fit(self, data):
        """``data``: DataSet or iterator of DataSets."""
        from .dataset import DataSet
        if isinstance(data, DataSet):
            self._fit_arrays([np.asarray(data.features)])
        else:
            feats = [np.asarray(ds.features) for ds in data]
            self._fit_arrays(feats)
        return self

    def _fit_arrays(self, arrays):
        raise NotImplementedError

    # ---------------------------------------------------------- application
    def transform(self, ds):
        ds.features = self._apply(np.asarray(ds.features))
        return ds

    def pre_process(self, ds):
        return self.transform(ds)

    preProcess = pre_process

    def revert(self, ds):
        ds.features = self._invert(np.asarray(ds.features))
        return ds

    def revert_features(self, features):
        return self._invert(np.asarray(features))

    revertFeatures = revert_features

    def _apply(self, x):
        raise NotImplementedError

    def _invert(self, x):
        raise NotImplementedError

    # ----------------------------------------------------------------- serde
    def _state(self) -> dict:
        raise NotImplementedError

    def _load_state(self, state: dict):
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        state = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                 for k, v in self._state().items()}
        return json.dumps({"type": self.TYPE, "state": state}).encode("utf-8")

    @staticmethod
    def from_bytes(data: bytes) -> "Normalizer":
        doc = json.loads(data.decode("utf-8"))
        cls = Normalizer._REGISTRY[doc["type"]]
        obj = cls()
        obj._load_state(doc["state"])
        return obj


def _stat_axes(ndim: int):
    """Axes reduced when computing per-feature statistics (ND4J semantics):
    2D [b, f] → per feature column; 3D [b, T, f] → per feature across batch AND
    time (so transform works for any sequence length); 4D NCHW [b, c, h, w] →
    per channel."""
    if ndim == 2:
        return (0,)
    if ndim == 3:
        return (0, 1)
    if ndim == 4:
        return (0, 2, 3)
    raise ValueError(f"Unsupported feature rank {ndim}")


def _bshape(ndim: int, stats: np.ndarray):
    """Shape that broadcasts per-feature stats against rank-``ndim`` data."""
    if ndim == 2:
        return (1, -1)
    if ndim == 3:
        return (1, 1, -1)
    return (1, -1, 1, 1)  # NCHW channel


class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature (reference NormalizerStandardize).
    Streaming moment accumulation over fit batches."""

    TYPE = "standardize"

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _fit_arrays(self, arrays):
        total = sum_ = sumsq = None
        for a in arrays:
            a = a.astype(np.float64)
            axes = _stat_axes(a.ndim)
            n = int(np.prod([a.shape[i] for i in axes]))
            s = a.sum(axis=axes)
            ss = (a * a).sum(axis=axes)
            if sum_ is None:
                total, sum_, sumsq = n, s, ss
            else:
                total, sum_, sumsq = total + n, sum_ + s, sumsq + ss
        self.mean = sum_ / total
        var = np.maximum(sumsq / total - self.mean ** 2, 0.0)
        self.std = np.sqrt(var)
        self.std[self.std < 1e-8] = 1.0

    def _apply(self, x):
        b = _bshape(x.ndim, self.mean)
        return ((x - self.mean.reshape(b)) / self.std.reshape(b)).astype(x.dtype)

    def _invert(self, x):
        b = _bshape(x.ndim, self.mean)
        return (x * self.std.reshape(b) + self.mean.reshape(b)).astype(x.dtype)

    def _state(self):
        return {"mean": self.mean, "std": self.std}

    def _load_state(self, s):
        self.mean = np.asarray(s["mean"])
        self.std = np.asarray(s["std"])


class NormalizerMinMaxScaler(Normalizer):
    """Scale each feature to [min_range, max_range] (reference class)."""

    TYPE = "minmax"

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def _fit_arrays(self, arrays):
        lo = hi = None
        for a in arrays:
            a = a.astype(np.float64)
            axes = _stat_axes(a.ndim)
            mn, mx = a.min(axis=axes), a.max(axis=axes)
            lo = mn if lo is None else np.minimum(lo, mn)
            hi = mx if hi is None else np.maximum(hi, mx)
        self.data_min, self.data_max = lo, hi

    def _scale(self):
        rng = self.data_max - self.data_min
        rng[rng < 1e-8] = 1.0
        return rng

    def _apply(self, x):
        b = _bshape(x.ndim, self.data_min)
        unit = (x - self.data_min.reshape(b)) / self._scale().reshape(b)
        out = unit * (self.max_range - self.min_range) + self.min_range
        return out.astype(x.dtype)

    def _invert(self, x):
        b = _bshape(x.ndim, self.data_min)
        unit = (x - self.min_range) / (self.max_range - self.min_range)
        out = unit * self._scale().reshape(b) + self.data_min.reshape(b)
        return out.astype(x.dtype)

    def _state(self):
        return {"min_range": self.min_range, "max_range": self.max_range,
                "data_min": self.data_min, "data_max": self.data_max}

    def _load_state(self, s):
        self.min_range = s["min_range"]
        self.max_range = s["max_range"]
        self.data_min = np.asarray(s["data_min"])
        self.data_max = np.asarray(s["data_max"])


class ImagePreProcessingScaler(Normalizer):
    """Pixel scaling [0, max_pixel] → [min, max] without fitting statistics
    (reference ImagePreProcessingScaler; default /255)."""

    TYPE = "image"

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.max_pixel = float(max_pixel)

    def _fit_arrays(self, arrays):
        pass  # stateless

    def _apply(self, x):
        return (x / self.max_pixel * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def _invert(self, x):
        return ((x - self.min_range) / (self.max_range - self.min_range)
                * self.max_pixel).astype(np.float32)

    def _state(self):
        return {"min_range": self.min_range, "max_range": self.max_range,
                "max_pixel": self.max_pixel}

    def _load_state(self, s):
        self.min_range = s["min_range"]
        self.max_range = s["max_range"]
        self.max_pixel = s["max_pixel"]
