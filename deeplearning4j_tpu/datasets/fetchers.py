"""Dataset fetchers: MNIST/EMNIST IDX parsing, IRIS, CIFAR-10 binaries.

TPU-native equivalents of reference ``deeplearning4j-core/.../datasets/``:
``MnistManager`` (IDX-file parser, ``datasets/mnist/MnistManager.java``),
``MnistDataFetcher`` (``datasets/fetchers/MnistDataFetcher.java:67``),
``IrisDataFetcher``, ``CifarDataSetIterator`` backing parser.

This build runs with zero network egress, so the reference's auto-download is
replaced by: (1) reading standard files from a local data directory
(``DL4J_TPU_DATA_DIR``, default ``~/.deeplearning4j_tpu``), and (2) a
deterministic synthetic mode for tests/benchmarks (shape- and dtype-faithful,
clearly labelled). Dropping the real IDX/CIFAR files into the data dir makes
the fetchers read genuine data with no code change.
"""
from __future__ import annotations

import gzip
import logging
import os
import struct
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

DATA_DIR_ENV = "DL4J_TPU_DATA_DIR"


def _warn_synthetic(name: str, where: str):
    """LOUD marker: nothing trained on this data supports accuracy claims.
    The produced DataSets also carry ``synthetic=True`` (see
    ``datasets/impl.py``) so downstream code can tell real from stand-in."""
    log.warning(
        "%s: no local files under %s — serving DETERMINISTIC SYNTHETIC "
        "stand-in data (shape/dtype-faithful gaussian-blob classes). "
        "Results are NOT comparable to the real dataset; drop the real "
        "files into the data dir to use them.", name, where)


def data_dir() -> str:
    return os.environ.get(DATA_DIR_ENV,
                          os.path.join(os.path.expanduser("~"),
                                       ".deeplearning4j_tpu"))


# ------------------------------------------------------------------ IDX files
IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
              0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8")}


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (optionally .gz) — the MNIST container format
    (reference ``MnistManager``/``MnistDbFile``). Uncompressed u8 files go
    through the native parser (ops/libdl4jtpu.so) when built."""
    from ..ops import native as _native
    fast = _native.idx_read(path)
    if fast is not None:
        return fast
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero1, zero2, dtype_code, ndim = struct.unpack("BBBB", f.read(4))
        if zero1 != 0 or zero2 != 0:
            raise ValueError(f"{path}: not an IDX file (bad magic)")
        if dtype_code not in IDX_DTYPES:
            raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=IDX_DTYPES[dtype_code])
    return data.reshape(shape)


def write_idx(path: str, array: np.ndarray):
    """Inverse of :func:`read_idx` (used by tests and data preparation)."""
    codes = {np.dtype(np.uint8): 0x08, np.dtype(np.int8): 0x09}
    code = codes.get(array.dtype)
    if code is None:
        raise ValueError(f"write_idx supports uint8/int8, got {array.dtype}")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(struct.pack("BBBB", 0, 0, code, array.ndim))
        f.write(struct.pack(">" + "I" * array.ndim, *array.shape))
        f.write(array.tobytes())


# ---------------------------------------------------------------------- MNIST
MNIST_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _find(base_dir, name) -> Optional[str]:
    for cand in (name, name + ".gz"):
        p = os.path.join(base_dir, cand)
        if os.path.exists(p):
            return p
    return None


class MnistDataFetcher:
    """Loads MNIST (or EMNIST subsets laid out the same way) as numpy arrays:
    features [n, 784] float32 in [0, 1], labels one-hot [n, 10].

    ``synthetic=True`` (or files absent + ``allow_synthetic``) generates a
    deterministic class-structured stand-in: per-class gaussian blob templates
    — classifiable, so training smoke tests show loss decreasing."""

    NUM_CLASSES = 10
    IMG = 28

    LABEL_OFFSET = 0  # EMNIST 'letters' labels are 1-indexed on disk

    def __init__(self, train: bool = True, binarize: bool = False,
                 shuffle: bool = False, seed: int = 123,
                 subdir: str = "mnist", synthetic: Optional[bool] = None,
                 num_synthetic: int = 2048):
        base = os.path.join(data_dir(), subdir)
        img_name, lbl_name = MNIST_FILES[train]
        img_path = _find(base, img_name)
        lbl_path = _find(base, lbl_name)
        have_files = img_path is not None and lbl_path is not None
        if synthetic is None:
            synthetic = not have_files
            if synthetic:
                _warn_synthetic(type(self).__name__, base)
        if synthetic:
            self.features, labels_idx = self._synthetic(seed, num_synthetic)
            self.is_synthetic = True
        else:
            imgs = read_idx(img_path).astype(np.float32) / 255.0
            self.features = imgs.reshape(imgs.shape[0], -1)
            # offset applies to on-disk labels only (synthetic are 0-indexed)
            labels_idx = read_idx(lbl_path).astype(np.int64) - self.LABEL_OFFSET
            self.is_synthetic = False
        if binarize:
            self.features = (self.features > 0.5).astype(np.float32)
        if labels_idx.min() < 0 or labels_idx.max() >= self.NUM_CLASSES:
            raise ValueError(
                f"Label ids outside [0, {self.NUM_CLASSES}) after offset "
                f"{self.LABEL_OFFSET}: range [{labels_idx.min()}, "
                f"{labels_idx.max()}] — wrong split or corrupt label file")
        self.labels = np.eye(self.NUM_CLASSES, dtype=np.float32)[labels_idx]
        if shuffle:
            rng = np.random.default_rng(seed)
            idx = rng.permutation(len(self.features))
            self.features = self.features[idx]
            self.labels = self.labels[idx]

    def _synthetic(self, seed, n):
        rng = np.random.default_rng(seed)
        d = self.IMG * self.IMG
        templates = rng.random((self.NUM_CLASSES, d)).astype(np.float32)
        labels = rng.integers(0, self.NUM_CLASSES, size=n)
        noise = rng.random((n, d)).astype(np.float32)
        feats = np.clip(0.6 * templates[labels] + 0.4 * noise, 0.0, 1.0)
        return feats.astype(np.float32), labels

    def total_examples(self) -> int:
        return len(self.features)


class EmnistDataFetcher(MnistDataFetcher):
    """EMNIST (reference ``EmnistDataFetcher``): same IDX layout under an
    ``emnist-<split>`` directory; class count depends on the split."""

    SPLITS = {"balanced": 47, "byclass": 62, "bymerge": 47, "digits": 10,
              "letters": 26, "mnist": 10}

    def __init__(self, split: str = "balanced", train: bool = True, **kw):
        if split not in self.SPLITS:
            raise ValueError(f"Unknown EMNIST split '{split}' "
                             f"(known: {sorted(self.SPLITS)})")
        self.NUM_CLASSES = self.SPLITS[split]
        # the 'letters' split is 1-indexed on disk (a=1..z=26); the canonical
        # class mapping is 0-indexed, so shift rather than wrap
        self.LABEL_OFFSET = 1 if split == "letters" else 0
        super().__init__(train=train, subdir=f"emnist-{split}", **kw)


# ----------------------------------------------------------------------- IRIS
class IrisDataFetcher:
    """IRIS (reference ``IrisDataFetcher``): 150×4 features, 3 classes. Served
    from scikit-learn's bundled copy (no network needed)."""

    def __init__(self):
        from sklearn.datasets import load_iris
        data = load_iris()
        self.features = data.data.astype(np.float32)
        self.labels = np.eye(3, dtype=np.float32)[data.target]

    def total_examples(self) -> int:
        return 150


# ------------------------------------------------------------------- CIFAR-10
class CifarDataFetcher:
    """CIFAR-10 binary-format parser (reference ``CifarDataSetIterator`` uses
    DataVec's loader): ``data_batch_{1..5}.bin`` / ``test_batch.bin``, each
    record = 1 label byte + 3072 pixel bytes (RGB planes). Features returned
    NCHW [n, 3, 32, 32] float32 in [0,1]; synthetic fallback as with MNIST."""

    NUM_CLASSES = 10

    def __init__(self, train: bool = True, seed: int = 123,
                 synthetic: Optional[bool] = None, num_synthetic: int = 1024):
        base = os.path.join(data_dir(), "cifar10")
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [_find(base, n) for n in names]
        have = all(p is not None for p in paths)
        if synthetic is None:
            synthetic = not have
            if synthetic:
                _warn_synthetic(type(self).__name__, base)
        if synthetic:
            rng = np.random.default_rng(seed)
            labels = rng.integers(0, 10, size=num_synthetic)
            templates = rng.random((10, 3, 32, 32)).astype(np.float32)
            noise = rng.random((num_synthetic, 3, 32, 32)).astype(np.float32)
            self.features = np.clip(0.6 * templates[labels] + 0.4 * noise, 0, 1)
            self.is_synthetic = True
        else:
            feats, labels = [], []
            for p in paths:
                raw = np.frombuffer(open(p, "rb").read(), np.uint8)
                rec = raw.reshape(-1, 3073)
                labels.append(rec[:, 0])
                feats.append(rec[:, 1:].reshape(-1, 3, 32, 32))
            labels = np.concatenate(labels)
            self.features = (np.concatenate(feats).astype(np.float32) / 255.0)
            self.is_synthetic = False
        self.labels = np.eye(10, dtype=np.float32)[labels]

    def total_examples(self) -> int:
        return len(self.features)


# ------------------------------------------------------- image-folder datasets
class _ImageFolderFetcher:
    """Shared machinery for LFW/TinyImageNet: a directory of
    ``<class-name>/<image files>`` (jpg/png/ppm via PIL), resized to the
    dataset's canonical shape; synthetic class-blob fallback when absent.
    Features NCHW float32 in [0, 1], labels one-hot."""

    IMG = 64
    CHANNELS = 3
    DEFAULT_CLASSES = 10

    def __init__(self, subdir: str, seed: int = 123,
                 synthetic: Optional[bool] = None, num_synthetic: int = 512,
                 num_classes: Optional[int] = None,
                 image_size: Optional[int] = None):
        self.IMG = int(image_size) if image_size else self.IMG
        base = os.path.join(data_dir(), subdir)
        class_dirs = (sorted(d for d in os.listdir(base)
                             if os.path.isdir(os.path.join(base, d)))
                      if os.path.isdir(base) else [])
        if synthetic is None:
            synthetic = not class_dirs
            if synthetic:
                _warn_synthetic(type(self).__name__, base)
        if synthetic:
            self.num_classes = int(num_classes or self.DEFAULT_CLASSES)
            rng = np.random.default_rng(seed)
            shape = (self.CHANNELS, self.IMG, self.IMG)
            labels = rng.integers(0, self.num_classes, size=num_synthetic)
            templates = rng.random((self.num_classes,) + shape).astype(np.float32)
            noise = rng.random((num_synthetic,) + shape).astype(np.float32)
            self.features = np.clip(0.6 * templates[labels] + 0.4 * noise, 0, 1)
            self.class_names = [f"class_{i}" for i in range(self.num_classes)]
            self.is_synthetic = True
        else:
            from PIL import Image
            exts = (".jpg", ".jpeg", ".png", ".ppm", ".bmp")
            feats, labels_list = [], []
            self.class_names = class_dirs
            self.num_classes = len(class_dirs)
            for ci, cname in enumerate(class_dirs):
                cdir = os.path.join(base, cname)
                # accept images directly in the class dir or one level down
                # (TinyImageNet's <wnid>/images/ layout)
                files = [os.path.join(cdir, fn)
                         for fn in sorted(os.listdir(cdir))
                         if fn.lower().endswith(exts)]
                for sub in sorted(os.listdir(cdir)):
                    subdir = os.path.join(cdir, sub)
                    if os.path.isdir(subdir):
                        files += [os.path.join(subdir, fn)
                                  for fn in sorted(os.listdir(subdir))
                                  if fn.lower().endswith(exts)]
                for path in files:
                    img = Image.open(path).convert("RGB")
                    img = img.resize((self.IMG, self.IMG))
                    arr = np.asarray(img, np.float32) / 255.0  # HWC
                    feats.append(arr.transpose(2, 0, 1))       # → CHW
                    labels_list.append(ci)
            if not feats:
                raise ValueError(
                    f"{type(self).__name__}: class directories exist under "
                    f"{base} but contain no image files ({'/'.join(exts)}) — "
                    f"expected <class>/<image> or <class>/<subdir>/<image>")
            self.features = np.stack(feats)
            labels = np.asarray(labels_list)
            self.is_synthetic = False
        self.labels = np.eye(self.num_classes, dtype=np.float32)[labels]

    def total_examples(self) -> int:
        return len(self.features)


class LFWDataFetcher(_ImageFolderFetcher):
    """Labeled Faces in the Wild (reference
    ``datasets/fetchers/LFWDataFetcher.java:1``: auto-download + per-person
    folders). Layout: ``<data_dir>/lfw/<person>/<image>.jpg``; canonical
    250×250 RGB, resized here to ``image_size`` (default 250 like the
    reference; pass 64 for fast experiments)."""

    IMG = 250
    DEFAULT_CLASSES = 5749  # people in full LFW

    def __init__(self, seed: int = 123, synthetic: Optional[bool] = None,
                 num_synthetic: int = 128, num_classes: Optional[int] = None,
                 image_size: Optional[int] = None):
        super().__init__("lfw", seed=seed, synthetic=synthetic,
                         num_synthetic=num_synthetic,
                         num_classes=num_classes or 10,
                         image_size=image_size)


class TinyImageNetFetcher(_ImageFolderFetcher):
    """Tiny ImageNet-200 (reference
    ``datasets/iterator/impl/TinyImageNetDataSetIterator.java``): 200 classes
    of 64×64 RGB. Layout: ``<data_dir>/tinyimagenet/<wnid>/<image>.jpg``."""

    IMG = 64
    DEFAULT_CLASSES = 200

    def __init__(self, seed: int = 123, synthetic: Optional[bool] = None,
                 num_synthetic: int = 512, num_classes: Optional[int] = None):
        super().__init__("tinyimagenet", seed=seed, synthetic=synthetic,
                         num_synthetic=num_synthetic,
                         num_classes=num_classes or self.DEFAULT_CLASSES)
