"""Multi-worker prefetch with device-put-ahead double buffering.

The step-anatomy report (``GET /profile``) splits ``step_ms`` from
``etl_ms``, and on input-bound workloads it shows the fit loops paying the
full host ETL latency on the training thread, then the host→device
transfer inside the step. This module is the production generalization of
:class:`~deeplearning4j_tpu.datasets.iterators.AsyncDataSetIterator`
(reference ``AsyncDataSetIterator.java``'s single prefetch thread):

- :class:`PrefetchIterator` — N worker threads pull from the base
  iterator. Pulls are serialized (python iterators are not thread-safe)
  and sequence-numbered, so the per-batch *processing* (decode, augment,
  padding, host cast, device transfer) runs in parallel while **batch
  order is preserved exactly**. Worker exceptions re-raise on the
  consumer thread at the position they occurred — a dead worker can
  never silently hang the training loop (bounded-timeout waits plus a
  liveness check).
- :class:`PrefetchDataSetIterator` — the DataSetIterator seam with
  **device-put-ahead**: while step *k* computes, batch *k+1* is already
  ``jax.device_put`` (optionally under the model's input
  ``Sharding`` when driving a ``parallel/`` mesh step), so the fit
  loops' ``etl_ms`` measures only a queue pop and the H2D transfer
  overlaps device compute instead of extending the step.
- :func:`wrap_for_training` — the containers' auto-wrap policy
  (``DL4J_TPU_PREFETCH_WORKERS``, default 2; ``0`` restores the fully
  synchronous path; ``DL4J_TPU_PUT_AHEAD=0`` keeps prefetch but moves
  the transfer back into the step; ``DL4J_TPU_PREFETCH_QUEUE`` bounds
  the ready-batch window — default 2 with put-ahead, so at most two
  batches pin device memory (double buffering), ``2 × workers``
  otherwise).

Monitor series (docs/OBSERVABILITY.md; all ride ``OP_TELEMETRY`` into
``GET /fleet`` and fold into the ``pipeline`` block of ``GET /profile``):

- ``input_queue_depth`` gauge — ready batches buffered ahead of the
  consumer (0 sustained = ETL-bound, full = compute-bound: healthy).
- ``input_wait_seconds`` histogram — how long ``next()`` actually
  blocked (the residual ETL the pipeline failed to hide).
- ``input_bytes_total`` / ``input_batches_total`` counters — host bytes
  and batches fed through the pipeline.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from .dataset import DataSet, DataSetIterator, MultiDataSet
from .iterators import AsyncDataSetIterator
from ..monitor import get_registry
from ..monitor.lockwatch import make_condition, make_lock

log = logging.getLogger(__name__)

__all__ = ["PrefetchIterator", "PrefetchDataSetIterator",
           "wrap_for_training"]

#: consumer/worker poll granularity (seconds): every blocking wait in this
#: module is bounded by this and re-checks stop/liveness, so no thread can
#: park forever on a condition a dead peer will never signal
_POLL_S = 0.2



class _Raise:
    """A worker-side error travelling the reorder buffer in batch order:
    batches produced BEFORE the failure are still delivered, then the
    exception re-raises on the consumer thread at its true position."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class _Epoch:
    """One epoch's worth of pipeline state. Workers only ever touch the
    epoch object they were born with (same ownership rule as
    ``AsyncDataSetIterator._worker``), so a ``reset()`` mid-epoch cannot
    leak stale batches into the next epoch. The PULL lock lives on the
    iterator, not here — a stale worker still blocked inside
    ``next(source)`` after a timed-out join must keep excluding the next
    epoch's workers from the shared (non-thread-safe) base."""

    __slots__ = ("source", "cond", "buf", "next_seq", "emit_seq",
                 "end_seq", "exc", "ended", "pulling", "source_done",
                 "stop", "threads")

    def __init__(self, source):
        self.source = source
        self.cond = make_condition("_Epoch.cond")  # guards buf/emit_seq/end_seq
        self.buf = {}                       # seq -> item | _Raise
        self.next_seq = 0
        self.emit_seq = 0
        self.end_seq = None                 # first seq past the stream end
        self.exc = None                     # pull-side error (raised at end_seq)
        self.ended = False                  # no further pulls
        self.pulling = 0                    # concurrent mode: in-flight pulls
        self.source_done = False            # concurrent mode: saw exhaustion
        self.stop = threading.Event()
        self.threads = []


class PrefetchIterator:
    """Order-preserving multi-worker prefetch over any iterator.

    ``transform`` runs on the worker threads — that is the parallel part.
    The pull itself is serialized under a lock by default (python
    iterators are not thread-safe); ``concurrent_pull=True`` lets the N
    workers call ``next(base)`` concurrently — REQUIRED for a slow
    *source* (disk decode, network fetch) to actually parallelize, and
    only sound when the base iterator is safe to call from multiple
    threads (``DataSetIterator.concurrent_pull_supported()`` is the
    opt-in; sequence numbers are still assigned under the lock, so
    delivery order is the pull-start order). ``queue_size`` bounds how
    many batches may sit ready ahead of the consumer (plus up to
    ``workers`` in-flight transforms), so a fast producer cannot balloon
    host/device memory.
    """

    def __init__(self, base, workers: int = 2, queue_size: Optional[int] = None,
                 transform: Optional[Callable] = None,
                 concurrent_pull: bool = False, finalize: Optional[Callable] = None,
                 name: str = "prefetch"):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self._base = base
        self._workers = int(workers)
        self._qsize = int(queue_size) if queue_size else max(2, 2 * workers)
        self._transform = transform
        #: runs AFTER admission into the bounded window (still on the
        #: worker thread) — the seam for work whose RESULT must stay
        #: bounded, e.g. the device put: at most ``queue_size`` finalized
        #: batches exist at once, while cheap pre-finalize batches may
        #: additionally sit with parked workers
        self._finalize = finalize
        self._concurrent = bool(concurrent_pull)
        self._name = name
        # iterator-level, NOT per-epoch: a stale worker still blocked
        # inside next(source) after a timed-out join keeps excluding the
        # next epoch's workers from the shared non-thread-safe base
        self._pull_lock = make_lock("PrefetchIterator._pull_lock")
        self._ep: Optional[_Epoch] = None
        self._handles = None

    # ------------------------------------------------------------ metrics
    def _metric_handles(self):
        if self._handles is None:
            reg = get_registry()
            self._handles = (
                reg.gauge("input_queue_depth",
                          "prefetched batches buffered ahead of the "
                          "training loop"),
                reg.histogram("input_wait_seconds",
                              "blocking wait for the next batch in the "
                              "input pipeline (seconds)", unit="s"),
                reg.counter("input_batches_total",
                            "batches served by the input pipeline"),
            )
        return self._handles

    # ------------------------------------------------------------- workers
    def _mark_end(self, ep: _Epoch, seq: int, exc=None):
        """Record the stream end (or the position of a failure): the
        smallest ending seq wins, and the exception travelling with it (if
        any) re-raises after every earlier batch has been delivered."""
        with ep.cond:
            if ep.end_seq is None or ep.end_seq > seq:
                ep.end_seq = seq
                ep.exc = exc
            ep.cond.notify_all()

    def _pull(self, ep: _Epoch):
        """One pull: returns ``(seq, item)``, or None when the stream (or
        this worker's reason to continue) ended.

        Serial mode: ``next(source)`` and the seq assignment both happen
        under the pull lock — order is exact, the first failure ends the
        stream at its true position.

        Concurrent mode: pulls run in parallel (the base declared itself
        pull-thread-safe) and seqs are assigned in pull-COMPLETION order,
        so no seq can ever map to a lost item. Exhaustion is only final
        once every in-flight pull has resolved (``ep.pulling`` drains to
        0) — the worker that raced past a sibling's StopIteration with
        the true last item still delivers it."""
        if not self._concurrent:
            with self._pull_lock:
                if ep.ended or ep.stop.is_set():
                    return None
                seq = ep.next_seq
                try:
                    item = next(ep.source)
                except StopIteration:
                    ep.ended = True
                    self._mark_end(ep, seq)
                    return None
                except Exception as e:
                    # pull failure: deliver the batches already produced,
                    # then re-raise at this position
                    ep.ended = True
                    self._mark_end(ep, seq, e)
                    return None
                ep.next_seq = seq + 1
            return seq, item
        with ep.cond:
            if ep.ended or ep.source_done:
                return None
            ep.pulling += 1
        try:
            item = next(ep.source)
        except StopIteration:
            self._concurrent_pull_resolved(ep, done=True)
            return None
        except Exception as e:
            self._concurrent_pull_resolved(ep, done=True, exc=e)
            return None
        with ep.cond:
            seq = ep.next_seq
            ep.next_seq = seq + 1
        self._concurrent_pull_resolved(ep, done=False)
        return seq, item

    @staticmethod
    def _concurrent_pull_resolved(ep: _Epoch, done: bool, exc=None):
        with ep.cond:
            ep.pulling -= 1
            if done:
                ep.source_done = True
                if exc is not None and ep.exc is None:
                    ep.exc = exc
            if ep.source_done and ep.pulling == 0 and ep.end_seq is None:
                # last in-flight pull resolved: every assigned seq has an
                # item, so the end is exactly the seq count — no drops
                ep.end_seq = ep.next_seq
            ep.cond.notify_all()

    def _worker_loop(self, ep: _Epoch):
        depth_g = self._metric_handles()[0]
        while not ep.stop.is_set():
            pulled = self._pull(ep)
            if pulled is None:
                return
            seq, item = pulled
            try:
                out = item if self._transform is None else self._transform(item)
            except Exception as e:
                out = _Raise(e)
                with ep.cond:
                    ep.ended = True     # no point producing past the error
                # the error IS the stream end at seq+1: the _Raise item
                # delivers (and re-raises) in order, later nexts stop
                self._mark_end(ep, seq + 1)
            # bounded put-ahead: wait for admission into the window, THEN
            # finalize (the device put) — at most queue_size finalized
            # batches hold device memory at once
            with ep.cond:
                while (not ep.stop.is_set()
                       and seq - ep.emit_seq >= self._qsize
                       and (ep.end_seq is None or seq < ep.end_seq)):
                    ep.cond.wait(_POLL_S)
                if ep.stop.is_set():
                    return
                if ep.end_seq is not None and seq >= ep.end_seq:
                    continue   # past the recorded end — drop, never deliver
            if self._finalize is not None and not isinstance(out, _Raise):
                try:
                    out = self._finalize(out)
                except Exception as e:
                    out = _Raise(e)
                    with ep.cond:
                        ep.ended = True
                    self._mark_end(ep, seq + 1)
            with ep.cond:
                if ep.stop.is_set():
                    return
                ep.buf[seq] = out
                depth_g.set(len(ep.buf))
                ep.cond.notify_all()

    # ------------------------------------------------------------ protocol
    def __iter__(self):
        self.reset()
        return self

    def reset(self):
        stale = self._stop_epoch()
        # base reset under the pull lock: a stale SERIAL-mode worker still
        # blocked inside next(source) (its join timed out) holds this lock,
        # so it cannot race the rewind. Bounded acquire: a source stuck
        # forever degrades to a loud warning, not a hang. Concurrent-mode
        # pulls run lock-free by contract — a stale one surviving the join
        # can still consume a post-rewind batch, so that degraded state is
        # warned about explicitly below instead of silently losing data.
        if self._pull_lock.acquire(timeout=5):
            try:
                source = iter(self._base)
            finally:
                self._pull_lock.release()
        else:
            log.warning(
                "%s: a previous epoch's worker is still blocked inside "
                "next(base) after 5s; resetting the base anyway", self._name)
            source = iter(self._base)
        if stale:
            log.warning(
                "%s: %d worker(s) from the previous epoch outlived their "
                "join; their in-flight pull may consume (and discard) a "
                "batch from the reset stream", self._name, stale)
        ep = _Epoch(source)
        for i in range(self._workers):
            t = threading.Thread(target=self._worker_loop, args=(ep,),
                                 name=f"{self._name}-{i}", daemon=True)
            ep.threads.append(t)
            t.start()
        self._ep = ep

    def _stop_epoch(self) -> int:
        """Stop and join the current epoch's workers; returns how many
        survived the bounded join (0 on the normal path)."""
        ep, self._ep = self._ep, None
        if ep is None:
            return 0
        ep.stop.set()
        with ep.cond:
            ep.cond.notify_all()
        for t in ep.threads:
            t.join(timeout=5)
        return sum(1 for t in ep.threads if t.is_alive())

    def shutdown(self):
        """Stop and join the current epoch's workers (reset-mid-epoch /
        end-of-fit cleanliness: no leaked threads)."""
        self._stop_epoch()

    def __next__(self):
        if self._ep is None:
            self.reset()
        ep = self._ep
        depth_g, wait_h, batches_c = self._metric_handles()
        t0 = time.perf_counter()
        with ep.cond:
            while True:
                if ep.emit_seq in ep.buf:
                    item = ep.buf.pop(ep.emit_seq)
                    ep.emit_seq += 1
                    depth_g.set(len(ep.buf))
                    ep.cond.notify_all()     # space freed for producers
                    break
                if ep.end_seq is not None and ep.emit_seq >= ep.end_seq:
                    if ep.exc is not None:
                        raise ep.exc
                    raise StopIteration
                if not any(t.is_alive() for t in ep.threads):
                    # liveness: every worker died without delivering the
                    # batch we are waiting for — never hang, raise the
                    # cause (or a loud stand-in for a hard thread death)
                    if ep.exc is not None:
                        raise ep.exc
                    raise RuntimeError(
                        f"{self._name}: all {self._workers} prefetch "
                        f"workers died without delivering batch "
                        f"{ep.emit_seq} or an end-of-stream marker")
                ep.cond.wait(_POLL_S)
        wait_h.observe(time.perf_counter() - t0)
        if isinstance(item, _Raise):
            raise item.exc
        batches_c.inc()
        return item


# ------------------------------------------------------- device-put-ahead
def _host_nbytes(ds) -> int:
    """Host bytes of a DataSet/MultiDataSet's arrays (pre-transfer)."""
    def nb(a):
        return int(getattr(a, "nbytes", 0) or 0) if a is not None else 0
    if isinstance(ds, MultiDataSet):
        total = sum(nb(a) for a in ds.features) + sum(nb(a) for a in ds.labels)
        for masks in (ds.features_masks, ds.labels_masks):
            if masks is not None:
                total += sum(nb(a) for a in masks)
        return total
    if isinstance(ds, DataSet):
        return (nb(ds.features) + nb(ds.labels) + nb(ds.features_mask)
                + nb(ds.labels_mask))
    return 0


def _device_view(ds, put):
    """A shallow DataSet/MultiDataSet whose arrays are device-resident.
    Built via ``__new__`` — the constructors call ``np.asarray``, which
    would pull a ``jax.Array`` straight back to the host. The caller's
    DataSet is never mutated, so the device buffers die with the view
    (one step), not with the user's dataset."""
    if isinstance(ds, MultiDataSet):
        view = MultiDataSet.__new__(MultiDataSet)
        view.features = [put(a) for a in ds.features]
        view.labels = [put(a) for a in ds.labels]
        view.features_masks = (None if ds.features_masks is None
                               else [put(a) for a in ds.features_masks])
        view.labels_masks = (None if ds.labels_masks is None
                             else [put(a) for a in ds.labels_masks])
        return view
    view = DataSet.__new__(DataSet)
    view.features = put(ds.features)
    view.labels = put(ds.labels)
    view.features_mask = put(ds.features_mask)
    view.labels_mask = put(ds.labels_mask)
    view.synthetic = getattr(ds, "synthetic", False)
    return view


class PrefetchDataSetIterator(PrefetchIterator, DataSetIterator):
    """Multi-worker prefetch over a ``DataSetIterator`` with optional
    device-put-ahead.

    ``device_put=True`` transfers each batch to the device ON THE WORKER
    THREAD, so the training loop receives device-resident arrays and its
    ``jnp.asarray`` is an identity — H2D overlaps the previous step's
    compute (double buffering, bounded by ``queue_size``).

    ``sharding`` (a ``jax.sharding.Sharding``) places batches under the
    model's input sharding — the seam for feeding
    ``parallel.sharding.data_parallel_step`` style mesh steps without a
    host re-placement inside the step.

    ``cache_device=True`` (``CacheMode.DEVICE`` models): instead of a
    fresh transfer per epoch, the worker warms
    :meth:`DataSet.device_arrays` on the BASE dataset, preserving the
    one-transfer-per-dataset cache semantics across fits.

    ``transform`` (host-side, runs before the device put) is where
    decode/augment/padding work parallelizes across workers.
    """

    def __init__(self, base: DataSetIterator, workers: int = 2,
                 queue_size: Optional[int] = None, device_put: bool = False,
                 sharding=None, cache_device: bool = False,
                 transform: Optional[Callable] = None,
                 concurrent_pull: Optional[bool] = None):
        self._user_transform = transform
        self._device_put = bool(device_put) or sharding is not None
        self._sharding = sharding
        self._cache_device = bool(cache_device)
        if concurrent_pull is None:
            # the base iterator's own declaration (DataSetIterator
            # protocol; default False — python iterators are not
            # thread-safe unless they say so)
            concurrent_pull = bool(getattr(base, "concurrent_pull_supported",
                                           lambda: False)())
        self._bytes_counter = get_registry().counter(
            "input_bytes_total",
            "host bytes fed through the input pipeline")
        # the device put is the FINALIZE stage: it runs only after
        # admission into the bounded window, so at most queue_size batches
        # hold device memory at once (workers parked for admission hold
        # cheap host batches, not HBM)
        super().__init__(base, workers=workers, queue_size=queue_size,
                         transform=self._prepare,
                         finalize=self._put_ahead if self._device_put
                         else None,
                         concurrent_pull=concurrent_pull,
                         name="input-prefetch")

    def _put(self, x):
        if x is None:
            return None
        import jax
        if self._sharding is not None:
            return jax.device_put(x, self._sharding)
        import jax.numpy as jnp
        return jnp.asarray(x)

    def _prepare(self, ds):
        if self._user_transform is not None:
            ds = self._user_transform(ds)
        self._bytes_counter.inc(_host_nbytes(ds))
        return ds

    def _put_ahead(self, ds):
        if self._cache_device and hasattr(ds, "device_arrays"):
            # warm the base dataset's CacheMode.DEVICE cache ahead of the
            # step; the fit loop's own device_arrays() call then hits it
            ds.device_arrays()
            return ds
        if isinstance(ds, (DataSet, MultiDataSet)):
            return _device_view(ds, self._put)
        return ds

    def batch(self):
        return self._base.batch()

    def async_supported(self):
        return False    # already asynchronous — never wrap again


def wrap_for_training(it, cache_device: bool = False):
    """The containers' fit-loop auto-wrap: returns ``(iterator, owned)``.
    ``owned`` is True when a new pipeline was created here — the caller
    must ``shutdown()`` it when fit ends (normally or by halt) so no
    worker threads outlive the loop.

    Dials (read per call, so benchmarks can A/B without re-imports):
    ``DL4J_TPU_PREFETCH_WORKERS`` (default 2; ``0`` → no wrap, fully
    synchronous), ``DL4J_TPU_PREFETCH_QUEUE`` (default 2 with put-ahead —
    true double buffering, so at most 2 batches pin device memory, the
    same residency the old transfer-in-step path peaked at; default
    ``2 × workers`` host batches otherwise), ``DL4J_TPU_PUT_AHEAD``
    (default on).
    """
    if not isinstance(it, DataSetIterator):
        return it, False
    if isinstance(it, (AsyncDataSetIterator, PrefetchDataSetIterator)):
        return it, False
    if not it.async_supported():
        return it, False
    try:
        workers = int(os.environ.get("DL4J_TPU_PREFETCH_WORKERS", "2"))
    except ValueError:
        workers = 2
    if workers <= 0:
        return it, False
    put_ahead = os.environ.get("DL4J_TPU_PUT_AHEAD", "1") \
        not in ("0", "false", "")
    qs = os.environ.get("DL4J_TPU_PREFETCH_QUEUE", "")
    if qs.isdigit() and int(qs) > 0:
        queue_size = int(qs)
    else:
        queue_size = 2 if put_ahead else None
    return PrefetchDataSetIterator(it, workers=workers,
                                   queue_size=queue_size,
                                   device_put=put_ahead,
                                   cache_device=cache_device), True
