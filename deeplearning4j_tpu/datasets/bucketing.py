"""Shape-bucketed batching: feed the jit cache a CLOSED set of signatures.

``jax.jit`` specializes per input shape, so a stream with ragged batch
sizes (tail batches) or ragged sequence lengths re-traces and re-compiles
the train step per distinct shape — the retrace-storm failure the
jitwatch detector (JAX003 machinery, docs/OBSERVABILITY.md "Compilation &
memory") diagnoses but cannot fix. This module is the fix the runbook
points at: :class:`ShapeBucketingDataSetIterator` pads every batch up to
a configurable set of bucket shapes (batch dim and, for sequence data,
the time dim), guaranteeing the jitted step sees at most
``len(batch_buckets) × len(time_buckets)`` signatures — measurable as the
jitwatch cache-miss ratio flattening after warmup.

Padding never trains, by the same masking conventions as
``datasets/records.py`` (``SequenceRecordReaderDataSetIterator`` pads
ragged sequences with zero features and a zero ``[b, T]`` mask):

- padded time steps get a zero ``features_mask``/``labels_mask`` entry;
- padded batch rows get a zero ``labels_mask`` row, so their loss
  contribution is exactly 0;
- the surviving mask entries are scaled by ``padded_b / real_b``
  (``_reduce`` in ``nn/losses.py`` divides by the minibatch size, which
  padding inflates — the rescale makes the bucketed loss AND its
  gradients bit-match the unpadded batch, so bucketing changes compile
  behavior, not training trajectories). ``rescale_loss=False`` keeps 0/1
  masks if exact reference ``average=true`` semantics over the padded
  size are wanted instead.

A ``labels_mask`` is synthesized for EVERY batch (all-real batches get a
constant one), and sequence batches always carry a ``features_mask`` —
mask presence is part of the jit signature, so an optional mask would
double the signature set the buckets exist to close.

Caveats: batch-statistics layers (BatchNormalization) see the padded
rows in their running statistics; evaluation treats mask values as
weights, so pad rows (weight 0) drop out there too. Compose with
:class:`~deeplearning4j_tpu.datasets.prefetch.PrefetchDataSetIterator`
to move the padding work off the training thread.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator, MultiDataSet

__all__ = ["ShapeBucketingDataSetIterator", "validate_buckets", "bucket_for"]


def validate_buckets(values: Sequence[int], kind: str = "batch"):
    """Normalize a bucket spec: sorted unique positive ints, loud on junk.
    Shared with the serving tier (``serving/batcher.py``), which buckets
    request batches by the same rules this iterator buckets dataset
    batches."""
    out = sorted({int(v) for v in values})
    if not out or out[0] < 1:
        raise ValueError(f"{kind} buckets must be positive ints, got "
                         f"{list(values)}")
    return out


def bucket_for(buckets, n: int, kind: str = "batch") -> int:
    """Smallest bucket admitting ``n``; oversize is rejected loudly (the
    caller must configure a bucket that fits, not silently truncate)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(
        f"{kind} size {n} exceeds the largest configured bucket "
        f"{buckets[-1]} — add a bucket >= {n} (buckets: {buckets})")


# intra-module shorthands (the public names are the API)
_buckets = validate_buckets
_bucket_for = bucket_for


def _pad_axis0(arr: np.ndarray, b: int, t: Optional[int] = None):
    """Zero-pad ``arr`` to ``b`` rows (and, when ``t`` is given and the
    array has a time axis, ``t`` steps)."""
    shape = list(arr.shape)
    shape[0] = b
    if t is not None and arr.ndim >= 2:
        shape[1] = t
    if shape == list(arr.shape):
        return arr
    out = np.zeros(shape, arr.dtype)
    sl = (slice(0, arr.shape[0]),) + (
        (slice(0, arr.shape[1]),) if t is not None and arr.ndim >= 2 else ())
    out[sl] = arr
    return out


class ShapeBucketingDataSetIterator(DataSetIterator):
    """Pad each batch up to the smallest admitting bucket shape.

    ``batch_buckets``: allowed batch sizes (e.g. ``(32, 64, 128)``).
    ``time_buckets``: allowed sequence lengths for rank-3 ``[b, T, f]``
    features (None → the time dim passes through unbucketed).
    ``rescale_loss``: scale the synthesized ``labels_mask`` by
    ``padded_b / real_b`` so the padded batch's loss/gradients equal the
    unpadded ones (see module docstring).
    """

    def __init__(self, base: DataSetIterator,
                 batch_buckets: Sequence[int],
                 time_buckets: Optional[Sequence[int]] = None,
                 rescale_loss: bool = True):
        self._base = base
        self._bb = _buckets(batch_buckets, "batch")
        self._tb = _buckets(time_buckets, "time") if time_buckets else None
        self._rescale = bool(rescale_loss)
        self._it = None

    @property
    def buckets(self):
        return list(self._bb)

    def __iter__(self):
        self._it = iter(self._base)
        return self

    def __next__(self) -> DataSet:
        if self._it is None:
            self._it = iter(self._base)
        return self.pad(next(self._it))

    def reset(self):
        self._base.reset()
        self._it = iter(self._base)

    def batch(self):
        return self._base.batch()

    # ------------------------------------------------------------- padding
    def pad(self, ds: DataSet) -> DataSet:
        if isinstance(ds, MultiDataSet):
            raise TypeError(
                "ShapeBucketingDataSetIterator pads DataSet streams; wrap "
                "the per-stream iterators before merging into MultiDataSets")
        f = np.asarray(ds.features)
        b = int(f.shape[0])
        tb = _bucket_for(self._bb, b, "batch")
        seq = f.ndim == 3
        T = int(f.shape[1]) if seq else None
        tt = (_bucket_for(self._tb, T, "time")
              if seq and self._tb is not None else T)

        out = DataSet(_pad_axis0(f, tb, tt if seq else None))
        out.synthetic = getattr(ds, "synthetic", False)
        if ds.labels is not None:
            l = np.asarray(ds.labels)
            # rank-2 labels are per-timestep SPARSE ids when they span the
            # sequence's time dim ([b, T] integer classes — the keras
            # sparse_categorical_crossentropy import shape); their time
            # dim pads with the features'. Otherwise rank-2 labels are
            # [b, n_classes] vectors and only the batch dim pads.
            per_step = l.ndim == 3 or (seq and l.ndim == 2
                                       and l.shape[1] == T)
            out.labels = _pad_axis0(l, tb, tt if per_step else None)
        if seq:
            fm = (np.asarray(ds.features_mask, np.float32)
                  if ds.features_mask is not None
                  else np.ones((b, T), np.float32))
            out.features_mask = _pad_axis0(fm, tb, tt)
        if ds.labels is not None:
            out.labels_mask = self._labels_mask(ds, b, tb, T, tt)
        return out

    def _labels_mask(self, ds: DataSet, b: int, tb: int,
                     T: Optional[int], tt: Optional[int]) -> np.ndarray:
        l = np.asarray(ds.labels)
        per_step = l.ndim == 3 or (T is not None and l.ndim == 2
                                   and l.shape[1] == T)
        if ds.labels_mask is not None:
            lm = np.asarray(ds.labels_mask, np.float32)
        elif per_step:
            # inherit the features mask so time padding in the LABELS also
            # stays out of the loss (records.py convention: one [b, T] mask)
            lm = (np.asarray(ds.features_mask, np.float32)
                  if ds.features_mask is not None
                  else np.ones((b, T), np.float32))
        else:
            lm = np.ones((b,), np.float32)
        if self._rescale and tb != b:
            # nn/losses._reduce divides by the PADDED minibatch size; the
            # rescale restores the unpadded batch's loss/gradient magnitude
            lm = lm * (tb / float(b))
        return _pad_axis0(lm, tb, tt if lm.ndim >= 2 else None)
