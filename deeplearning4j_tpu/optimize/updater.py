"""Network-level updater: per-layer updater resolution + gradient normalization.

TPU-native equivalent of reference ``nn/updater/BaseMultiLayerUpdater.java`` /
``UpdaterBlock.java`` and ``BaseOptimizer.updateGradientAccordingToParams``:
resolves which IUpdater governs each layer (global default or per-layer
override), applies gradient normalization (reference
``nn/conf/GradientNormalization.java`` modes) and produces updates inside the
jitted step. State is a pytree keyed like the param pytree — the functional
replacement for the reference's single flat updater-state buffer with views.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.conf import GradientNormalization


def normalize_gradients(grads_per_layer, mode, threshold):
    """grads_per_layer: dict layer_key -> param dict. Matches reference semantics:
    per-layer modes operate over all params of one layer; per-param-type modes
    operate on each param tensor separately."""
    if mode in (None, GradientNormalization.None_, "none"):
        return grads_per_layer

    def l2_of(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        if not leaves:
            return jnp.asarray(0.0)
        return jnp.sqrt(sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves))

    out = {}
    for lk, g in grads_per_layer.items():
        if not g:
            out[lk] = g
            continue
        if mode == GradientNormalization.RenormalizeL2PerLayer:
            norm = jnp.maximum(l2_of(g), 1e-8)
            out[lk] = jax.tree_util.tree_map(lambda x: x / norm, g)
        elif mode == GradientNormalization.RenormalizeL2PerParamType:
            out[lk] = {k: v / jnp.maximum(l2_of(v), 1e-8) for k, v in g.items()}
        elif mode == GradientNormalization.ClipElementWiseAbsoluteValue:
            t = threshold
            out[lk] = jax.tree_util.tree_map(lambda x: jnp.clip(x, -t, t), g)
        elif mode == GradientNormalization.ClipL2PerLayer:
            norm = l2_of(g)
            scale = jnp.where(norm > threshold, threshold / jnp.maximum(norm, 1e-8), 1.0)
            out[lk] = jax.tree_util.tree_map(lambda x: x * scale, g)
        elif mode == GradientNormalization.ClipL2PerParamType:
            def clip_one(v):
                norm = l2_of(v)
                scale = jnp.where(norm > threshold,
                                  threshold / jnp.maximum(norm, 1e-8), 1.0)
                return v * scale
            out[lk] = {k: clip_one(v) for k, v in g.items()}
        else:
            raise ValueError(f"Unknown gradient normalization mode {mode}")
    return out


class NetworkUpdater:
    """Maps each layer key to its resolved IUpdater and applies them jointly."""

    def __init__(self, layer_updaters):
        # layer_updaters: dict layer_key -> IUpdater
        self.layer_updaters = dict(layer_updaters)

    def init_state(self, params):
        return {k: self.layer_updaters[k].init_state(v) if v else {}
                for k, v in params.items()}

    def apply(self, state, grads, iteration):
        updates, new_state = {}, {}
        for k, g in grads.items():
            if not g:
                updates[k] = g
                new_state[k] = state.get(k, {})
                continue
            u, s = self.layer_updaters[k].apply(state[k], g, iteration)
            updates[k] = u
            new_state[k] = s
        return updates, new_state
