"""Full-batch convex optimizers: Solver dispatch, LBFGS, CG, line search.

TPU-native equivalent of reference ``optimize/`` (SURVEY.md §2.1
"Optimization"): ``Solver`` dispatch (``Solver.java:64`` → LBFGS :68, LineGD
:71, CG :74, SGD :77), ``BaseOptimizer.gradientAndScore``,
``BackTrackLineSearch``, termination conditions (``EpsTermination``,
``Norm2Termination``).

The minibatch SGD path lives in the network fit loop (the reference's
``StochasticGradientDescent``); these full-batch optimizers serve the same
niche as the reference's: small problems, fine-tuning, scientific workloads.
The loss+gradient evaluation is ONE jitted XLA computation over flattened
params; the line-search/direction logic runs on host (cheap scalar work).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..nn.conf import OptimizationAlgorithm
from ..monitor.jitwatch import monitored_jit

log = logging.getLogger(__name__)


def _flatten_params(params):
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    vec = np.concatenate([np.asarray(l, np.float64).ravel()
                          for _, l in leaves]) if leaves else np.zeros(0)
    meta = [(kp, np.shape(l), np.asarray(l).dtype) for kp, l in leaves]
    treedef = jax.tree_util.tree_structure(params)
    return vec, meta, treedef


def _unflatten_params(vec, meta, treedef):
    out = []
    pos = 0
    for _, shape, dtype in meta:
        n = int(np.prod(shape)) if shape else 1
        out.append(jnp.asarray(vec[pos:pos + n].reshape(shape), dtype=dtype))
        pos += n
    return jax.tree_util.tree_unflatten(treedef, out)


class BackTrackLineSearch:
    """Armijo backtracking (reference ``BackTrackLineSearch.java``)."""

    def __init__(self, c1: float = 1e-4, shrink: float = 0.5,
                 max_iterations: int = 20):
        self.c1 = c1
        self.shrink = shrink
        self.max_iterations = max_iterations

    def search(self, f, x, fx, gx, direction, step0: float = 1.0
               ) -> Tuple[float, float]:
        """Returns (step, f(x + step*d)). Falls back to the smallest step."""
        slope = float(gx @ direction)
        if slope >= 0:  # not a descent direction — caller should reset
            return 0.0, fx
        step = step0
        for _ in range(self.max_iterations):
            fnew = f(x + step * direction)
            if fnew <= fx + self.c1 * step * slope:
                return step, fnew
            step *= self.shrink
        return step, f(x + step * direction)


class BaseOptimizer:
    """Shared machinery: jitted loss/grad over flattened params."""

    def __init__(self, net, ds, max_iterations: int = 100, tol: float = 1e-8):
        from ..nn.gradientcheck import _loss_at
        self.net = net
        self.max_iterations = max_iterations
        self.tol = tol
        vec, self._meta, self._treedef = _flatten_params(net.params)
        self._x0 = vec

        def loss_on_tree(p):
            return _loss_at(net, p, ds)

        self._loss_tree = monitored_jit(loss_on_tree,
                                        name="solvers/loss")
        self._grad_tree = monitored_jit(
            jax.value_and_grad(loss_on_tree),
            name="solvers/value_and_grad")

    def f(self, x: np.ndarray) -> float:
        return float(self._loss_tree(_unflatten_params(x, self._meta,
                                                       self._treedef)))

    def f_g(self, x: np.ndarray) -> Tuple[float, np.ndarray]:
        val, g = self._grad_tree(_unflatten_params(x, self._meta,
                                                   self._treedef))
        gvec = np.concatenate([np.asarray(l, np.float64).ravel()
                               for l in jax.tree_util.tree_leaves(g)])
        return float(val), gvec

    def _commit(self, x):
        self.net.params = _unflatten_params(x, self._meta, self._treedef)

    def optimize(self) -> bool:
        raise NotImplementedError


class LineGradientDescent(BaseOptimizer):
    """Steepest descent + line search (reference ``LineGradientDescent``)."""

    def optimize(self) -> bool:
        x = self._x0.copy()
        ls = BackTrackLineSearch()
        fx, g = self.f_g(x)
        for it in range(self.max_iterations):
            d = -g
            step, fnew = ls.search(self.f, x, fx, g, d)
            if step == 0.0 or abs(fx - fnew) < self.tol:
                break
            x = x + step * d
            fx, g = self.f_g(x)
        self._commit(x)
        self.net.score_ = fx
        return True


class ConjugateGradient(BaseOptimizer):
    """Polak-Ribière+ nonlinear CG (reference ``ConjugateGradient``)."""

    def optimize(self) -> bool:
        x = self._x0.copy()
        ls = BackTrackLineSearch()
        fx, g = self.f_g(x)
        d = -g
        for it in range(self.max_iterations):
            step, fnew = ls.search(self.f, x, fx, g, d)
            if step == 0.0:
                d = -g  # restart with steepest descent
                step, fnew = ls.search(self.f, x, fx, g, d)
                if step == 0.0:
                    break
            x = x + step * d
            fprev, gprev = fx, g
            fx, g = self.f_g(x)
            if abs(fprev - fx) < self.tol:
                break
            beta = max(0.0, float(g @ (g - gprev) / max(gprev @ gprev, 1e-300)))
            d = -g + beta * d
        self._commit(x)
        self.net.score_ = fx
        return True


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS, two-loop recursion (reference ``LBFGS``)."""

    def __init__(self, net, ds, max_iterations: int = 100, tol: float = 1e-8,
                 m: int = 10):
        super().__init__(net, ds, max_iterations, tol)
        self.m = m

    def optimize(self) -> bool:
        x = self._x0.copy()
        ls = BackTrackLineSearch()
        fx, g = self.f_g(x)
        s_hist: List[np.ndarray] = []
        y_hist: List[np.ndarray] = []
        for it in range(self.max_iterations):
            # two-loop recursion
            q = g.copy()
            alphas = []
            for s, y in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / max(float(y @ s), 1e-300)
                a = rho * float(s @ q)
                alphas.append((a, rho))
                q -= a * y
            if y_hist:
                y_last, s_last = y_hist[-1], s_hist[-1]
                gamma = float(s_last @ y_last) / max(float(y_last @ y_last),
                                                     1e-300)
                q *= gamma
            for (a, rho), s, y in zip(reversed(alphas), s_hist, y_hist):
                b = rho * float(y @ q)
                q += (a - b) * s
            d = -q
            step, fnew = ls.search(self.f, x, fx, g, d,
                                   step0=1.0 if y_hist else
                                   min(1.0, 1.0 / max(np.abs(g).sum(), 1e-12)))
            if step == 0.0:
                break
            x_new = x + step * d
            f_new, g_new = self.f_g(x_new)
            s_hist.append(x_new - x)
            y_hist.append(g_new - g)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            converged = abs(fx - f_new) < self.tol
            x, fx, g = x_new, f_new, g_new
            if converged:
                break
        self._commit(x)
        self.net.score_ = fx
        return True


class Solver:
    """Dispatch facade (reference ``Solver.java:43``; algo switch :64-77)."""

    class Builder:
        def __init__(self):
            self._net = None
            self._max_iterations = 100

        def model(self, net):
            self._net = net
            return self

        def max_iterations(self, n):
            self._max_iterations = int(n)
            return self

        maxIterations = max_iterations

        def build(self):
            return Solver(self._net, self._max_iterations)

    @staticmethod
    def builder():
        return Solver.Builder()

    def __init__(self, net, max_iterations: int = 100):
        self.net = net
        self.max_iterations = max_iterations

    def optimize(self, ds) -> bool:
        """Full-batch optimization of the net on ``ds`` with the configured
        algorithm; SGD falls through to the network's minibatch fit."""
        algo = self.net.gc.optimization_algo
        if algo == OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            self.net.fit(ds)
            return True
        cls = {OptimizationAlgorithm.LBFGS: LBFGS,
               OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
               OptimizationAlgorithm.LINE_GRADIENT_DESCENT: LineGradientDescent}
        if algo not in cls:
            raise ValueError(f"Unknown optimization algorithm '{algo}'")
        return cls[algo](self.net, ds,
                         max_iterations=self.max_iterations).optimize()
