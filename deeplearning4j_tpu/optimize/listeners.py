"""Training listeners.

TPU-native equivalents of reference ``optimize/api/IterationListener.java`` /
``TrainingListener`` and the stock implementations in ``optimize/listeners/``
(SURVEY.md §2.1 "Listeners"): ScoreIterationListener, PerformanceListener
(samples/sec + batches/sec, ``PerformanceListener.java:19-23``),
CollectScoresIterationListener, TimeIterationListener, EvaluativeListener,
SleepyTrainingListener, ParamAndGradientIterationListener.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


class TrainingListener:
    """Listener bus contract. ``iteration_done`` fires once per minibatch with the
    scalar score; epoch/forward/backward hooks mirror the reference's
    TrainingListener."""

    def iteration_done(self, model, iteration, score):
        pass

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_backward_pass(self, model):
        pass

    def on_training_error(self, model, exception):
        """``fit`` is unwinding on ``exception`` — release any
        process-global resource this listener holds (e.g. an active
        ``jax.profiler`` trace window). Must not raise; a failing
        cleanup hook is logged and skipped, never masks the original
        error."""
        pass


IterationListener = TrainingListener  # reference naming alias


def dispatch_training_error(model, listeners, exception):
    """Best-effort ``on_training_error`` fan-out from the fit loops'
    except seam: every listener gets the hook even if an earlier one
    fails, and nothing here can mask the original exception."""
    for lst in listeners:
        hook = getattr(lst, "on_training_error", None)
        if hook is None:
            continue
        try:
            hook(model, exception)
        except Exception as e:
            log.warning("on_training_error hook of %r failed: %r",
                        lst, e)


class ScoreIterationListener(TrainingListener):
    """Reference ``ScoreIterationListener``: log score every N iterations."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, float(score))


class PerformanceListener(TrainingListener):
    """Reference ``PerformanceListener.java:19-23``: per-N-iteration throughput
    (samples/sec, batches/sec). ``last_samples_per_sec`` is the benchmark hook."""

    def __init__(self, frequency: int = 1, report_score: bool = False):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time = None
        self._samples = 0
        self._batches = 0
        self.last_samples_per_sec = 0.0
        self.last_batches_per_sec = 0.0

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        batch = getattr(model, "last_batch_size", 0) or 0
        self._samples += batch
        self._batches += 1
        if self._last_time is None:
            self._last_time = now
            self._samples = 0
            self._batches = 0
            return
        if self._batches >= self.frequency:
            dt = now - self._last_time
            if dt > 0:
                self.last_batches_per_sec = self._batches / dt
                if self._samples:
                    self.last_samples_per_sec = self._samples / dt
                    msg = (f"iteration {iteration}: "
                           f"{self.last_samples_per_sec:.1f} samples/sec, "
                           f"{self.last_batches_per_sec:.2f} batches/sec")
                else:
                    # model never reported last_batch_size: a 0.0
                    # samples/sec line would read as "training stalled" —
                    # report the rate we actually measured
                    msg = (f"iteration {iteration}: "
                           f"{self.last_batches_per_sec:.2f} batches/sec")
                if self.report_score:
                    msg += f", score {float(score):.5f}"
                log.info("%s", msg)
            self._last_time = now
            self._samples = 0
            self._batches = 0


class CollectScoresIterationListener(TrainingListener):
    """Reference ``CollectScoresIterationListener``: record (iteration, score)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """Reference ``TimeIterationListener``: ETA logging."""

    def __init__(self, iteration_count: int, frequency: int = 10):
        # perf_counter, not time.time(): a wall-clock jump (NTP step, DST)
        # would corrupt every subsequent ETA
        self.start = time.perf_counter()
        self.total = iteration_count
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration, score):
        if iteration and iteration % self.frequency == 0:
            elapsed = time.perf_counter() - self.start
            per_it = elapsed / max(iteration, 1)
            remaining = per_it * max(self.total - iteration, 0)
            log.info("iteration %d/%d, ETA %.1fs", iteration, self.total, remaining)


class SleepyTrainingListener(TrainingListener):
    """Reference ``SleepyTrainingListener``: throttle iterations (debug tool)."""

    def __init__(self, sleep_ms: int = 0):
        self.sleep_ms = sleep_ms

    def iteration_done(self, model, iteration, score):
        if self.sleep_ms:
            time.sleep(self.sleep_ms / 1000.0)


class EvaluativeListener(TrainingListener):
    """Reference ``EvaluativeListener``: run evaluation every N iterations."""

    def __init__(self, iterator, frequency: int = 100, evaluation_factory=None):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.evaluation_factory = evaluation_factory
        self.last_evaluation = None

    def iteration_done(self, model, iteration, score):
        if iteration and iteration % self.frequency == 0:
            self.last_evaluation = model.evaluate(self.iterator)
            log.info("Evaluation at iteration %d:\n%s", iteration,
                     self.last_evaluation.stats())


class ParamAndGradientIterationListener(TrainingListener):
    """Per-iteration parameter/update statistics (reference
    ``optimize/listeners/ParamAndGradientIterationListener.java``: mean,
    min/max, mean-abs of params and gradients, tab-delimited to console/
    file/log every N iterations).

    The jitted step doesn't expose raw gradients to the listener bus (it
    applies the updater in-graph), so the second stat family reports the
    applied UPDATE (param delta between iterations — the reference's
    gradient column is likewise the updater-transformed value by the time
    listeners fire). Columns: score, then per-family mean/min/max/meanAbs.
    """

    def __init__(self, iterations: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs_value: bool = True, output_to_console: bool = True,
                 file_path: Optional[str] = None, delimiter: str = "\t"):
        self.frequency = max(1, iterations)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs = print_mean_abs_value
        self.output_to_console = output_to_console
        self.file_path = file_path
        self.delimiter = delimiter
        self.rows = []          # collected rows (always, for programmatic use)
        self._prev_flat = None
        self._wrote_header = False

    def _stats(self, flat):
        out = []
        if self.print_mean:
            out.append(float(flat.mean()))
        if self.print_min_max:
            out += [float(flat.min()), float(flat.max())]
        if self.print_mean_abs:
            out.append(float(np.abs(flat).mean()))
        return out

    def _header(self):
        cols = ["iteration", "score"]
        for fam in ("param", "update"):
            if self.print_mean:
                cols.append(f"{fam}Mean")
            if self.print_min_max:
                cols += [f"{fam}Min", f"{fam}Max"]
            if self.print_mean_abs:
                cols.append(f"{fam}MeanAbsValue")
        return cols

    def iteration_done(self, model, iteration, score):
        import jax

        flat = np.concatenate([np.asarray(x).ravel() for x in
                               jax.tree_util.tree_leaves(model.params)])
        if iteration % self.frequency != 0:
            self._prev_flat = flat
            return
        update = (flat - self._prev_flat if self._prev_flat is not None
                  else np.zeros_like(flat))
        self._prev_flat = flat
        row = [iteration, float(score)] + self._stats(flat) + \
            self._stats(update)
        self.rows.append(row)
        lines = []
        if self.print_header and not self._wrote_header:
            lines.append(self.delimiter.join(self._header()))
            self._wrote_header = True
        lines.append(self.delimiter.join(str(v) for v in row))
        text = "\n".join(lines)
        if self.output_to_console:
            print(text)
        if self.file_path:
            try:
                with open(self.file_path, "a") as fh:
                    fh.write(text + "\n")
            except OSError as e:  # reference caps write-failure messages
                log.warning("ParamAndGradientIterationListener write failed: %s", e)


class CheckpointListener(TrainingListener):
    """Periodic checkpointing with rotation + resume.

    The 0.9.x reference persists models only through early-stopping savers
    (``earlystopping/saver/``) and manual ``ModelSerializer`` calls; its
    successor line added exactly this listener (periodic saves with
    keep-last rotation). Operationally it is the missing piece of the
    checkpoint/resume story (SURVEY.md §5): attach it, train, and
    ``last_checkpoint(dir)`` restores an exact-resume model (updater state
    included — ModelSerializer round-trips it) after any interruption.

    ``save_every_n_iterations`` / ``save_every_n_epochs``: either or both;
    ``keep_last``: how many checkpoint files to retain (older files are
    deleted — set 0/None to keep everything)."""

    def __init__(self, directory: str, save_every_n_iterations: int = 0,
                 save_every_n_epochs: Optional[int] = None,
                 keep_last: int = 3, save_updater: bool = True):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.every_iter = int(save_every_n_iterations or 0)
        if save_every_n_epochs is None:
            # default: epoch cadence only when no iteration cadence was
            # requested — otherwise epoch saves would consume keep_last
            # slots and evict the files the user actually asked for
            save_every_n_epochs = 0 if self.every_iter else 1
        self.every_epoch = int(save_every_n_epochs or 0)
        self.keep_last = keep_last
        self.save_updater = save_updater
        # adopt any pre-existing checkpoints (resume-after-interruption):
        # the file index must keep increasing or last_checkpoint() would
        # prefer a stale pre-crash file, and rotation must prune old saves
        self.saved = self.checkpoints(directory)
        self._counter = 0
        for p in self.saved:
            idx = self._index_of(p)
            if idx is not None:
                self._counter = max(self._counter, idx)
        # orphaned .tmp from a hard crash mid-write: clean on adoption
        for name in os.listdir(directory):
            if name.startswith("checkpoint-") and name.endswith(".zip.tmp"):
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:
                    pass
        # next-save threshold: iteration_count can advance by >1 per
        # iteration_done (iterations(n) scans, TBPTT segments) — an exact
        # modulo would fire at the lcm of stride and cadence instead
        self._next_iter_save = self.every_iter

    # -- hooks ------------------------------------------------------------
    def iteration_done(self, model, iteration, score):
        if self.every_iter and iteration + 1 >= self._next_iter_save:
            self._save(model, f"iter-{iteration + 1}")
            self._next_iter_save = iteration + 1 + self.every_iter

    def on_epoch_end(self, model, epoch):
        if self.every_epoch and (epoch + 1) % self.every_epoch == 0:
            self._save(model, f"epoch-{epoch + 1}")

    # -- mechanics --------------------------------------------------------
    def _save(self, model, tag):
        from ..utils.model_serializer import ModelSerializer

        self._counter += 1
        path = os.path.join(self.directory,
                            f"checkpoint-{self._counter:05d}-{tag}.zip")
        tmp = path + ".tmp"
        try:
            ModelSerializer.write_model(model, tmp,
                                        save_updater=self.save_updater)
            os.replace(tmp, path)  # atomic: a crash never leaves a torn file
        except Exception as e:
            # a failed save (disk full, permissions, an unserializable
            # config field) must not abort the training loop — log and
            # keep training; no torn files left
            log.warning("CheckpointListener: save to %s failed: %s", path, e)
            try:
                if os.path.exists(tmp):
                    os.remove(tmp)
            except OSError:
                pass
            return None
        self.saved.append(path)
        if self.keep_last:
            while len(self.saved) > self.keep_last:
                old = self.saved.pop(0)
                try:
                    os.remove(old)
                except OSError:
                    pass
        return path

    @staticmethod
    def _index_of(path):
        try:
            return int(os.path.basename(path).split("-")[1])
        except (IndexError, ValueError):
            return None

    @classmethod
    def checkpoints(cls, directory):
        """Checkpoint paths in save order — sorted by the parsed numeric
        file index (lexicographic order breaks past 99999 saves)."""
        if not os.path.isdir(directory):
            return []
        paths = [os.path.join(directory, n) for n in os.listdir(directory)
                 if n.startswith("checkpoint-") and n.endswith(".zip")]
        return sorted(paths, key=lambda p: (cls._index_of(p) or 0, p))

    @classmethod
    def last_checkpoint(cls, directory):
        """Restore the newest checkpoint (exact resume: params + updater
        state), or None when the directory holds none."""
        from ..utils.model_serializer import ModelSerializer

        paths = cls.checkpoints(directory)
        return ModelSerializer.restore_model(paths[-1]) if paths else None
