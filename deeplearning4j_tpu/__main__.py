"""``python -m deeplearning4j_tpu`` → the operational CLI (main.py)."""
from .main import main

raise SystemExit(main())
