"""HTTP/JSON inference front door for the serving tier.

Sibling of the training UI server (``ui/server.py`` — same stdlib
``ThreadingHTTPServer``, same :class:`~deeplearning4j_tpu.ui.server.
JsonRequestHandler` plumbing and POST Content-Length cap), serving:

- ``POST /v1/models/<name>/predict`` — body ``{"inputs": [[...], ...],
  "deadline_ms": optional}``; responds ``{"model", "outputs",
  "latency_ms"}``. Typed failures map onto HTTP: unknown model → 404,
  malformed body/shape → 400, :class:`OverloadedError` (queue at
  capacity / draining) → **429** with a ``Retry-After`` hint,
  :class:`DeadlineExceededError` → **504**, anything else → 500.
- ``GET /v1/models`` — hosted-model listing with queue depth and config
  (since ISSUE 11 each row also carries the model's serving ``precision``
  and response-cache occupancy — docs/SERVING.md "Data-plane tuning").
- ``GET /v1/models/<name>`` — one model's row.
- ``GET /metrics`` / ``GET /healthz`` / ``GET /profile`` /
  ``GET /alerts`` / ``GET /history`` / ``GET /trace`` /
  ``GET /events`` / ``GET /fleet`` / ``GET /fleet/trace`` /
  ``GET /telemetry`` / ``GET /incidents`` / ``GET /incidents/<id>``
  — the monitor endpoints (shared ``_monitor_get``
  routing) re-exposed here so a serving replica is scrapeable (and
  alertable) without a training UI attached; ``/profile`` carries the
  per-model ``serving`` block (p50/p99 latency, QPS, batch-size
  distribution, queue depth), and ``/telemetry`` is the one-round-trip
  bundle the fleet :class:`~deeplearning4j_tpu.monitor.collector.
  TelemetryCollector` scrapes.

Requests are request-scope traced: the ``X-DL4J-Trace`` header
(``<trace hex>:<span hex>``, the proto-v2 ``SpanContext`` ids) joins the
caller's trace, responses carry the request's ``trace_id``, and the
worst recent latencies latch their trace ids as histogram exemplars for
the alert engine.

Each handler thread blocks on its request's Future while the model's
batching scheduler coalesces concurrent requests into one padded
forward — the HTTP layer adds no batching logic of its own.
``stop(drain=True)`` is the graceful path: stop accepting, drain every
model's queue (no accepted request is dropped), then close the socket.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..monitor.tracer import SpanContext, get_tracer
from ..ui.server import JsonRequestHandler
from .batcher import (DeadlineExceededError, ModelNotFoundError,
                      OverloadedError)
from .registry import ModelRegistry

__all__ = ["InferenceServer", "TRACE_HEADER", "PROBE_HEADER",
           "parse_trace_header"]

#: request trace-context header: ``<trace_id hex>:<span_id hex>`` — the
#: same 64-bit ids the paramserver proto v2 FLAG_TRACE frame carries
#: (``struct "<QQ"`` there, hex here), so one trace id follows a request
#: across HTTP serving and paramserver hops alike
TRACE_HEADER = "X-DL4J-Trace"

#: probe-traffic marker (``X-DL4J-Probe: 1``): the request bypasses the
#: response cache end to end — a synthetic probe answered from the LRU
#: would prove nothing about the live model path, and probes must not
#: evict real traffic's cached entries either (monitor/probes.py sets
#: this on every golden-set replay)
PROBE_HEADER = "X-DL4J-Probe"


def parse_trace_header(value: Optional[str]) -> Optional[SpanContext]:
    """``"<trace hex>:<span hex>"`` → :class:`SpanContext` (None on a
    missing/malformed header — a bad trace header must never fail the
    request it decorates)."""
    if not value:
        return None
    try:
        tid_s, _, sid_s = value.partition(":")
        tid, sid = int(tid_s, 16), int(sid_s, 16)
        if not (0 < tid < 1 << 64 and 0 < sid < 1 << 64):
            return None
        return SpanContext(tid, sid)
    except ValueError:
        return None


class _ServingHandler(JsonRequestHandler):
    registry: ModelRegistry = None     # bound by the server factory

    # ------------------------------------------------------------- routes
    def do_GET(self):
        url = urlparse(self.path)
        if self._monitor_get(url, parse_qs(url.query)):
            return                  # shared /metrics /healthz /telemetry …
        parts = [p for p in url.path.split("/") if p]
        if parts == ["v1", "models"]:
            self._json({"models": self.registry.list_models()})
            return
        if len(parts) == 3 and parts[:2] == ["v1", "models"]:
            try:
                self._json(self.registry.get(parts[2]).stats())
            except ModelNotFoundError:
                self._json({"error": f"model {parts[2]!r} not found",
                            "models": self.registry.names()}, 404)
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if not (len(parts) == 4 and parts[:2] == ["v1", "models"]
                and parts[3] == "predict"):
            self._json({"error": "not found"}, 404)
            return
        body = self._post_body()
        if body is None:
            return
        name = parts[2]
        try:
            doc = json.loads(body)
            inputs = np.asarray(doc["inputs"], np.float32)
            deadline_ms = doc.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)   # non-numeric → 400 here,
                if deadline_ms <= 0:               # not a 500 at submit
                    raise ValueError("deadline_ms must be > 0")
            if inputs.ndim < 1 or inputs.shape[0] < 1:
                raise ValueError("inputs must be a non-empty [b, ...] "
                                 "array")
        except (KeyError, TypeError, ValueError) as e:
            self._json({"error": f"bad request body: {e}"}, 400)
            return
        t0 = time.perf_counter()
        # request-scoped trace: join the caller's context when the
        # X-DL4J-Trace header carries one, else the span mints a fresh
        # trace — either way the batcher stamps the request with THIS
        # span's context, so /trace shows http/predict → queue_wait →
        # (linked) serving/flush as one causal chain per request
        remote = parse_trace_header(self.headers.get(TRACE_HEADER))
        probe = self.headers.get(PROBE_HEADER) not in (None, "", "0")
        ctx = None
        # probe requests tag their span (visible on /trace) and ride the
        # cache-bypass path — never answered from, never stored into, the
        # response LRU
        span_args = {"model": name}
        if probe:
            span_args["probe"] = True
        try:
            with get_tracer().span("http/predict", cat="serving",
                                   parent=remote, **span_args) as ctx:
                fut = self.registry.submit(name, inputs,
                                           deadline_ms=deadline_ms,
                                           trace_ctx=ctx,
                                           cache_bypass=probe)
                # generous transport-level backstop — per-request shedding
                # is the batcher's deadline, not this timeout
                out = fut.result(timeout=max(
                    60.0, (deadline_ms or 0.0) / 1e3 + 30.0))
        except ModelNotFoundError:
            self._json({"error": f"model {name!r} not found",
                        "models": self.registry.names()}, 404)
            return
        except ValueError as e:            # oversize request, bad shape
            self._json({"error": str(e)}, 400)
            return
        except OverloadedError as e:
            self._json({"error": str(e)}, 429,
                       headers={"Retry-After": "1"})
            return
        except DeadlineExceededError as e:
            self._json({"error": str(e)}, 504)
            return
        except Exception as e:             # model blew up: the caller
            self._json({"error": f"{type(e).__name__}: {e}"}, 500)
            return
        self._json({"model": name, "outputs": np.asarray(out).tolist(),
                    "latency_ms": round((time.perf_counter() - t0) * 1e3,
                                        3),
                    "trace_id": f"{ctx.trace_id:x}"})


class InferenceServer:
    """The serving front door: a :class:`ModelRegistry` behind HTTP.

    ``InferenceServer().start(port=0)`` returns the bound port; bind is
    loopback by default (the endpoints are unauthenticated — widen to
    ``"0.0.0.0"`` only on a trusted network, exactly like ``UIServer``).
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 port: int = 8500, host: str = "127.0.0.1"):
        self.registry = registry if registry is not None else ModelRegistry()
        self.port = port
        self.host = host
        self._httpd = None
        self._thread = None

    def register(self, name: str, model, **config):
        """Convenience passthrough to the registry."""
        return self.registry.register(name, model, **config)

    def start(self, port: Optional[int] = None,
              host: Optional[str] = None) -> int:
        if self._httpd is not None:
            return self.port
        if port is not None:
            self.port = port
        if host is not None:
            self.host = host
        handler = type("BoundServingHandler", (_ServingHandler,),
                       {"registry": self.registry})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="inference-server")
        self._thread.start()
        return self.port

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Graceful shutdown: stop the accept loop first (no NEW requests
        land), then drain every model's batcher so every ACCEPTED request
        resolves — handler threads blocked on their futures finish writing
        their responses — and finally close the listening socket."""
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self.registry.close_all(drain=drain, timeout=timeout)
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
