"""Production inference serving tier (docs/SERVING.md).

The "millions of users" path the ROADMAP names: the reference's
``ParallelInference.java`` observer threads reborn as a continuous-
batching scheduler behind an HTTP front door.

- :class:`ContinuousBatcher` — coalesces concurrent single-example
  requests into shape-bucketed padded batches (one jitted forward per
  flush, a CLOSED jit-signature set under any request-size churn —
  jitwatch-enforced), with per-request deadlines, a max-linger bound so
  a lone request is never stranded, and bounded-queue admission control
  (typed :class:`OverloadedError` / :class:`DeadlineExceededError`).
- :class:`ModelRegistry` / :class:`ServedModel` — multi-model hosting:
  zoo models and ``keras/`` imports side by side, each with its own
  batcher, queue caps, and per-model latency/QPS/batch-size series in
  the monitor registry (the ``serving`` block on ``GET /profile``).
  Per-model data-plane dials (ISSUE 11, docs/SERVING.md "Data-plane
  tuning"): ``precision="bf16"`` serves the forward in bfloat16 (f32
  responses, its own closed jit-signature set, half the wire bytes) and
  ``cache_size=`` puts a content-addressed response LRU in front of the
  queue — a hit skips queue and flush entirely. The flush path itself
  is device-resident: one h2d transfer of the real examples, on-device
  padding into a donation-recycled bucket buffer, on-device slicing,
  one d2h transfer (``serving/pad``/``serving/transfer`` spans +
  ``serving_pad_ms``/``serving_transfer_ms`` histograms prove the
  split).
- :class:`InferenceServer` — the HTTP/JSON front door
  (``POST /v1/models/<name>/predict``, ``GET /v1/models``, plus the
  monitor scrape endpoints incl. ``/alerts`` and ``/history``), mapping
  the typed errors onto 429/504 and draining gracefully on ``stop()`` so
  no accepted request is dropped.

Every request is **request-scope traced**: the front door joins the
caller's ``X-DL4J-Trace`` header (:data:`TRACE_HEADER` — the proto-v2
``SpanContext`` ids in hex) or mints a fresh trace, the batcher records
a ``serving/queue_wait`` span linked to the shared ``serving/flush``
span, and the latency histogram latches the trace id of the worst recent
samples as **exemplars** — a firing p99 alert (monitor/alerts.py) names
a trace resolvable against ``GET /trace``.

``ParallelInference`` (``parallel/inference.py``) delegates its BATCHED
accumulate-then-flush path to the same scheduler.
"""
from .batcher import (ContinuousBatcher, DeadlineExceededError,
                      ModelNotFoundError, OverloadedError)
from .registry import ModelRegistry, ServedModel, DEFAULT_BATCH_BUCKETS
from .server import (InferenceServer, PROBE_HEADER, TRACE_HEADER,
                     parse_trace_header)

__all__ = ["ContinuousBatcher", "ModelRegistry", "ServedModel",
           "InferenceServer", "OverloadedError", "DeadlineExceededError",
           "ModelNotFoundError", "DEFAULT_BATCH_BUCKETS", "TRACE_HEADER",
           "PROBE_HEADER", "parse_trace_header"]
