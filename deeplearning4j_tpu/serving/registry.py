"""Multi-model hosting: named models, each behind its own batcher.

The reference serves one net per ``ParallelInference`` instance; a
production front door hosts MANY — zoo models and ``keras/`` imports side
by side — so the registry maps ``name -> ServedModel``, where each entry
owns its own :class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher`
(independent queues, buckets, deadlines) while sharing one optional
``max_in_flight`` semaphore so N models cannot pile N concurrent forwards
onto one device. Per-model latency/QPS/batch-size/queue-depth series land
in the monitor registry under a ``model`` label and roll up into the
``serving`` block of ``GET /profile`` (docs/OBSERVABILITY.md).

Anything with an ``output(features)`` method serves: ``MultiLayerNetwork``,
``ComputationGraph``, a ``keras.model_import`` product, or a test stub.
Zoo models may be passed un-initialized (``ZooModel`` instances are
``init()``-ed on registration).
"""
from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..monitor.lockwatch import make_lock
from .batcher import ContinuousBatcher, ModelNotFoundError

__all__ = ["ServedModel", "ModelRegistry"]

#: default batch buckets: powers of two up to a modest serving batch —
#: small enough that a lone request pads little, closed enough that the
#: jit cache stays warm under any request-size churn
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)


class ServedModel:
    """One hosted model: the net, its batcher, and its serving config."""

    def __init__(self, name: str, model, *,
                 batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 time_buckets: Optional[Sequence[int]] = None,
                 max_queue_examples: int = 256,
                 linger_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = 2000.0,
                 input_shape: Optional[Sequence[int]] = None,
                 warmup: bool = False,
                 qps_window_s: float = 10.0,
                 in_flight: Optional[threading.Semaphore] = None):
        if hasattr(model, "conf") and not hasattr(model, "output"):
            model = model.init()          # a ZooModel, not yet built
        if not callable(getattr(model, "output", None)):
            raise TypeError(
                f"model {name!r} has no callable output(features) — pass "
                f"an initialized network (MultiLayerNetwork, "
                f"ComputationGraph, keras import) or a ZooModel")
        self.name = name
        self.model = model
        self.input_shape = (tuple(int(d) for d in input_shape)
                            if input_shape is not None else None)
        self.batcher = ContinuousBatcher(
            self._forward, name=name,
            batch_buckets=batch_buckets, time_buckets=time_buckets,
            max_queue_examples=max_queue_examples, linger_ms=linger_ms,
            default_deadline_ms=default_deadline_ms,
            queue_policy="reject", in_flight=in_flight,
            metrics_label=name, qps_window_s=qps_window_s)
        if warmup:
            self.warm()

    def warm(self):
        """Pre-compile every bucket signature (synchronously, on the
        registering thread): after this, request-size churn NEVER
        compiles — the whole closed signature set is already in the jit
        cache, so serving cold-start is paid at registration, not on the
        first unlucky requests. Requires ``input_shape`` (the per-example
        trailing shape, e.g. ``(784,)`` or ``(T, features)``).

        Note the jitwatch interplay: warming ``>= DL4J_TPU_RETRACE_
        THRESHOLD`` (default 3) buckets back-to-back is, to the
        per-instance storm detector, indistinguishable from churn — it
        logs one storm during warmup. Size the bucket set below the
        threshold, or raise the threshold for serving processes; steady
        state is storm-free either way (docs/SERVING.md)."""
        if self.input_shape is None:
            raise ValueError(
                f"model {self.name!r}: warmup needs input_shape= (the "
                f"per-example trailing shape) at registration")
        b = self.batcher
        shapes = [(n,) + self.input_shape for n in (b._bb or [b.max_batch])]
        for shape in shapes:
            if b._tb is not None and len(shape) >= 3:
                # one variant per (batch, time) bucket, through the same
                # masked path real sequence requests take
                for tt in b._tb:
                    xs = np.zeros((shape[0], tt) + shape[2:], np.float32)
                    self._forward(xs, np.ones((shape[0], tt), np.float32))
            else:
                self._forward(np.zeros(shape, np.float32))
        return self

    def _forward(self, xs, mask=None):
        # the scheduler thread is the only caller, so the model's lazy
        # jit-wrapper construction needs no extra locking here
        y = self.model.output(xs) if mask is None \
            else self.model.output(xs, mask=mask)
        return np.asarray(y)

    def submit(self, x, deadline_ms: Optional[float] = None,
               trace_ctx=None) -> Future:
        return self.batcher.submit(x, deadline_ms=deadline_ms,
                                   trace_ctx=trace_ctx)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: float = 60.0, trace_ctx=None):
        """Synchronous convenience: submit + wait for the result rows."""
        return self.submit(x, deadline_ms=deadline_ms,
                           trace_ctx=trace_ctx).result(timeout)

    def stats(self) -> Dict[str, Any]:
        b = self.batcher
        return {
            "name": self.name,
            "model": type(self.model).__name__,
            "queue_depth": b.queue_depth(),
            "batch_buckets": list(b._bb) if b._bb else None,
            "time_buckets": list(b._tb) if b._tb else None,
            "max_queue_examples": b.max_queue_examples,
            "linger_ms": b.linger_ms,
            "default_deadline_ms": b.default_deadline_ms,
        }

    def close(self, drain: bool = True, timeout: float = 30.0):
        self.batcher.close(drain=drain, timeout=timeout)


class ModelRegistry:
    """Thread-safe name → :class:`ServedModel` table.

    ``max_in_flight`` bounds CONCURRENT forwards across all hosted models
    (each model's scheduler acquires the shared semaphore around its
    flush); per-model queue caps bound each model's backlog. The lock
    covers only the name map — request traffic never runs under it, so
    registering model B cannot stall model A's flushes.
    """

    def __init__(self, max_in_flight: Optional[int] = None):
        self._lock = make_lock("ModelRegistry._lock")
        self._models: Dict[str, ServedModel] = {}
        self._reserved: set = set()
        self._in_flight = (threading.BoundedSemaphore(int(max_in_flight))
                           if max_in_flight else None)

    def register(self, name: str, model, **config) -> ServedModel:
        """Host ``model`` under ``name`` (see :class:`ServedModel` for the
        per-model config dials). Re-using a live name raises — unregister
        (which drains) first, so in-flight requests are never orphaned.
        The name is reserved BEFORE the ServedModel is built: a duplicate
        fails fast instead of paying warmup compiles and a scheduler
        thread just to tear them down again; construction itself runs
        outside the registry lock (warmup can take seconds and must not
        block lookups)."""
        with self._lock:
            if name in self._models or name in self._reserved:
                raise ValueError(f"model {name!r} already registered — "
                                 f"unregister it first")
            self._reserved.add(name)
        try:
            served = ServedModel(name, model, in_flight=self._in_flight,
                                 **config)
            with self._lock:
                self._models[name] = served
        finally:
            with self._lock:
                self._reserved.discard(name)
        return served

    def unregister(self, name: str, drain: bool = True):
        with self._lock:
            served = self._models.pop(name, None)
        if served is None:
            raise ModelNotFoundError(name)
        served.close(drain=drain)

    def get(self, name: str) -> ServedModel:
        with self._lock:
            served = self._models.get(name)
        if served is None:
            raise ModelNotFoundError(name)
        return served

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def list_models(self) -> List[Dict[str, Any]]:
        """Stats rows for ``GET /v1/models`` (stable name order)."""
        with self._lock:
            models = sorted(self._models.items())
        return [m.stats() for _, m in models]

    def submit(self, name: str, x, deadline_ms: Optional[float] = None,
               trace_ctx=None) -> Future:
        return self.get(name).submit(x, deadline_ms=deadline_ms,
                                     trace_ctx=trace_ctx)

    def predict(self, name: str, x, deadline_ms: Optional[float] = None,
                timeout: float = 60.0, trace_ctx=None):
        return self.get(name).predict(x, deadline_ms=deadline_ms,
                                      timeout=timeout, trace_ctx=trace_ctx)

    def close_all(self, drain: bool = True, timeout: float = 30.0):
        """Graceful shutdown: stop admission on every model, serve what
        was accepted (``drain=True``), join every scheduler. Closing
        happens OUTSIDE the registry lock (a drain can take a while and
        must not block lookups, nor create a lock-order edge onto the
        batcher's condition)."""
        with self._lock:
            models, self._models = list(self._models.values()), {}
        for m in models:
            m.close(drain=drain, timeout=timeout)
