"""Multi-model hosting: named models, each behind its own batcher.

The reference serves one net per ``ParallelInference`` instance; a
production front door hosts MANY — zoo models and ``keras/`` imports side
by side — so the registry maps ``name -> ServedModel``, where each entry
owns its own :class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher`
(independent queues, buckets, deadlines) while sharing one optional
``max_in_flight`` semaphore so N models cannot pile N concurrent forwards
onto one device. Per-model latency/QPS/batch-size/queue-depth series land
in the monitor registry under a ``model`` label and roll up into the
``serving`` block of ``GET /profile`` (docs/OBSERVABILITY.md).

Anything with an ``output(features)`` method serves: ``MultiLayerNetwork``,
``ComputationGraph``, a ``keras.model_import`` product, or a test stub.
Zoo models may be passed un-initialized (``ZooModel`` instances are
``init()``-ed on registration).
"""
from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..monitor.lockwatch import make_lock
from .batcher import (ContinuousBatcher, ModelNotFoundError, PRECISIONS,
                      serving_dtype)

__all__ = ["ServedModel", "ModelRegistry"]


def _flip_compute_dtype(model, dtype_name: str) -> bool:
    """Switch a framework net's layer compute policy to ``dtype_name``
    (the mixed-precision policy ``nn/layers/base.py`` documents: params
    stay f32 masters, activations and MXU compute flow in the low
    precision). NOTE: this mutates the NET OBJECT — registration takes
    ownership of the model's compute policy, so a net that is still
    training elsewhere (or hosted by a second registry entry) computes
    in the new dtype too. Share a net across serving and training only
    at precision="f32", or register a copy (docs/SERVING.md). Anything
    without ``impls`` (duck-typed models) is left alone — those only see
    the low-precision INPUTS the batcher casts. Returns True when at
    least one layer flipped."""
    impls = getattr(model, "impls", None)
    if impls is None:
        return False          # duck model — and keeps jax-free fleets
    import jax.numpy as jnp   # (device_path=False) importing lazily
    dt = jnp.dtype(dtype_name)
    flipped = False
    stack = list(impls.values() if isinstance(impls, dict) else impls)
    while stack:
        impl = stack.pop()
        if impl is None:
            continue
        inner = getattr(impl, "inner", None)   # wrapper impls (Frozen,
        if inner is not None:                  # Bidirectional, ...)
            stack.append(inner)
        if hasattr(impl, "compute_dtype") \
                and jnp.dtype(impl.compute_dtype) != dt:
            impl.compute_dtype = dt
            impl.out_dtype = (dt if dt.itemsize < 4
                              else getattr(impl, "dtype", dt))
            flipped = True
    if not flipped:
        return False       # already at the target precision: a no-op
        # re-registration (the common f32-on-f32 case) must not discard
        # valid compiled traces below
    gc = getattr(model, "gc", None)
    if gc is not None and hasattr(gc, "compute_dtype"):
        gc.compute_dtype = str(dt)     # keep config honest for serde/stats
    cache = getattr(model, "_jit_output", None)
    if isinstance(cache, dict):
        cache.clear()      # any pre-flip traces compiled the OLD dtype —
    return True            # they must not serve under the new contract

#: default batch buckets: powers of two up to a modest serving batch —
#: small enough that a lone request pads little, closed enough that the
#: jit cache stays warm under any request-size churn
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)


class ServedModel:
    """One hosted model: the net, its batcher, and its serving config.

    ``precision="bf16"`` serves this model in bfloat16 (docs/SERVING.md
    "Data-plane tuning"): framework nets have their layer compute policy
    flipped at registration (f32 params, bf16 activations/MXU compute),
    the batcher casts inputs to bf16 at submit — so h2d/d2h wire bytes
    halve and the bf16 dtype keys its OWN closed jit-signature set — and
    responses come back f32. Duck-typed models simply receive bf16
    inputs. ``cache_size`` (examples) enables the content-addressed
    response cache in front of the queue."""

    def __init__(self, name: str, model, *,
                 batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 time_buckets: Optional[Sequence[int]] = None,
                 max_queue_examples: int = 256,
                 linger_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = 2000.0,
                 input_shape: Optional[Sequence[int]] = None,
                 warmup: bool = False,
                 qps_window_s: float = 10.0,
                 in_flight: Optional[threading.Semaphore] = None,
                 precision: str = "f32",
                 cache_size: Optional[int] = None,
                 device_path: Optional[bool] = None,
                 warmup_artifact: Optional[str] = None):
        # the compile-once fleet dial (compilecache/): a serving replica
        # about to pay warmup compiles is exactly the process that wants
        # the shared persistent cache — a no-op unless
        # DL4J_TPU_COMPILE_CACHE_DIR is exported (tier-1 default: off)
        from ..compilecache.cache import maybe_enable
        maybe_enable()
        if hasattr(model, "conf") and not hasattr(model, "output"):
            model = model.init()          # a ZooModel, not yet built
        if not callable(getattr(model, "output", None)):
            raise TypeError(
                f"model {name!r} has no callable output(features) — pass "
                f"an initialized network (MultiLayerNetwork, "
                f"ComputationGraph, keras import) or a ZooModel")
        if precision not in PRECISIONS:
            raise ValueError(f"model {name!r}: precision must be one of "
                             f"{PRECISIONS}, got {precision!r}")
        self.name = name
        self.model = model
        self.precision = precision
        # enforce the declared precision in BOTH directions: registering
        # f32 flips a previously-bf16-served net back (and clears its jit
        # cache), so stats()['precision'] can never disagree with what
        # the layers actually compute in — the flip is a property of the
        # registration, not a one-way ratchet on the net
        _flip_compute_dtype(model,
                            "bfloat16" if precision == "bf16"
                            else "float32")
        self.input_shape = (tuple(int(d) for d in input_shape)
                            if input_shape is not None else None)
        if device_path is None:
            # framework nets (layer impls) compute on device — stage
            # their batches there. Duck-typed models compute wherever
            # they please, usually host numpy: auto-staging would ADD
            # the h2d+d2h round trip the device path exists to remove
            # (and hand an in-place-mutating forward an immutable
            # jax.Array) — they opt in with device_path=True
            device_path = hasattr(model, "impls")
        #: AOT forward table (compilecache/artifacts.py): signature key →
        #: deserialized executable. Populated only by a successful
        #: ``warm(artifact=)``; empty = every forward rides model.output
        self._aot: Dict[Any, Any] = {}
        #: latched golden set (see :meth:`golden`) — None until captured
        self._golden: Optional[Dict[str, Any]] = None
        self.batcher = ContinuousBatcher(
            self._forward, name=name,
            batch_buckets=batch_buckets, time_buckets=time_buckets,
            max_queue_examples=max_queue_examples, linger_ms=linger_ms,
            default_deadline_ms=default_deadline_ms,
            queue_policy="reject", in_flight=in_flight,
            metrics_label=name, qps_window_s=qps_window_s,
            precision=precision, cache_size=cache_size,
            device_path=device_path)
        if warmup_artifact is not None:
            self.warm(artifact=warmup_artifact)
        elif warmup:
            self.warm()

    def warm(self, artifact: Optional[str] = None):
        """Pre-compile every bucket signature (synchronously, on the
        registering thread): after this, request-size churn NEVER
        compiles — the whole closed signature set is already in the jit
        cache, so serving cold-start is paid at registration, not on the
        first unlucky requests. Requires ``input_shape`` (the per-example
        trailing shape, e.g. ``(784,)`` or ``(T, features)``).

        ``artifact=`` (compile-once fleet, PERF.md): load an AOT warmup
        artifact instead — the closed compile set deserialized from disk,
        ZERO compiles. The artifact's fingerprint (jax+backend version),
        topology hash, precision and bucket set must all match; ANY
        mismatch or corruption falls back LOUDLY to the live warmup below
        (``compile_cache_miss`` flight event naming the reason), never a
        crash. A successful load adopts the artifact's ``input_shape``
        when none was configured.

        Note the jitwatch interplay (live path): warming ``>= DL4J_TPU_
        RETRACE_THRESHOLD`` (default 3) buckets back-to-back is, to the
        per-instance storm detector, indistinguishable from churn — it
        logs one storm during warmup. Size the bucket set below the
        threshold, or raise the threshold for serving processes; steady
        state is storm-free either way (docs/SERVING.md). With the
        persistent compile cache enabled (``DL4J_TPU_COMPILE_CACHE_DIR``)
        the live warmup's compiles become disk hits on every process
        after the first — watch ``jit_persistent_cache_hits_total``."""
        b = self.batcher
        fallback = False
        if artifact is not None:
            from ..compilecache.artifacts import try_install
            if try_install(self, artifact):
                self._warm_pads()
                return self
            # loud fallback: the compile_cache_miss flight event already
            # landed — pay the live compiles below instead
            fallback = True
        if self.input_shape is None:
            if fallback:
                # a loader-only replica (no input_shape configured — the
                # artifact was going to supply it) whose artifact was
                # rejected CANNOT live-warm, and the never-a-crash
                # contract of warm(artifact=) holds: start cold, let the
                # first requests pay the compiles the artifact would
                # have covered (the miss flight event already names why)
                import logging
                logging.getLogger(__name__).warning(
                    "model %r: rejected warmup artifact and no "
                    "input_shape configured — starting COLD (first "
                    "requests will compile)", self.name)
                return self
            raise ValueError(
                f"model {self.name!r}: warmup needs input_shape= (the "
                f"per-example trailing shape) at registration")
        # warm in the SERVING dtype: precision is part of the jit
        # signature, so an f32 warmup of a bf16 model would pre-compile
        # the wrong variants and the first real requests would retrace.
        # compile_signatures is the same enumeration the AOT exporter
        # serializes — warm() and artifacts cover the identical set
        dt = serving_dtype(self.precision)
        for shape, _, masked in b.compile_signatures(self.input_shape):
            xs = np.zeros(shape, dt)
            if masked:
                # through the same masked path real sequence requests take
                self._forward(xs, np.ones((shape[0], shape[1]), np.float32))
            else:
                self._forward(xs)
        self._warm_pads()
        return self

    def _warm_pads(self):
        # data-plane warm-in (ISSUE 11): the device pad program
        # specializes per (real rows, bucket) pair — pre-compile those
        # too, so no live flush ever pays a pad compile. Pad programs are
        # NOT part of the AOT artifact (trivial compiles; the persistent
        # cache covers them when enabled), so both warm paths run this
        b = self.batcher
        if self.input_shape is None:
            return
        if b._tb is not None and len(self.input_shape) >= 2:
            for tt in b._tb:
                b.warm_pads((tt,) + self.input_shape[1:], masked=True)
        else:
            b.warm_pads(self.input_shape)

    def export_warmup(self, out: str) -> str:
        """Serialize this model's closed compile set into a content-
        addressed AOT warmup artifact (``compilecache/artifacts.py``) at
        ``out`` (directory → content-addressed name, else exact path).
        Returns the written path; load it on a cold replica with
        ``warm(artifact=path)`` / ``register(..., warmup_artifact=)``."""
        from ..compilecache.artifacts import export_warmup_artifact
        return export_warmup_artifact(self, out)

    def _forward(self, xs, mask=None):
        # the scheduler thread is the only caller, so the model's lazy
        # jit-wrapper construction needs no extra locking here. The raw
        # (possibly device-resident) output is returned — the batcher
        # slices the padding off ON DEVICE and does the one host
        # transfer itself (the old np.asarray here was the d2h round-trip
        # the ISSUE-11 data-plane pass removed)
        if self._aot:
            fn = self._aot.get((tuple(int(d) for d in xs.shape),
                                str(xs.dtype), mask is not None))
            if fn is not None:
                # AOT executable from warm(artifact=): the same XLA
                # program a live compile would produce, run against the
                # CURRENT params/states — bit-identical results, zero
                # compiles. Signatures outside the artifact (impossible
                # for bucket-conforming traffic — the batcher pads to
                # the same closed set) fall through to the live path
                return fn(self.model.params, self.model.states, xs, mask)
        return self.model.output(xs) if mask is None \
            else self.model.output(xs, mask=mask)

    def submit(self, x, deadline_ms: Optional[float] = None,
               trace_ctx=None, cache_bypass: bool = False) -> Future:
        return self.batcher.submit(x, deadline_ms=deadline_ms,
                                   trace_ctx=trace_ctx,
                                   cache_bypass=cache_bypass)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: float = 60.0, trace_ctx=None,
                cache_bypass: bool = False):
        """Synchronous convenience: submit + wait for the result rows."""
        return self.submit(x, deadline_ms=deadline_ms,
                           trace_ctx=trace_ctx,
                           cache_bypass=cache_bypass).result(timeout)

    def golden(self, inputs=None, examples: int = 2,
               refresh: bool = False) -> Dict[str, Any]:
        """The model's **golden set**: canonical inputs plus their f32
        expected outputs, captured through the REAL serving path (batcher
        bucketing and precision cast included, response cache bypassed —
        the oracle must describe the live model path, not the LRU). The
        probe plane (:mod:`deeplearning4j_tpu.monitor.probes`) replays
        these inputs from the outside and compares answers within
        ``atol`` — the correctness half of black-box monitoring.

        ``inputs`` defaults to a deterministic canonical batch derived
        from ``input_shape`` (``examples`` rows, values in ``[0, 1)``) —
        the same inputs on every capture, so two captures of the same
        weights produce the same ``version``. The ``version`` key is a
        content hash over inputs + expected outputs + precision: a
        retrained or re-precisioned model gets a NEW version, and an AOT
        warmup artifact exported from this model
        (:meth:`export_warmup`) ships the golden set whose version names
        exactly the weights it was captured against. ``atol`` follows
        the serving precision (bf16 answers are compared loosely — the
        docs/SERVING.md bf16 tolerance). The capture is latched; pass
        ``refresh=True`` after mutating the model's weights."""
        if self._golden is not None and not refresh and inputs is None:
            return self._golden
        if inputs is None:
            if self.input_shape is None:
                raise ValueError(
                    f"model {self.name!r}: golden() needs input_shape= "
                    f"at registration (or pass canonical inputs=)")
            per = int(np.prod(self.input_shape, dtype=np.int64))
            n = max(1, int(examples))
            x = (np.arange(n * per, dtype=np.float32)
                 .reshape((n,) + self.input_shape) % 7.0) / 7.0
        else:
            x = np.asarray(inputs, np.float32)
            if x.ndim < 2:
                x = x.reshape(1, -1)
        expected = np.asarray(
            self.predict(x, cache_bypass=True), np.float32)
        import hashlib
        h = hashlib.sha256()
        h.update(x.tobytes())
        h.update(expected.tobytes())
        h.update(self.precision.encode())
        self._golden = {
            "model": self.name,
            "version": h.hexdigest()[:16],
            "precision": self.precision,
            "inputs": x.tolist(),
            "outputs": expected.tolist(),
            # bf16 forwards round-trip through ~8 mantissa bits; the f32
            # oracle must not flag that as a gray failure
            "atol": 5e-2 if self.precision == "bf16" else 1e-4,
        }
        return self._golden

    def stats(self) -> Dict[str, Any]:
        b = self.batcher
        return {
            "name": self.name,
            "model": type(self.model).__name__,
            "queue_depth": b.queue_depth(),
            "batch_buckets": list(b._bb) if b._bb else None,
            "time_buckets": list(b._tb) if b._tb else None,
            "max_queue_examples": b.max_queue_examples,
            "linger_ms": b.linger_ms,
            "default_deadline_ms": b.default_deadline_ms,
            "precision": self.precision,
            "cache_size": b.cache_size,
            "cache": b.cache_stats(),
            "aot_signatures": len(self._aot),
            "golden_version": (self._golden or {}).get("version"),
        }

    def set_admission(self, max_queue_examples: Optional[int] = None,
                      linger_ms: Optional[float] = None) -> Dict[str, Any]:
        """Step this model's admission knobs on the live batcher (the
        control plane's pressure-relief actuator); returns the previous
        values so the caller can restore them on resolve."""
        return self.batcher.set_admission(
            max_queue_examples=max_queue_examples, linger_ms=linger_ms)

    def close(self, drain: bool = True, timeout: float = 30.0):
        self.batcher.close(drain=drain, timeout=timeout)


class ModelRegistry:
    """Thread-safe name → :class:`ServedModel` table.

    ``max_in_flight`` bounds CONCURRENT forwards across all hosted models
    (each model's scheduler acquires the shared semaphore around its
    flush); per-model queue caps bound each model's backlog. The lock
    covers only the name map — request traffic never runs under it, so
    registering model B cannot stall model A's flushes.
    """

    def __init__(self, max_in_flight: Optional[int] = None):
        self._lock = make_lock("ModelRegistry._lock")
        self._models: Dict[str, ServedModel] = {}
        self._reserved: set = set()
        self._in_flight = (threading.BoundedSemaphore(int(max_in_flight))
                           if max_in_flight else None)

    def register(self, name: str, model, **config) -> ServedModel:
        """Host ``model`` under ``name`` (see :class:`ServedModel` for the
        per-model config dials). Re-using a live name raises — unregister
        (which drains) first, so in-flight requests are never orphaned.
        The name is reserved BEFORE the ServedModel is built: a duplicate
        fails fast instead of paying warmup compiles and a scheduler
        thread just to tear them down again; construction itself runs
        outside the registry lock (warmup can take seconds and must not
        block lookups)."""
        with self._lock:
            if name in self._models or name in self._reserved:
                raise ValueError(f"model {name!r} already registered — "
                                 f"unregister it first")
            self._reserved.add(name)
        try:
            served = ServedModel(name, model, in_flight=self._in_flight,
                                 **config)
            with self._lock:
                self._models[name] = served
        finally:
            with self._lock:
                self._reserved.discard(name)
        return served

    def unregister(self, name: str, drain: bool = True):
        with self._lock:
            served = self._models.pop(name, None)
        if served is None:
            raise ModelNotFoundError(name)
        served.close(drain=drain)

    def get(self, name: str) -> ServedModel:
        with self._lock:
            served = self._models.get(name)
        if served is None:
            raise ModelNotFoundError(name)
        return served

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def list_models(self) -> List[Dict[str, Any]]:
        """Stats rows for ``GET /v1/models`` (stable name order)."""
        with self._lock:
            models = sorted(self._models.items())
        return [m.stats() for _, m in models]

    def submit(self, name: str, x, deadline_ms: Optional[float] = None,
               trace_ctx=None, cache_bypass: bool = False) -> Future:
        return self.get(name).submit(x, deadline_ms=deadline_ms,
                                     trace_ctx=trace_ctx,
                                     cache_bypass=cache_bypass)

    def predict(self, name: str, x, deadline_ms: Optional[float] = None,
                timeout: float = 60.0, trace_ctx=None,
                cache_bypass: bool = False):
        return self.get(name).predict(x, deadline_ms=deadline_ms,
                                      timeout=timeout, trace_ctx=trace_ctx,
                                      cache_bypass=cache_bypass)

    def close_all(self, drain: bool = True, timeout: float = 30.0):
        """Graceful shutdown: stop admission on every model, serve what
        was accepted (``drain=True``), join every scheduler. Closing
        happens OUTSIDE the registry lock (a drain can take a while and
        must not block lookups, nor create a lock-order edge onto the
        batcher's condition)."""
        with self._lock:
            models, self._models = list(self._models.values()), {}
        for m in models:
            m.close(drain=drain, timeout=timeout)
