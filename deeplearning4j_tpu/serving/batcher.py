"""Continuous-batching scheduler: many small requests, one jitted forward.

The serving tier's core loop (docs/SERVING.md). Concurrent callers
``submit()`` single-example (or small-batch) requests; a dedicated
scheduler thread coalesces compatible requests into ONE padded batch,
runs ONE forward per flush, and demultiplexes per-request result rows
back onto each caller's :class:`~concurrent.futures.Future`. This is the
reference ``ParallelInference.java`` observer/``BatchedInferenceObservable``
design rebuilt for an XLA device, with the two production constraints the
reference never had:

- **closed jit signature set.** ``jax.jit`` specializes per input shape,
  so naive coalescing (flush whatever accumulated) feeds the jit cache an
  open set of batch sizes — the retrace-storm failure jitwatch detects
  (docs/OBSERVABILITY.md "Compilation & memory"). Every flush therefore
  pads its batch dim up to a configured **bucket**
  (``datasets/bucketing.py`` rules: smallest admitting bucket, zero-pad
  rows, oversize rejected loudly), and sequence inputs optionally pad
  their time dim up to a time bucket with a zero ``features_mask`` for
  the padding (the records.py/bucketing.py masking convention — mask
  presence is part of the jit signature, so time-bucketed groups ALWAYS
  carry a mask). Steady state compiles exactly
  ``len(batch_buckets) × len(time_buckets)`` variants, no matter how
  request sizes churn.
- **admission control.** The queue is bounded (``max_queue_examples`` /
  ``max_queue_requests``); an over-cap ``submit`` raises the typed
  :class:`OverloadedError` (HTTP 429 at the front door) instead of
  letting latency grow without bound, and every request carries a
  deadline — a request whose deadline expires while queued completes
  with :class:`DeadlineExceededError` (HTTP 504) rather than wasting a
  flush slot. ``close(drain=True)`` stops admission and drains: every
  accepted request still gets its answer.

A lone request is never stranded: the scheduler flushes a partial batch
once the oldest queued request has lingered ``linger_ms`` (the max-linger
bound ``parallel/inference.py`` previously approximated with ad-hoc
``threading.Timer`` threads — ``ParallelInference`` now delegates its
BATCHED path here).

Locking: ONE condition variable (``ContinuousBatcher._cond`` through the
lockwatch factory, so THR003/THR004 and the runtime sanitizer cover it)
guards the queue; the forward always runs OUTSIDE the lock on the
scheduler thread, so submitters never block behind device compute.
"""
from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datasets.bucketing import bucket_for, validate_buckets
from ..monitor.lockwatch import make_condition

log = logging.getLogger(__name__)


def _complete(fut: Future, value=None, exc: Optional[Exception] = None):
    """Resolve a request future, tolerating caller-side ``cancel()``: a
    cancelled future refuses ``set_result``/``set_exception`` with
    InvalidStateError, and that must never escape into the scheduler
    thread (the caller explicitly said they no longer want the answer).
    Returns True when the future actually took the completion."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
        return True
    except InvalidStateError:
        return False

__all__ = ["ContinuousBatcher", "OverloadedError", "DeadlineExceededError",
           "ModelNotFoundError"]


class OverloadedError(RuntimeError):
    """Admission refused: queue at capacity or the batcher is shutting
    down. The HTTP front door maps this to 429 (with Retry-After) — the
    caller should back off or hit another replica."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired before a flush could serve it.
    Mapped to HTTP 504 — the work was shed, not half-done."""


class ModelNotFoundError(KeyError):
    """No model registered under that name (HTTP 404). Lives here so the
    whole typed-error surface of the serving tier imports from one
    module."""


class _Request:
    __slots__ = ("x", "mask", "fut", "key", "n", "t_enq", "t_perf",
                 "deadline", "orig_t", "padded_t", "ctx")

    def __init__(self, x, mask, key, t_enq, deadline, orig_t, padded_t,
                 ctx=None):
        self.x = x
        self.mask = mask
        self.fut: Future = Future()
        self.key = key
        self.n = int(x.shape[0])
        self.t_enq = t_enq
        self.t_perf = time.perf_counter()   # tracer timebase for spans
        self.deadline = deadline      # monotonic seconds, or None
        self.orig_t = orig_t          # pre-padding time steps, or None
        self.padded_t = padded_t      # time bucket the input was padded to
        self.ctx = ctx                # SpanContext (serving mode), or None


class ContinuousBatcher:
    """Iteration-level request coalescing behind one forward callable.

    ``forward_fn(xs)`` (or ``forward_fn(xs, mask)`` when a features mask
    is present) receives the assembled ``[bucket, ...]`` batch and returns
    an array whose leading dim matches; result rows are sliced back per
    request. Requests with different trailing shapes/dtypes never mix in
    one flush (each trailing shape is its own jit signature anyway).

    ``queue_policy``: ``"reject"`` (serving default) raises
    :class:`OverloadedError` at the cap; ``"flush"`` (the
    ``ParallelInference`` semantics) instead forces an immediate flush
    and keeps accepting.
    """

    def __init__(self, forward_fn: Callable, *, name: str = "model",
                 batch_buckets: Optional[Sequence[int]] = None,
                 time_buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue_examples: Optional[int] = 256,
                 max_queue_requests: Optional[int] = None,
                 linger_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = None,
                 queue_policy: str = "reject",
                 in_flight: Optional[threading.Semaphore] = None,
                 metrics_label: Optional[str] = None,
                 qps_window_s: float = 10.0):
        if queue_policy not in ("reject", "flush"):
            raise ValueError(f"queue_policy must be 'reject' or 'flush', "
                             f"got {queue_policy!r}")
        self.name = str(name)
        self._forward = forward_fn
        self._bb = (validate_buckets(batch_buckets, "batch")
                    if batch_buckets else None)
        self._tb = (validate_buckets(time_buckets, "time")
                    if time_buckets else None)
        self.max_batch = self._bb[-1] if self._bb else int(max_batch)
        self.max_queue_examples = max_queue_examples
        self.max_queue_requests = max_queue_requests
        self.linger_ms = float(linger_ms)
        self.default_deadline_ms = default_deadline_ms
        self.queue_policy = queue_policy
        self._in_flight = in_flight
        self._label = metrics_label
        self._qps_window = float(qps_window_s)

        self._cond = make_condition("ContinuousBatcher._cond")
        self._queue: List[_Request] = []
        self._queued_examples = 0
        self._key_examples: Dict[Tuple, int] = {}
        self._force = False
        self._closed = False
        self._running = False          # a flush is executing forward_fn
        self._done_times: List[float] = []   # completion stamps (qps gauge)
        self._handles = None
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-batcher-{self.name}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- metrics
    def _metric_handles(self):
        # lazy, like MonitoredJit: constructing a batcher must not
        # populate /metrics until traffic actually flows
        if self._label is None:
            return None
        if self._handles is None:
            from ..monitor.registry import get_registry
            reg = get_registry()
            self._handles = {
                "latency": reg.histogram(
                    "serving_request_latency_ms",
                    "request latency, submit to result (queue + batch "
                    "assembly + forward)", model=self._label),
                "batch": reg.histogram(
                    "serving_batch_examples",
                    "real (pre-padding) examples per flushed batch",
                    model=self._label),
                "depth": reg.gauge(
                    "serving_queue_depth",
                    "requests currently queued for batching",
                    model=self._label),
                "depth_ex": reg.gauge(
                    "serving_queue_examples",
                    "examples currently queued for batching — the unit "
                    "the admission cap (max_queue_examples) is in, so "
                    "saturation alerts compare like with like",
                    model=self._label),
                "qps": reg.gauge(
                    "serving_qps",
                    "completed requests per second over the trailing "
                    "window", model=self._label),
            }
        return self._handles

    def _count(self, outcome: str, n: int = 1):
        if self._label is None:
            return
        from ..monitor.registry import get_registry
        get_registry().counter(
            "serving_requests_total",
            "inference requests by outcome (ok/rejected/deadline/error)",
            model=self._label, outcome=outcome).inc(n)

    def _note_done(self, outcome: str, latency_ms: Optional[float] = None,
                   exemplar: Optional[str] = None):
        h = self._metric_handles()
        self._count(outcome)
        if h is None:
            return
        if latency_ms is not None:
            # the exemplar (the request's trace id) rides the worst-bucket
            # latch, so a firing p99 alert can name a concrete trace
            h["latency"].observe(latency_ms, exemplar=exemplar)
        now = time.monotonic()
        # trailing-window QPS: scheduler-thread-only bookkeeping (the
        # scheduler is the only completer, submitters never touch this)
        self._done_times.append(now)
        self._trim_done(now, h)

    def _trim_done(self, now: float, h) -> bool:
        """Drop completions older than the window and refresh the qps
        gauge — the ONE implementation behind both the completion path
        and the idle decay (they must never disagree on the gauge).
        Returns True when anything aged out."""
        cut = now - self._qps_window
        changed = False
        while self._done_times and self._done_times[0] < cut:
            self._done_times.pop(0)
            changed = True
        if h is not None:
            h["qps"].set(len(self._done_times) / self._qps_window)
        return changed

    def _decay_qps(self, now: float):
        """Scheduler-driven staleness fix: the trailing-window gauge is
        otherwise only written by completion bookkeeping, so after traffic
        stops it would report the last value FOREVER. The idle scheduler
        wakes as completions age out of the window (see
        ``_wait_timeout_locked``) and walks the gauge down to zero."""
        if not self._done_times:
            return
        self._trim_done(now, self._metric_handles())

    def _set_depth(self):
        h = self._metric_handles()
        if h is not None:
            h["depth"].set(len(self._queue))
            h["depth_ex"].set(self._queued_examples)

    # -------------------------------------------------------------- submit
    def submit(self, x, deadline_ms: Optional[float] = None,
               trace_ctx=None) -> Future:
        """Queue a request; returns a Future resolving to the result rows
        for exactly the submitted examples (padding never leaks out).

        ``x``: ``[b, ...]`` features (``b >= 1``). Raises
        :class:`OverloadedError` when the queue is at capacity (policy
        ``"reject"``) or the batcher is closed; ``ValueError`` when ``b``
        exceeds the largest bucket (configure a bucket that fits).

        ``trace_ctx``: the request's :class:`SpanContext` (the HTTP front
        door forwards the caller's ``X-DL4J-Trace`` header, or its own
        ``http/predict`` span). Serving-labeled batchers mint a fresh
        context when none is given, so EVERY request owns a trace id —
        the scheduler records a ``serving/queue_wait`` span under it
        (linked to the shared ``serving/flush`` span) and latches it as
        the latency histogram's exemplar."""
        x = np.asarray(x)
        if x.dtype.kind == "f" and x.dtype != np.float32:
            x = x.astype(np.float32)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request must be [b, ...] with b >= 1, "
                             f"got shape {x.shape}")
        b = int(x.shape[0])
        if self._bb is not None and b > self.max_batch:
            # only a HARD limit when buckets are configured (no bucket can
            # pad it); unbucketed mode treats max_batch as the flush
            # trigger and serves an oversize request as its own batch —
            # the original ParallelInference accept-and-flush semantics
            raise ValueError(
                f"request of {b} examples exceeds the largest batch "
                f"bucket {self.max_batch} — split the request or "
                f"configure a bigger bucket")
        mask = orig_t = padded_t = None
        if self._tb is not None and x.ndim >= 3:
            # sequence request [b, T, f]: pad T up to its time bucket and
            # carry a features mask (ALWAYS, even when T already fits — a
            # sometimes-present mask would double the signature set)
            orig_t = int(x.shape[1])
            padded_t = bucket_for(self._tb, orig_t, "time")
            mask = np.zeros((b, padded_t), np.float32)
            mask[:, :orig_t] = 1.0
            if padded_t != orig_t:
                pad = np.zeros((b, padded_t - orig_t) + x.shape[2:],
                               x.dtype)
                x = np.concatenate([x, pad], axis=1)
        key = (x.shape[1:], str(x.dtype), mask is not None)
        now = time.monotonic()
        dl_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        ctx = trace_ctx
        if ctx is None and self._label is not None:
            # serving mode: every request gets a trace identity even when
            # the caller brought none (direct registry.submit callers)
            from ..monitor.tracer import new_context
            ctx = new_context()
        req = _Request(x, mask, key, now,
                       now + dl_ms / 1e3 if dl_ms is not None else None,
                       orig_t, padded_t, ctx=ctx)
        with self._cond:
            if self._closed:
                self._count("rejected")
                raise OverloadedError(
                    f"model {self.name!r} is shutting down")
            over = ((self.max_queue_examples is not None
                     and self._queued_examples + b > self.max_queue_examples)
                    or (self.max_queue_requests is not None
                        and len(self._queue) + 1 > self.max_queue_requests))
            if over and self.queue_policy == "reject":
                self._count("rejected")
                raise OverloadedError(
                    f"model {self.name!r} overloaded: "
                    f"{self._queued_examples} examples / "
                    f"{len(self._queue)} requests queued (caps: "
                    f"{self.max_queue_examples} examples, "
                    f"{self.max_queue_requests} requests)")
            self._queue.append(req)
            self._queued_examples += b
            self._key_examples[key] = self._key_examples.get(key, 0) + b
            if over:                      # policy "flush": drain, keep going
                self._force = True
            self._set_depth()
            self._cond.notify_all()
        return req.fut

    # ----------------------------------------------------------- scheduler
    def _ripe_locked(self, now: float) -> bool:
        if not self._queue:
            return False
        if self._force or self._closed:
            return True
        if any(n >= self.max_batch for n in self._key_examples.values()):
            return True
        if (self.max_queue_requests is not None
                and len(self._queue) >= self.max_queue_requests):
            return True
        # an expired deadline is ripe too: the request must complete with
        # DeadlineExceededError NOW, not spin-wait until the linger bound
        if any(r.deadline is not None and now > r.deadline
               for r in self._queue):
            return True
        return (now - self._queue[0].t_enq) * 1e3 >= self.linger_ms

    def _wait_timeout_locked(self, now: float) -> Optional[float]:
        """Sleep until the oldest request's linger expires or the nearest
        deadline passes, whichever is sooner. With an empty queue but a
        non-empty qps window, wake when the oldest completion ages out so
        ``_decay_qps`` can walk the gauge down (None = park until
        notified)."""
        if not self._queue:
            if self._done_times:
                return max(self._done_times[0] + self._qps_window - now,
                           0.0) + 0.05
            return None
        t = self._queue[0].t_enq + self.linger_ms / 1e3
        for r in self._queue:
            if r.deadline is not None:
                t = min(t, r.deadline)
        return max(t - now, 0.0)

    def _take_locked(self, now: float):
        """Pop expired requests plus one same-key batch (FIFO head's key,
        up to the bucket cap). Futures complete OUTSIDE the lock."""
        expired, batch = [], []
        keep = []
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                expired.append(r)
                self._queued_examples -= r.n
                self._key_examples[r.key] -= r.n
            else:
                keep.append(r)
        self._queue = keep
        if self._queue:
            key = self._queue[0].key
            taken = 0
            keep = []
            for r in self._queue:
                # the head is ALWAYS taken (an unbucketed oversize request
                # must flush as its own batch, never starve); others join
                # while the cap holds
                if r.key == key and (not batch
                                     or taken + r.n <= self.max_batch):
                    batch.append(r)
                    taken += r.n
                else:
                    keep.append(r)
            self._queue = keep
            self._queued_examples -= taken
            self._key_examples[key] -= taken
        for k in [k for k, n in self._key_examples.items() if n <= 0]:
            del self._key_examples[k]
        if not self._queue:
            self._force = False
        self._set_depth()
        return expired, batch

    def _loop(self):
        while True:
            with self._cond:
                now = time.monotonic()
                while not self._ripe_locked(now):
                    if self._closed and not self._queue:
                        # the gauge must not outlive the scheduler: a
                        # closed model frozen at its last nonzero qps
                        # would report a dead model as serving forever
                        self._done_times.clear()
                        h = self._metric_handles()
                        if h is not None:
                            h["qps"].set(0.0)
                        return
                    if self._force and not self._queue:
                        self._force = False    # stale flush() of an idle
                                               # queue must not bypass the
                                               # next request's linger
                    self._cond.wait(self._wait_timeout_locked(now))
                    now = time.monotonic()
                    # idle ticks double as the qps-gauge decay driver
                    # (only this thread touches _done_times)
                    self._decay_qps(now)
                expired, batch = self._take_locked(now)
                self._running = bool(batch)
            try:
                for r in expired:
                    if _complete(r.fut, exc=DeadlineExceededError(
                            f"deadline expired after "
                            f"{(now - r.t_enq) * 1e3:.1f}ms in queue "
                            f"(model {self.name!r})")):
                        self._note_done("deadline")
                if batch:
                    self._run_batch(batch)
            except Exception:
                # the scheduler thread must survive anything — a dead
                # scheduler turns every future submit into a silent hang
                # (_run_batch resolves per-request errors itself; this is
                # the last-resort belt)
                log.exception("serving batcher %s: scheduler iteration "
                              "failed", self.name)
            finally:
                with self._cond:
                    self._running = False
                    self._cond.notify_all()

    def _assemble(self, batch: List[_Request]):
        total = sum(r.n for r in batch)
        padded = (bucket_for(self._bb, total, "batch")
                  if self._bb else total)
        trailing = batch[0].x.shape[1:]
        xs = np.zeros((padded,) + tuple(trailing), batch[0].x.dtype)
        pos = 0
        for r in batch:
            xs[pos:pos + r.n] = r.x
            pos += r.n
        mask = None
        if batch[0].mask is not None:
            # zero mask rows for batch padding: padded rows contribute
            # nothing to mask-aware layers (bucketing.py convention)
            mask = np.zeros((padded,) + batch[0].mask.shape[1:], np.float32)
            pos = 0
            for r in batch:
                mask[pos:pos + r.n] = r.mask
                pos += r.n
        return xs, mask, total

    def _forward_batch(self, xs, mask):
        if self._in_flight is not None:
            self._in_flight.acquire()
        try:
            return self._forward(xs) if mask is None \
                else self._forward(xs, mask)
        finally:
            if self._in_flight is not None:
                self._in_flight.release()

    def _run_batch(self, batch: List[_Request]):
        try:
            xs, mask, total = self._assemble(batch)
            flush_start = time.perf_counter()
            if self._label is not None:
                # request-scoped tracing (docs/OBSERVABILITY.md): ONE
                # shared serving/flush span on the scheduler thread —
                # compiles inside the forward nest under it — and each
                # request's queue-wait span below links to it, so p99
                # decomposes into queue vs compute vs compile per trace
                from ..monitor.tracer import get_tracer
                with get_tracer().span(
                        "serving/flush", cat="serving", model=self.name,
                        examples=int(total), padded=int(xs.shape[0]),
                        requests=len(batch)) as flush_ctx:
                    ys = self._forward_batch(xs, mask)
            else:
                flush_ctx = None
                ys = self._forward_batch(xs, mask)
            ys = np.asarray(ys)
            h = self._metric_handles()
            if h is not None:
                h["batch"].observe(float(total))
            done = time.monotonic()
            if flush_ctx is not None:
                from ..monitor.tracer import get_tracer
                tracer = get_tracer()
                for r in batch:
                    if r.ctx is None:
                        continue
                    tracer.record_complete(
                        "serving/queue_wait", r.t_perf,
                        max(flush_start - r.t_perf, 0.0), cat="serving",
                        parent=r.ctx, model=self.name,
                        flush_span_id=f"{flush_ctx.span_id:x}")
            pos = 0
            for r in batch:
                yr = ys[pos:pos + r.n]
                pos += r.n
                if (r.padded_t is not None and r.padded_t != r.orig_t
                        and yr.ndim >= 2 and yr.shape[1] == r.padded_t):
                    # per-timestep output ([b, T', ...] tracking the padded
                    # time dim): strip the time padding from the result too
                    yr = yr[:, :r.orig_t]
                if _complete(r.fut, yr):
                    self._note_done(
                        "ok", (done - r.t_enq) * 1e3,
                        exemplar=(f"{r.ctx.trace_id:x}" if r.ctx is not None
                                  else None))
        except Exception as e:
            for r in batch:
                if not r.fut.done() and _complete(r.fut, exc=e):
                    self._note_done("error")

    # ------------------------------------------------------------ lifecycle
    def flush(self, wait: bool = True, timeout: float = 30.0) -> bool:
        """Force everything queued to flush now (ignoring linger).
        ``wait=True`` blocks until the queue is empty and no flush is
        executing; returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            if not self._queue and not self._running:
                return True       # idle: nothing to flush, and leaving
                                  # _force armed would rob the NEXT lone
                                  # request of its linger coalescing
            self._force = True
            self._cond.notify_all()
            if not wait:
                return True
            while self._queue or self._running:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def close(self, drain: bool = True, timeout: float = 30.0):
        """Stop admission, then either serve (``drain=True`` — no accepted
        request is dropped) or fail (``drain=False`` → OverloadedError)
        everything still queued, and join the scheduler thread."""
        with self._cond:
            self._closed = True
            dropped: List[_Request] = []
            if not drain:
                dropped, self._queue = self._queue, []
                self._queued_examples = 0
                self._key_examples.clear()
            self._cond.notify_all()
        for r in dropped:
            if _complete(r.fut, exc=OverloadedError(
                    f"model {self.name!r} shut down without drain")):
                # counter only — _note_done's qps window belongs to the
                # scheduler thread, which may still be draining a batch
                self._count("rejected")
        self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
