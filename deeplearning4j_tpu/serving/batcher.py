"""Continuous-batching scheduler: many small requests, one jitted forward.

The serving tier's core loop (docs/SERVING.md). Concurrent callers
``submit()`` single-example (or small-batch) requests; a dedicated
scheduler thread coalesces compatible requests into ONE padded batch,
runs ONE forward per flush, and demultiplexes per-request result rows
back onto each caller's :class:`~concurrent.futures.Future`. This is the
reference ``ParallelInference.java`` observer/``BatchedInferenceObservable``
design rebuilt for an XLA device, with the two production constraints the
reference never had:

- **closed jit signature set.** ``jax.jit`` specializes per input shape,
  so naive coalescing (flush whatever accumulated) feeds the jit cache an
  open set of batch sizes — the retrace-storm failure jitwatch detects
  (docs/OBSERVABILITY.md "Compilation & memory"). Every flush therefore
  pads its batch dim up to a configured **bucket**
  (``datasets/bucketing.py`` rules: smallest admitting bucket, zero-pad
  rows, oversize rejected loudly), and sequence inputs optionally pad
  their time dim up to a time bucket with a zero ``features_mask`` for
  the padding (the records.py/bucketing.py masking convention — mask
  presence is part of the jit signature, so time-bucketed groups ALWAYS
  carry a mask). Steady state compiles exactly
  ``len(batch_buckets) × len(time_buckets)`` variants, no matter how
  request sizes churn.
- **admission control.** The queue is bounded (``max_queue_examples`` /
  ``max_queue_requests``); an over-cap ``submit`` raises the typed
  :class:`OverloadedError` (HTTP 429 at the front door) instead of
  letting latency grow without bound, and every request carries a
  deadline — a request whose deadline expires while queued completes
  with :class:`DeadlineExceededError` (HTTP 504) rather than wasting a
  flush slot. ``close(drain=True)`` stops admission and drains: every
  accepted request still gets its answer.

A lone request is never stranded: the scheduler flushes a partial batch
once the oldest queued request has lingered ``linger_ms`` (the max-linger
bound ``parallel/inference.py`` previously approximated with ad-hoc
``threading.Timer`` threads — ``ParallelInference`` now delegates its
BATCHED path here).

The flush data plane is built for raw speed (ISSUE 11, docs/SERVING.md
"Data-plane tuning"):

- **device residency + donation.** The host only ever moves the REAL
  examples: requests are coalesced into one ``[total, ...]`` host view
  (a lone request ships zero-copy), ``jax.device_put`` once, and the
  padding up to the bucket happens ON DEVICE into a bucket-shaped buffer
  recycled flush-over-flush via XLA buffer donation — the donated buffer
  is only ever overwritten, never read, so stale contents cannot leak
  into padding rows. The forward's output is sliced back to the real
  rows on device and crosses device→host in ONE transfer. The split is
  observable: ``serving/pad`` and ``serving/transfer`` spans nest under
  ``serving/flush``, and ``serving_pad_ms``/``serving_transfer_ms``
  histograms carry the same numbers for /profile and the bench.
- **precision.** ``precision="bf16"`` casts inputs to bfloat16 at submit
  (halving host→device bytes) and serves the forward in bf16; responses
  are cast back to float32 on the host side of the single transfer.
  Dtype is part of the jit signature, so each served precision owns its
  own closed ``len(buckets)`` compile set — jitwatch-provable.
- **response cache.** ``cache_size=`` (capacity in EXAMPLES) enables a
  per-model content-addressed LRU checked at ``submit()``: a hit
  resolves the future immediately with a bit-identical copy of the
  cached rows — no queue, no ``serving/queue_wait`` span, no flush —
  counted by ``serving_cache_hits_total``/``serving_cache_misses_total``.

Locking: ONE condition variable (``ContinuousBatcher._cond`` through the
lockwatch factory, so THR003/THR004 and the runtime sanitizer cover it)
guards the queue; the forward always runs OUTSIDE the lock on the
scheduler thread, so submitters never block behind device compute. The
response cache has its own lock (``ContinuousBatcher._cache_lock``),
never held while acquiring the condition (and vice versa) — the serving
lock graph stays edge-free.
"""
from __future__ import annotations

import contextlib
import hashlib
import logging
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from typing import (Any, Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

import numpy as np

from ..datasets.bucketing import bucket_for, validate_buckets
from ..monitor.lockwatch import make_condition, make_lock

log = logging.getLogger(__name__)

#: serving precisions → the numpy dtype submitted floats are cast to.
#: bfloat16 comes from ml_dtypes (a jax dependency), so host buffers can
#: hold it natively and the host→device transfer ships half the bytes.
PRECISIONS = ("f32", "bf16")


def serving_dtype(precision: str) -> np.dtype:
    """The input dtype a serving precision casts float features to."""
    if precision == "bf16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float32)


def _floatish(dtype) -> bool:
    # bfloat16 registers as kind "V" (ml_dtypes extension type), so the
    # float-family test must name it explicitly
    return dtype.kind == "f" or dtype.name == "bfloat16"


#: warm_pads budget: at most this many pad-program pre-compiles per
#: bucket (the default bucket set needs far fewer; see warm_pads)
_WARM_PADS_PER_BUCKET = 64

_PAD_JIT = None


def _pad_jit():
    """The device-side pad: write the coalesced rows into a bucket-shaped
    zero buffer, DONATING the previous flush's buffer so XLA reuses its
    memory for the output instead of allocating fresh. The donated buffer
    is write-only to this op (``zeros_like`` then ``set`` — its VALUES are
    never read), which is what makes recycling safe: stale rows from the
    previous flush can never survive into padding rows. Shared across
    batchers — jax's own cache specializes per shape/dtype, and the set of
    shapes is closed by the bucket set."""
    global _PAD_JIT
    if _PAD_JIT is None:
        import jax
        import jax.numpy as jnp
        # deliberately a bare jax.jit, NOT monitored_jit: the pad program
        # legitimately specializes per (total, bucket) pair — a set
        # bounded by the bucket config — and the per-instance storm
        # detector would report that bounded warm-in as retrace churn,
        # poisoning the zero-storm invariant the MODEL forward must keep
        _PAD_JIT = jax.jit(  # tpulint: disable=JAX003
            lambda buf, rows: jnp.zeros_like(buf).at[:rows.shape[0]]
            .set(rows), donate_argnums=(0,))
    return _PAD_JIT


def _content_key(x: np.ndarray) -> Tuple:
    """The response-cache content address: shape + dtype (which carries
    the precision) + sha256 of the bytes. Hashes the buffer IN PLACE
    when possible — a tobytes() copy of every submitted payload on the
    latency-critical caller thread would undo the submit no-copy work.
    Extension dtypes (ml_dtypes bfloat16) refuse buffer export entirely
    ("cannot include dtype 'E'"), so they take the copy."""
    try:
        buf = x.data if x.flags.c_contiguous else x.tobytes()
    except ValueError:
        buf = x.tobytes()
    return (x.shape, str(x.dtype), hashlib.sha256(buf).digest())


def _complete(fut: Future, value=None, exc: Optional[Exception] = None):
    """Resolve a request future, tolerating caller-side ``cancel()``: a
    cancelled future refuses ``set_result``/``set_exception`` with
    InvalidStateError, and that must never escape into the scheduler
    thread (the caller explicitly said they no longer want the answer).
    Returns True when the future actually took the completion."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
        return True
    except InvalidStateError:
        return False

__all__ = ["ContinuousBatcher", "OverloadedError", "DeadlineExceededError",
           "ModelNotFoundError"]


class OverloadedError(RuntimeError):
    """Admission refused: queue at capacity or the batcher is shutting
    down. The HTTP front door maps this to 429 (with Retry-After) — the
    caller should back off or hit another replica."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired before a flush could serve it.
    Mapped to HTTP 504 — the work was shed, not half-done."""


class ModelNotFoundError(KeyError):
    """No model registered under that name (HTTP 404). Lives here so the
    whole typed-error surface of the serving tier imports from one
    module."""


class _Request:
    __slots__ = ("x", "mask", "fut", "key", "n", "t_enq", "t_perf",
                 "deadline", "orig_t", "padded_t", "ctx", "ckey")

    def __init__(self, x, mask, key, t_enq, deadline, orig_t, padded_t,
                 ctx=None, ckey=None):
        self.x = x
        self.mask = mask
        self.fut: Future = Future()
        self.key = key
        self.n = int(x.shape[0])
        self.t_enq = t_enq
        self.t_perf = time.perf_counter()   # tracer timebase for spans
        self.deadline = deadline      # monotonic seconds, or None
        self.orig_t = orig_t          # pre-padding time steps, or None
        self.padded_t = padded_t      # time bucket the input was padded to
        self.ctx = ctx                # SpanContext (serving mode), or None
        self.ckey = ckey              # response-cache key, or None


class ContinuousBatcher:
    """Iteration-level request coalescing behind one forward callable.

    ``forward_fn(xs)`` (or ``forward_fn(xs, mask)`` when a features mask
    is present) receives the assembled ``[bucket, ...]`` batch and returns
    an array whose leading dim matches; result rows are sliced back per
    request. Requests with different trailing shapes/dtypes never mix in
    one flush (each trailing shape is its own jit signature anyway).

    ``queue_policy``: ``"reject"`` (serving default) raises
    :class:`OverloadedError` at the cap; ``"flush"`` (the
    ``ParallelInference`` semantics) instead forces an immediate flush
    and keeps accepting.

    ``precision``: ``"f32"`` (default) or ``"bf16"`` — the dtype float
    inputs are cast to at submit and served in (module docstring).
    ``cache_size``: response-cache capacity in EXAMPLES (None = off).
    ``device_path``: pad/slice on device with donated buffers. Default
    OFF for a directly-constructed batcher — the forward keeps receiving
    host ndarrays, the pre-ISSUE-11 contract (a host-numpy forward must
    not silently start seeing immutable jax.Arrays, nor pay an h2d+d2h
    round trip it never asked for). :class:`ServedModel` turns it on for
    framework nets, whose forwards are jax-backed; device-computing
    custom forwards opt in with ``device_path=True``.
    """

    def __init__(self, forward_fn: Callable, *, name: str = "model",
                 batch_buckets: Optional[Sequence[int]] = None,
                 time_buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 64,
                 max_queue_examples: Optional[int] = 256,
                 max_queue_requests: Optional[int] = None,
                 linger_ms: float = 5.0,
                 default_deadline_ms: Optional[float] = None,
                 queue_policy: str = "reject",
                 in_flight: Optional[threading.Semaphore] = None,
                 metrics_label: Optional[str] = None,
                 qps_window_s: float = 10.0,
                 precision: str = "f32",
                 cache_size: Optional[int] = None,
                 device_path: Optional[bool] = None):
        if queue_policy not in ("reject", "flush"):
            raise ValueError(f"queue_policy must be 'reject' or 'flush', "
                             f"got {queue_policy!r}")
        if precision not in PRECISIONS:
            raise ValueError(f"precision must be one of {PRECISIONS}, "
                             f"got {precision!r}")
        self.name = str(name)
        self._forward = forward_fn
        self.precision = precision
        self._in_dtype = serving_dtype(precision)
        if cache_size is not None and int(cache_size) < 1:
            # 0 raises like -1 does — a miscomputed capacity must not
            # silently serve uncached (None is the one off spelling)
            raise ValueError(f"cache_size must be >= 1 examples, got "
                             f"{cache_size}")
        self.cache_size = (int(cache_size) if cache_size is not None
                           else None)
        # content-addressed LRU: ckey -> READ-ONLY result rows (hits hand
        # out writable copies, so no caller can corrupt the cached master)
        self._cache: Optional[OrderedDict] = (
            OrderedDict() if self.cache_size is not None else None)
        self._cache_examples = 0
        self._cache_lock = (make_lock("ContinuousBatcher._cache_lock")
                            if self._cache is not None else None)
        self._device_path = bool(device_path)
        # per-(key, bucket) device-resident pad buffer, recycled via
        # donation each flush; scheduler-thread-only, dropped on close
        self._dev_bufs: Dict[Tuple, object] = {}
        self._bb = (validate_buckets(batch_buckets, "batch")
                    if batch_buckets else None)
        self._tb = (validate_buckets(time_buckets, "time")
                    if time_buckets else None)
        self.max_batch = self._bb[-1] if self._bb else int(max_batch)
        self.max_queue_examples = max_queue_examples
        self.max_queue_requests = max_queue_requests
        self.linger_ms = float(linger_ms)
        self.default_deadline_ms = default_deadline_ms
        self.queue_policy = queue_policy
        self._in_flight = in_flight
        self._label = metrics_label
        self._qps_window = float(qps_window_s)

        self._cond = make_condition("ContinuousBatcher._cond")
        self._queue: List[_Request] = []
        self._queued_examples = 0
        self._key_examples: Dict[Tuple, int] = {}
        self._force = False
        self._closed = False
        self._running = False          # a flush is executing forward_fn
        # completion stamps for the qps gauge: deque so the window trim
        # is O(1) popleft per aged-out stamp — a plain list's pop(0)
        # memmove would grow per-completion cost linearly with sustained
        # QPS, under the shared condition, on the cache-hit fast path
        self._done_times: Deque[float] = deque()
        self._handles = None
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-batcher-{self.name}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- metrics
    def _metric_handles(self):
        # lazy, like MonitoredJit: constructing a batcher must not
        # populate /metrics until traffic actually flows
        if self._label is None:
            return None
        if self._handles is None:
            from ..monitor.registry import get_registry
            reg = get_registry()
            handles = {
                "req_ok": reg.counter(
                    "serving_requests_total",
                    "inference requests by outcome "
                    "(ok/rejected/deadline/error)",
                    model=self._label, outcome="ok"),
                "latency": reg.histogram(
                    "serving_request_latency_ms",
                    "request latency, submit to result (queue + batch "
                    "assembly + forward)", model=self._label),
                "batch": reg.histogram(
                    "serving_batch_examples",
                    "real (pre-padding) examples per flushed batch",
                    model=self._label),
                "depth": reg.gauge(
                    "serving_queue_depth",
                    "requests currently queued for batching",
                    model=self._label),
                "depth_ex": reg.gauge(
                    "serving_queue_examples",
                    "examples currently queued for batching — the unit "
                    "the admission cap (max_queue_examples) is in, so "
                    "saturation alerts compare like with like",
                    model=self._label),
                "qps": reg.gauge(
                    "serving_qps",
                    "completed requests per second over the trailing "
                    "window", model=self._label),
                "pad": reg.histogram(
                    "serving_pad_ms",
                    "per-flush batch-assembly time: host coalesce + mask "
                    "pad + on-device pad to the bucket shape",
                    model=self._label),
                "xfer": reg.histogram(
                    "serving_transfer_ms",
                    "per-flush host<->device movement: one device_put of "
                    "the real examples in, one sliced fetch out",
                    model=self._label),
            }
            if self._cache is not None:
                handles["c_hit"] = reg.counter(
                    "serving_cache_hits_total",
                    "response-cache hits — requests answered without "
                    "queueing or a flush", model=self._label)
                handles["c_miss"] = reg.counter(
                    "serving_cache_misses_total",
                    "response-cache misses — requests that paid the full "
                    "queue + flush path", model=self._label)
            # publish COMPLETE: concurrent submitters read this dict
            # lock-free (_cache_count), so the assignment must be the
            # last step — a partially-built dict must never be visible
            self._handles = handles
        return self._handles

    def _cache_count(self, hit: bool):
        # cached handles: the hit path runs on the latency-critical
        # caller thread — no per-submit registry-lock lookup
        h = self._metric_handles()
        if h is not None:
            (h["c_hit"] if hit else h["c_miss"]).inc()

    def _count(self, outcome: str, n: int = 1):
        if self._label is None:
            return
        if outcome == "ok" and self._handles is not None:
            # the hot completion path (every cache hit, every flushed
            # request) rides the cached handle — no registry-lock lookup
            self._handles["req_ok"].inc(n)
            return
        from ..monitor.registry import get_registry
        get_registry().counter(
            "serving_requests_total",
            "inference requests by outcome (ok/rejected/deadline/error)",
            model=self._label, outcome=outcome).inc(n)

    def _note_done(self, outcome: str, latency_ms: Optional[float] = None,
                   exemplar: Optional[str] = None):
        h = self._metric_handles()
        self._count(outcome)
        if h is None:
            return
        if latency_ms is not None:
            # the exemplar (the request's trace id) rides the worst-bucket
            # latch, so a firing p99 alert can name a concrete trace
            h["latency"].observe(latency_ms, exemplar=exemplar)
        now = time.monotonic()
        # trailing-window QPS under the condition (cache hits complete on
        # SUBMITTER threads since ISSUE 11, so the window is no longer
        # scheduler-thread-only; _set_depth already writes gauges under
        # the cond, same registry-lock ordering)
        with self._cond:
            if self._closed and not self._thread.is_alive():
                # a late cache hit completing after close: the scheduler
                # (the only decay driver) is gone and has already zeroed
                # the gauge — re-latching a nonzero qps here would freeze
                # a dead model at that value forever
                return
            was_empty = not self._done_times
            self._done_times.append(now)
            self._trim_done(now, h)
            if was_empty:
                # wake a scheduler parked with wait(None) — it only parks
                # unbounded when the window is empty; with completions
                # already in the window a decay timeout is armed, so the
                # common per-request completion skips the wakeup. The
                # empty→nonempty edge re-arms idle decay when ONLY cache
                # hits (submitter threads) have been completing
                self._cond.notify_all()

    def _trim_done(self, now: float, h) -> bool:
        """Drop completions older than the window and refresh the qps
        gauge — the ONE implementation behind both the completion path
        and the idle decay (they must never disagree on the gauge).
        Returns True when anything aged out."""
        cut = now - self._qps_window
        changed = False
        while self._done_times and self._done_times[0] < cut:
            self._done_times.popleft()
            changed = True
        if h is not None:
            h["qps"].set(len(self._done_times) / self._qps_window)
        return changed

    def _decay_qps(self, now: float):
        """Scheduler-driven staleness fix: the trailing-window gauge is
        otherwise only written by completion bookkeeping, so after traffic
        stops it would report the last value FOREVER. The idle scheduler
        wakes as completions age out of the window (see
        ``_wait_timeout_locked``) and walks the gauge down to zero."""
        if not self._done_times:
            return
        self._trim_done(now, self._metric_handles())

    def _set_depth(self):
        h = self._metric_handles()
        if h is not None:
            h["depth"].set(len(self._queue))
            h["depth_ex"].set(self._queued_examples)

    # -------------------------------------------------------- response cache
    def _cache_lookup(self, ckey):
        """LRU get (submitter threads). The cache lock is never held while
        taking the batcher condition — no lock-graph edge."""
        with self._cache_lock:
            got = self._cache.get(ckey)
            if got is not None:
                self._cache.move_to_end(ckey)
            return got

    def _cache_store(self, ckey, rows: np.ndarray):
        """Insert freshly-computed result rows (scheduler thread). The
        stored master is an owned, read-only copy — decoupled from the
        flush's big output buffer, immune to caller mutation — and hits
        are byte-for-byte what the flush computed."""
        if self._closed:
            # a drain-window flush after close() started: storing would
            # repopulate the cache BEHIND close's clear (the join may
            # have timed out) — the drained futures still resolve, the
            # result just isn't cached for a model being torn down
            return
        master = np.array(rows)
        master.flags.writeable = False
        n = int(rows.shape[0]) if rows.ndim >= 1 else 1
        with self._cache_lock:
            old = self._cache.pop(ckey, None)
            if old is not None:
                self._cache_examples -= (int(old.shape[0])
                                         if old.ndim >= 1 else 1)
            self._cache[ckey] = master
            self._cache_examples += n
            while self._cache_examples > self.cache_size and self._cache:
                _, evicted = self._cache.popitem(last=False)
                self._cache_examples -= (int(evicted.shape[0])
                                         if evicted.ndim >= 1 else 1)

    def cache_stats(self) -> Dict[str, int]:
        """Live cache occupancy (entries, examples) for stats()/tests."""
        if self._cache is None:
            return {"entries": 0, "examples": 0}
        with self._cache_lock:
            return {"entries": len(self._cache),
                    "examples": self._cache_examples}

    # -------------------------------------------------------------- submit
    def submit(self, x, deadline_ms: Optional[float] = None,
               trace_ctx=None, cache_bypass: bool = False) -> Future:
        """Queue a request; returns a Future resolving to the result rows
        for exactly the submitted examples (padding never leaks out).

        ``x``: ``[b, ...]`` features (``b >= 1``). Raises
        :class:`OverloadedError` when the queue is at capacity (policy
        ``"reject"``) or the batcher is closed; ``ValueError`` when ``b``
        exceeds the largest bucket (configure a bucket that fits).

        ``cache_bypass``: skip the response cache ENTIRELY for this
        request — no lookup, and the computed result is never stored
        (the request keeps ``ckey=None`` end to end). The probe plane
        sets this (via the ``X-DL4J-Probe`` header): a synthetic probe
        answered from the LRU would prove nothing about the live model
        path, and a probe must not evict real traffic's entries either.

        ``trace_ctx``: the request's :class:`SpanContext` (the HTTP front
        door forwards the caller's ``X-DL4J-Trace`` header, or its own
        ``http/predict`` span). Serving-labeled batchers mint a fresh
        context when none is given, so EVERY request owns a trace id —
        the scheduler records a ``serving/queue_wait`` span under it
        (linked to the shared ``serving/flush`` span) and latches it as
        the latency histogram's exemplar.

        **No-copy / no-mutation contract**: an ndarray whose float dtype
        already matches the serving precision is enqueued AS-IS — no
        ``asarray`` copy, no cast (the old path re-copied every submit).
        The batcher never mutates a submitted array; in return the caller
        must not mutate it until the returned future resolves (the flush
        reads it exactly once, to coalesce the device batch). The
        contract extends to the FORWARD: a lone conforming request may
        be handed to ``forward_fn`` as-is (zero-copy end to end), so a
        custom forward must not mutate its input batch in place — it may
        be the caller's own memory. Exception:
        a CACHE-enabled model copies on a miss — the content address must
        name immutable bytes, or a contract-violating caller could plant
        a poisoned entry that other callers of those bytes would hit."""
        owned = not isinstance(x, np.ndarray)
        if owned:
            x = np.asarray(x)
        if _floatish(x.dtype) and x.dtype != self._in_dtype:
            # the ONLY submit-path copy, and only for non-conforming
            # dtypes (f64 callers, or any float feeding a bf16 model)
            x = x.astype(self._in_dtype)
            owned = True
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request must be [b, ...] with b >= 1, "
                             f"got shape {x.shape}")
        b = int(x.shape[0])
        if self._bb is not None and b > self.max_batch:
            # only a HARD limit when buckets are configured (no bucket can
            # pad it); unbucketed mode treats max_batch as the flush
            # trigger and serves an oversize request as its own batch —
            # the original ParallelInference accept-and-flush semantics
            raise ValueError(
                f"request of {b} examples exceeds the largest batch "
                f"bucket {self.max_batch} — split the request or "
                f"configure a bigger bucket")
        ckey = None
        if self._cache is not None and not self._closed and not cache_bypass:
            # a closed (draining) batcher must not keep answering cached
            # inputs while rejecting uncached ones — admission after
            # close() is uniform: skip the fast path, let the cond-
            # guarded admission below raise OverloadedError (the
            # unlocked _closed read races close() at most as much as the
            # submit itself would)
            # content address = the submitted bytes (pre-padding) + shape
            # + dtype; dtype carries the precision, the per-model cache
            # carries the model — together the full ISSUE-11 cache key
            ckey = _content_key(x)
            hit = self._cache_lookup(ckey)
            if hit is not None:
                # a hit skips the queue ENTIRELY: no queue_wait span, no
                # flush — the future resolves here, on the caller's
                # thread, with a writable bit-identical copy. It still
                # counts as a completion everywhere (ok outcome, ~0ms
                # latency sample, the trailing-QPS window), so the qps
                # gauge stays honest for cache-heavy workloads
                self._cache_count(True)
                self._note_done(
                    "ok", 0.0,
                    exemplar=(f"{trace_ctx.trace_id:x}"
                              if trace_ctx is not None else None))
                fut: Future = Future()
                fut.set_result(hit.copy())
                return fut
            if not owned:
                # a MISS will be stored under sha256(these bytes) at
                # flush time — own them now, so a caller mutating its
                # array in the linger window (violating the no-mutation
                # contract) can only corrupt its own answer, never plant
                # a poisoned entry other callers would hit. The no-copy
                # fast path is therefore an uncached-model guarantee; a
                # content address must name immutable bytes.
                x = np.array(x)
                # ... and re-derive the address from the OWNED bytes: a
                # racing mutation in the hash→copy window above would
                # otherwise file f(mutated) under the ORIGINAL bytes'
                # hash — the exact cross-caller poisoning the copy
                # exists to prevent. Costs one extra hash per miss; the
                # hit path stays copy-free
                ckey = _content_key(x)
        mask = orig_t = padded_t = None
        if self._tb is not None and x.ndim >= 3:
            # sequence request [b, T, f]: pad T up to its time bucket and
            # carry a features mask (ALWAYS, even when T already fits — a
            # sometimes-present mask would double the signature set)
            orig_t = int(x.shape[1])
            padded_t = bucket_for(self._tb, orig_t, "time")
            mask = np.zeros((b, padded_t), np.float32)
            mask[:, :orig_t] = 1.0
            if padded_t != orig_t:
                pad = np.zeros((b, padded_t - orig_t) + x.shape[2:],
                               x.dtype)
                x = np.concatenate([x, pad], axis=1)
        key = (x.shape[1:], str(x.dtype), mask is not None)
        now = time.monotonic()
        dl_ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        ctx = trace_ctx
        if ctx is None and self._label is not None:
            # serving mode: every request gets a trace identity even when
            # the caller brought none (direct registry.submit callers)
            from ..monitor.tracer import new_context
            ctx = new_context()
        req = _Request(x, mask, key, now,
                       now + dl_ms / 1e3 if dl_ms is not None else None,
                       orig_t, padded_t, ctx=ctx, ckey=ckey)
        with self._cond:
            if self._closed:
                self._count("rejected")
                raise OverloadedError(
                    f"model {self.name!r} is shutting down")
            over = ((self.max_queue_examples is not None
                     and self._queued_examples + b > self.max_queue_examples)
                    or (self.max_queue_requests is not None
                        and len(self._queue) + 1 > self.max_queue_requests))
            if over and self.queue_policy == "reject":
                self._count("rejected")
                raise OverloadedError(
                    f"model {self.name!r} overloaded: "
                    f"{self._queued_examples} examples / "
                    f"{len(self._queue)} requests queued (caps: "
                    f"{self.max_queue_examples} examples, "
                    f"{self.max_queue_requests} requests)")
            self._queue.append(req)
            self._queued_examples += b
            self._key_examples[key] = self._key_examples.get(key, 0) + b
            if over:                      # policy "flush": drain, keep going
                self._force = True
            self._set_depth()
            self._cond.notify_all()
        if ckey is not None:
            # counted only for ADMITTED requests — a 429'd submit neither
            # hit nor missed, and must not depress the hit rate
            self._cache_count(False)
        return req.fut

    # ----------------------------------------------------------- scheduler
    def _ripe_locked(self, now: float) -> bool:
        if not self._queue:
            return False
        if self._force or self._closed:
            return True
        if any(n >= self.max_batch for n in self._key_examples.values()):
            return True
        if (self.max_queue_requests is not None
                and len(self._queue) >= self.max_queue_requests):
            return True
        # an expired deadline is ripe too: the request must complete with
        # DeadlineExceededError NOW, not spin-wait until the linger bound
        if any(r.deadline is not None and now > r.deadline
               for r in self._queue):
            return True
        return (now - self._queue[0].t_enq) * 1e3 >= self.linger_ms

    def _wait_timeout_locked(self, now: float) -> Optional[float]:
        """Sleep until the oldest request's linger expires or the nearest
        deadline passes, whichever is sooner. With an empty queue but a
        non-empty qps window, wake when the oldest completion ages out so
        ``_decay_qps`` can walk the gauge down (None = park until
        notified)."""
        if not self._queue:
            if self._done_times:
                return max(self._done_times[0] + self._qps_window - now,
                           0.0) + 0.05
            return None
        t = self._queue[0].t_enq + self.linger_ms / 1e3
        for r in self._queue:
            if r.deadline is not None:
                t = min(t, r.deadline)
        return max(t - now, 0.0)

    def _take_locked(self, now: float):
        """Pop expired requests plus one same-key batch (FIFO head's key,
        up to the bucket cap). Futures complete OUTSIDE the lock."""
        expired, batch = [], []
        keep = []
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                expired.append(r)
                self._queued_examples -= r.n
                self._key_examples[r.key] -= r.n
            else:
                keep.append(r)
        self._queue = keep
        if self._queue:
            key = self._queue[0].key
            taken = 0
            keep = []
            for r in self._queue:
                # the head is ALWAYS taken (an unbucketed oversize request
                # must flush as its own batch, never starve); others join
                # while the cap holds
                if r.key == key and (not batch
                                     or taken + r.n <= self.max_batch):
                    batch.append(r)
                    taken += r.n
                else:
                    keep.append(r)
            self._queue = keep
            self._queued_examples -= taken
            self._key_examples[key] -= taken
        for k in [k for k, n in self._key_examples.items() if n <= 0]:
            del self._key_examples[k]
        if not self._queue:
            self._force = False
        self._set_depth()
        return expired, batch

    def _loop(self):
        try:
            self._loop_inner()
        finally:
            # the scheduler OWNS _dev_bufs (scheduler-thread-only): it
            # releases device residency on ITS way out, so even a close()
            # whose join timed out mid-drain sees the buffers dropped
            # when the drain actually finishes — close() only clears
            # them itself once the thread is provably dead
            self._dev_bufs.clear()

    def _loop_inner(self):
        while True:
            with self._cond:
                now = time.monotonic()
                while not self._ripe_locked(now):
                    if self._closed and not self._queue:
                        # the gauge must not outlive the scheduler: a
                        # closed model frozen at its last nonzero qps
                        # would report a dead model as serving forever
                        self._done_times.clear()
                        h = self._metric_handles()
                        if h is not None:
                            h["qps"].set(0.0)
                        return
                    if self._force and not self._queue:
                        self._force = False    # stale flush() of an idle
                                               # queue must not bypass the
                                               # next request's linger
                    self._cond.wait(self._wait_timeout_locked(now))
                    now = time.monotonic()
                    # idle ticks double as the qps-gauge decay driver
                    # (_done_times is cond-guarded: cache hits append
                    # from submitter threads and notify, so a park with
                    # wait(None) re-arms against the refreshed window)
                    self._decay_qps(now)
                expired, batch = self._take_locked(now)
                self._running = bool(batch)
            try:
                for r in expired:
                    if _complete(r.fut, exc=DeadlineExceededError(
                            f"deadline expired after "
                            f"{(now - r.t_enq) * 1e3:.1f}ms in queue "
                            f"(model {self.name!r})")):
                        self._note_done("deadline")
                if batch:
                    self._run_batch(batch)
            except Exception:
                # the scheduler thread must survive anything — a dead
                # scheduler turns every future submit into a silent hang
                # (_run_batch resolves per-request errors itself; this is
                # the last-resort belt)
                log.exception("serving batcher %s: scheduler iteration "
                              "failed", self.name)
            finally:
                with self._cond:
                    self._running = False
                    self._cond.notify_all()

    def _use_device(self) -> bool:
        return self._device_path

    def _span(self, name: str, **args):
        if self._label is None:
            return contextlib.nullcontext()
        from ..monitor.tracer import get_tracer
        return get_tracer().span(name, cat="serving", model=self.name,
                                 **args)

    def _coalesce(self, batch: List[_Request], padded: int):
        """Host-side coalesce of the REAL examples only — ``[total, ...]``
        — plus the bucket-shaped mask. A lone request IS the coalesced
        batch (zero host copies: the submit no-copy contract holds end to
        end; the one read happens here). Padding rows are NOT materialized
        on host — they are the device pad's job."""
        xs = batch[0].x if len(batch) == 1 else np.concatenate(
            [r.x for r in batch], axis=0)
        mask = None
        if batch[0].mask is not None:
            # masks are tiny [b, T] f32: pad rows to the bucket here; zero
            # rows contribute nothing to mask-aware layers (bucketing.py
            # convention)
            mask = np.zeros((padded,) + batch[0].mask.shape[1:], np.float32)
            pos = 0
            for r in batch:
                mask[pos:pos + r.n] = r.mask
                pos += r.n
        return xs, mask

    def _pad_device(self, xs_dev, padded: int, key):
        """Pad to the bucket ON DEVICE, recycling the previous flush's
        bucket-shaped buffer via donation (module docstring). The donated
        handle is dead after the call — only the new buffer is kept, as
        the forward's input and then as the NEXT flush's donation."""
        import jax.numpy as jnp
        shape = (padded,) + tuple(xs_dev.shape[1:])
        buf = self._dev_bufs.pop((key, padded), None)
        if buf is None or buf.shape != shape or buf.dtype != xs_dev.dtype:
            buf = jnp.zeros(shape, xs_dev.dtype)
        out = _pad_jit()(buf, xs_dev)
        self._dev_bufs[(key, padded)] = out
        return out

    def compile_signatures(self, input_shape: Sequence[int]
                           ) -> List[Tuple[Tuple[int, ...], str, bool]]:
        """The CLOSED forward compile set this batcher will ever request
        for a model with per-example trailing shape ``input_shape``:
        ``[(batch_shape, dtype, masked), ...]`` — one entry per batch
        bucket (× time bucket for sequence models), in the serving
        dtype. This enumeration is the single source of truth shared by
        ``ServedModel.warm()`` (pre-compile each signature live) and the
        AOT warmup-artifact exporter (``compilecache/artifacts.py`` —
        serialize each signature's compiled executable), so an artifact
        can never silently cover a different set than warm() compiles."""
        shape = tuple(int(d) for d in input_shape)
        dt = str(np.dtype(self._in_dtype))
        out: List[Tuple[Tuple[int, ...], str, bool]] = []
        for n in (self._bb or [self.max_batch]):
            if self._tb is not None and len(shape) >= 2:
                # one variant per (batch, time) bucket, masked — mask
                # presence is part of the jit signature (module docstring)
                for tt in self._tb:
                    out.append(((n, tt) + shape[1:], dt, True))
            else:
                out.append(((n,) + shape, dt, False))
        return out

    def warm_pads(self, trailing: Sequence[int], masked: bool = False):
        """Pre-compile the device-pad programs for every (real rows,
        bucket) pair with this trailing shape — warm()'s cold-start-
        paid-once contract extended to the data plane: the pad jit
        legitimately specializes per pair (``_pad_jit``), and without
        this the first live flush at each partial batch size pays that
        (trivial) compile inside a request's ``serving/flush``, spiking
        warm-in p99 and skewing ``serving_pad_ms``. Pre-traffic only
        (same convention as warm()'s direct forward calls: ``_dev_bufs``
        is scheduler-thread-only once requests flow)."""
        if not self._bb or not self._use_device():
            return
        import jax
        key = (tuple(int(d) for d in trailing),
               str(np.dtype(self._in_dtype)), masked)
        lo = 0
        for bucket in self._bb:
            gap = range(lo + 1, bucket)
            if len(gap) > _WARM_PADS_PER_BUCKET:
                # coarse bucket sets (e.g. (64, 512)) would otherwise pay
                # one compile per admissible row count — hundreds of
                # trivial programs before registration returns. Warm an
                # evenly-spaced subset; uncovered sizes warm in their
                # first live flush (the pre-warmup behavior), bounded by
                # the same closed set either way
                step = max(1, len(gap) // _WARM_PADS_PER_BUCKET)
                gap = list(gap)[::step]
            for total in gap:
                rows = jax.device_put(
                    np.zeros((total,) + key[0], self._in_dtype))
                self._pad_device(rows, bucket, key)
            lo = bucket

    def _stage_in(self, batch: List[_Request], total: int, padded: int):
        """Assemble the padded device batch: coalesce (host), ONE h2d
        transfer of the real examples, pad on device. Returns
        ``(xs, mask, pad_seconds, h2d_seconds)``; falls back to host
        padding when the device path is off (the direct-construction
        default; :class:`ServedModel` enables it for framework nets)."""
        t0 = time.perf_counter()
        with self._span("serving/pad", examples=int(total),
                        padded=int(padded)):
            xs, mask = self._coalesce(batch, padded)
        t1 = time.perf_counter()
        if self._use_device():
            import jax
            with self._span("serving/transfer", direction="h2d"):
                xs = jax.device_put(xs).block_until_ready()
                if mask is not None:
                    mask = jax.device_put(mask)
            t2 = time.perf_counter()
            if int(xs.shape[0]) != padded:
                with self._span("serving/pad", padded=int(padded)):
                    xs = self._pad_device(
                        xs, padded, batch[0].key).block_until_ready()
            return xs, mask, (t1 - t0) + (time.perf_counter() - t2), t2 - t1
        if int(xs.shape[0]) != padded:
            with self._span("serving/pad", padded=int(padded)):
                out = np.zeros((padded,) + xs.shape[1:], xs.dtype)
                out[:xs.shape[0]] = xs
                xs = out
        return xs, mask, time.perf_counter() - t0, 0.0

    def _stage_out(self, ys, total: int):
        """Slice the padding off (on device, when the forward's output
        lives there) and cross device→host ONCE; bf16 outputs are cast to
        f32 on the host side of the transfer — half the wire bytes."""
        if getattr(ys, "ndim", 0) >= 1 and ys.shape[0] >= total:
            ys = ys[:total]
        with self._span("serving/transfer", direction="d2h",
                        examples=int(total)):
            out = np.asarray(ys)
        if out.dtype.name == "bfloat16":
            out = out.astype(np.float32)
        return out

    def _forward_batch(self, xs, mask):
        if self._in_flight is not None:
            self._in_flight.acquire()
        try:
            return self._forward(xs) if mask is None \
                else self._forward(xs, mask)
        finally:
            if self._in_flight is not None:
                self._in_flight.release()

    def _flush_once(self, batch: List[_Request], total: int, padded: int):
        """stage-in → forward → stage-out, returning the host result rows
        plus the pad/transfer timing split."""
        xs, mask, t_pad, t_h2d = self._stage_in(batch, total, padded)
        ys = self._forward_batch(xs, mask)
        if self._use_device():
            # jit dispatch is async: synchronize HERE so the compute tail
            # lands in the forward's share of serving/flush, not in the
            # d2h transfer span below (on the axon tunnel
            # block_until_ready under-reports — the value fetch is still
            # the honest boundary there, see the verify skill)
            import jax
            ys = jax.block_until_ready(ys)
        t0 = time.perf_counter()
        out = self._stage_out(ys, total)
        return out, t_pad, t_h2d + (time.perf_counter() - t0)

    def _run_batch(self, batch: List[_Request]):
        try:
            total = sum(r.n for r in batch)
            padded = (bucket_for(self._bb, total, "batch")
                      if self._bb else total)
            flush_start = time.perf_counter()
            if self._label is not None:
                # request-scoped tracing (docs/OBSERVABILITY.md): ONE
                # shared serving/flush span on the scheduler thread — the
                # serving/pad + serving/transfer stage spans and compiles
                # inside the forward nest under it — and each request's
                # queue-wait span below links to it, so p99 decomposes
                # into queue vs pad vs transfer vs compute per trace
                from ..monitor.tracer import get_tracer
                with get_tracer().span(
                        "serving/flush", cat="serving", model=self.name,
                        examples=int(total), padded=int(padded),
                        requests=len(batch)) as flush_ctx:
                    ys, t_pad, t_xfer = self._flush_once(batch, total,
                                                         padded)
            else:
                flush_ctx = None
                ys, t_pad, t_xfer = self._flush_once(batch, total, padded)
            h = self._metric_handles()
            if h is not None:
                h["batch"].observe(float(total))
                h["pad"].observe(t_pad * 1e3)
                h["xfer"].observe(t_xfer * 1e3)
            done = time.monotonic()
            if flush_ctx is not None:
                from ..monitor.tracer import get_tracer
                tracer = get_tracer()
                for r in batch:
                    if r.ctx is None:
                        continue
                    tracer.record_complete(
                        "serving/queue_wait", r.t_perf,
                        max(flush_start - r.t_perf, 0.0), cat="serving",
                        parent=r.ctx, model=self.name,
                        flush_span_id=f"{flush_ctx.span_id:x}")
            pos = 0
            for r in batch:
                yr = ys[pos:pos + r.n]
                pos += r.n
                if (r.padded_t is not None and r.padded_t != r.orig_t
                        and yr.ndim >= 2 and yr.shape[1] == r.padded_t):
                    # per-timestep output ([b, T', ...] tracking the padded
                    # time dim): strip the time padding from the result too
                    yr = yr[:, :r.orig_t]
                if self._cache is not None and r.ckey is not None:
                    self._cache_store(r.ckey, yr)
                if _complete(r.fut, yr):
                    self._note_done(
                        "ok", (done - r.t_enq) * 1e3,
                        exemplar=(f"{r.ctx.trace_id:x}" if r.ctx is not None
                                  else None))
        except Exception as e:
            for r in batch:
                if not r.fut.done() and _complete(r.fut, exc=e):
                    self._note_done("error")

    # ------------------------------------------------------------ lifecycle
    def flush(self, wait: bool = True, timeout: float = 30.0) -> bool:
        """Force everything queued to flush now (ignoring linger).
        ``wait=True`` blocks until the queue is empty and no flush is
        executing; returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            if not self._queue and not self._running:
                return True       # idle: nothing to flush, and leaving
                                  # _force armed would rob the NEXT lone
                                  # request of its linger coalescing
            self._force = True
            self._cond.notify_all()
            if not wait:
                return True
            while self._queue or self._running:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def set_admission(self, max_queue_examples: Optional[int] = None,
                      linger_ms: Optional[float] = None
                      ) -> Dict[str, Any]:
        """Mutate the admission knobs of a LIVE batcher — the control
        plane's serving actuator. A lowered ``max_queue_examples`` only
        tightens the gate for FUTURE submits (already-queued examples are
        served, never evicted — admission was a promise); a lowered
        ``linger_ms`` wakes the scheduler so a queue that was sitting out
        a long linger re-arms on the new deadline immediately. Returns
        the previous values so a resolve-edge can restore them."""
        with self._cond:
            prev = {"max_queue_examples": self.max_queue_examples,
                    "linger_ms": self.linger_ms}
            if max_queue_examples is not None:
                cap = int(max_queue_examples)
                if cap < 1:
                    raise ValueError(
                        f"max_queue_examples must be >= 1, got {cap}")
                self.max_queue_examples = cap
            if linger_ms is not None:
                lg = float(linger_ms)
                if lg < 0:
                    raise ValueError(f"linger_ms must be >= 0, got {lg}")
                self.linger_ms = lg
            self._cond.notify_all()
        return prev

    def close(self, drain: bool = True, timeout: float = 30.0):
        """Stop admission, then either serve (``drain=True`` — no accepted
        request is dropped) or fail (``drain=False`` → OverloadedError)
        everything still queued, and join the scheduler thread."""
        with self._cond:
            self._closed = True
            dropped: List[_Request] = []
            if not drain:
                dropped, self._queue = self._queue, []
                self._queued_examples = 0
                self._key_examples.clear()
            self._cond.notify_all()
        for r in dropped:
            if _complete(r.fut, exc=OverloadedError(
                    f"model {self.name!r} shut down without drain")):
                # counter only — _note_done's qps window belongs to the
                # scheduler thread, which may still be draining a batch
                self._count("rejected")
        self._thread.join(timeout)
        # release device residency: the recycled pad buffers (and the
        # response cache) must not outlive the model they served —
        # device_memory_in_use_bytes drops back after unregister. A join
        # that TIMED OUT leaves the scheduler draining: _dev_bufs is its
        # data structure (mutating it here would race), so only clear
        # when the thread is provably dead — the scheduler's own _loop
        # finally releases the buffers when the drain actually ends
        if not self._thread.is_alive():
            self._dev_bufs.clear()
        if self._cache is not None:
            with self._cache_lock:
                self._cache.clear()
                self._cache_examples = 0
            # belt for the drain-window race: a hit that appended between
            # the scheduler's own exit-zeroing and the join lands here;
            # anything later is refused by _note_done's closed-and-dead
            # guard — between the two, a dead model always reads qps 0
            h = self._metric_handles()
            if h is not None:
                with self._cond:
                    self._done_times.clear()
                    h["qps"].set(0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
