"""Keras model import (reference ``deeplearning4j-modelimport`` — SURVEY.md §2.6)."""
from .model_import import KerasModelImport, KerasLayerMapper

__all__ = ["KerasModelImport", "KerasLayerMapper"]
