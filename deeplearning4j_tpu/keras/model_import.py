"""Keras model import from HDF5.

TPU-native equivalent of reference ``deeplearning4j-modelimport/`` (SURVEY.md
§2.6): ``KerasModelImport.java:50-233`` entry points (Sequential →
MultiLayerNetwork, functional → ComputationGraph), per-layer mapping
(``KerasLayer`` + ``keras/layers/**``, Keras 1 & 2 via
``config/KerasLayerConfiguration.java:43-71``) and weight copying with layout
transposition. The reference reads HDF5 through JavaCPP (``Hdf5Archive.java:51``,
native libhdf5); here h5py provides the container access and the interesting
work — config translation + weight layout — is this module.

Weight layout notes (TF-backend Keras, the reference's supported ordering):
 - Dense kernel [in, out] — matches our "W" directly.
 - Conv2D kernel HWIO — matches our internal HWIO layout directly (the
   reference permutes to its OIHW; we deliberately chose HWIO to match
   XLA/TPU, which makes Keras import a straight copy).
 - LSTM kernels [in, 4H] with Keras gate order (i, f, c, o); ours is
   (i, f, o, g=c) — columns are permuted per gate block.
 - BatchNormalization gamma/beta are params; moving mean/var land in the
   layer *state* pytree.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf import NeuralNetConfiguration, MultiLayerConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (DenseLayer, ConvolutionLayer, SubsamplingLayer,
                              BatchNormalization, DropoutLayer, ActivationLayer,
                              EmbeddingSequenceLayer, LSTM, SimpleRnn,
                              LastTimeStep, OutputLayer, RnnOutputLayer,
                              LossLayer, GlobalPoolingLayer, ZeroPaddingLayer,
                              Upsampling2D, Upsampling1D, PoolingType,
                              ConvolutionMode, SeparableConvolution2D,
                              DepthwiseConvolution2D, Convolution1DLayer,
                              Subsampling1DLayer, Cropping2D, Bidirectional)
from ..nn.conf.graph import MergeVertex, ElementWiseVertex
from ..nn.multilayer import MultiLayerNetwork
from ..nn.graph import ComputationGraph

_ACTIVATIONS = {"relu": "relu", "softmax": "softmax", "sigmoid": "sigmoid",
                "tanh": "tanh", "linear": "identity", "elu": "elu",
                "selu": "selu", "softplus": "softplus", "softsign": "softsign",
                "hard_sigmoid": "hardsigmoid", "swish": "swish"}

_LOSSES = {"categorical_crossentropy": "mcxent",
           "sparse_categorical_crossentropy": "sparse_mcxent",
           "binary_crossentropy": "xent",
           "mean_squared_error": "mse", "mse": "mse",
           "mean_absolute_error": "mean_absolute_error", "mae":
           "mean_absolute_error",
           "kullback_leibler_divergence": "kl_divergence",
           "poisson": "poisson", "cosine_proximity": "cosine_proximity",
           "hinge": "hinge", "squared_hinge": "squared_hinge"}


def _act(name: Optional[str]) -> str:
    if not name:
        return "identity"
    key = str(name).lower()
    if key not in _ACTIVATIONS:
        raise ValueError(f"Unsupported Keras activation '{name}'")
    return _ACTIVATIONS[key]


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _padding_mode(cfg) -> str:
    mode = cfg.get("padding", cfg.get("border_mode", "valid"))
    return (ConvolutionMode.Same if str(mode).lower() == "same"
            else ConvolutionMode.Truncate)


def _maybe_last_step(layer, cfg):
    """Keras ``return_sequences=False`` returns the final timestep only —
    wrap in LastTimeStep (reference ``KerasLstm`` does the same)."""
    if cfg.get("return_sequences", False):
        return layer
    return LastTimeStep(inner=layer)


#: custom-layer SPI (reference ``KerasLayer.registerCustomLayer`` +
#: ``keras/layers/custom/``): Keras class name → mapper(cfg) returning a
#: layer config; optional weight_setter(params_dict, state_dict, weights)
#: overrides the built-in weight copy for that layer.
_CUSTOM_LAYERS: Dict[str, Tuple[Any, Optional[Any]]] = {}


def register_custom_layer(class_name: str, mapper, weight_setter=None):
    """Register an importer for a custom Keras layer type. ``mapper(cfg)``
    receives the Keras config dict and returns a layer config;
    ``weight_setter(params, state, weights)`` (optional) receives the layer's
    param/state dicts and the {short name: array} weight map."""
    _CUSTOM_LAYERS[str(class_name)] = (mapper, weight_setter)


registerCustomLayer = register_custom_layer


class KerasLayerMapper:
    """Config-dict → layer-config translation (reference ``KerasLayer``
    subclasses). Keras 1 and 2 key spellings both accepted (the reference
    carries both in ``config/KerasLayerConfiguration.java:43-71``)."""

    SKIPPED = {"InputLayer", "Flatten", "Reshape"}  # handled structurally

    @staticmethod
    def map(class_name: str, cfg: Dict) -> Optional[Any]:
        if class_name in _CUSTOM_LAYERS:
            mapper, setter = _CUSTOM_LAYERS[class_name]
            layer = mapper(cfg)
            if setter is not None:
                # carried to _set_layer_weights (custom copy semantics)
                layer._keras_weight_setter = setter
            return layer
        m = getattr(KerasLayerMapper, f"_map_{class_name.lower()}", None)
        if m is None:
            raise ValueError(
                f"Unsupported Keras layer type '{class_name}' — register an "
                f"importer with register_custom_layer('{class_name}', ...)")
        return m(cfg)

    # ------------------------------------------------------------- dense etc.
    @staticmethod
    def _map_dense(cfg):
        return DenseLayer(n_out=int(cfg.get("units", cfg.get("output_dim"))),
                          activation=_act(cfg.get("activation")),
                          has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))

    @staticmethod
    def _map_dropout(cfg):
        # Keras rate = drop prob; our dropout = retain prob (reference 0.9.x)
        return DropoutLayer(dropout=1.0 - float(cfg.get("rate", cfg.get("p", 0.5))))

    @staticmethod
    def _map_activation(cfg):
        return ActivationLayer(activation=_act(cfg.get("activation")))

    @staticmethod
    def _map_conv2d(cfg):
        k = _pair(cfg.get("kernel_size",
                          (cfg.get("nb_row", 3), cfg.get("nb_col", 3))))
        return ConvolutionLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter"))),
            kernel_size=k,
            stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
            dilation=_pair(cfg.get("dilation_rate", (1, 1))),
            convolution_mode=_padding_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))

    _map_convolution2d = _map_conv2d  # Keras 1 name

    @staticmethod
    def _map_maxpooling2d(cfg):
        return SubsamplingLayer(
            pooling_type=PoolingType.MAX,
            kernel_size=_pair(cfg.get("pool_size", (2, 2))),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=_padding_mode(cfg))

    @staticmethod
    def _map_averagepooling2d(cfg):
        return SubsamplingLayer(
            pooling_type=PoolingType.AVG,
            kernel_size=_pair(cfg.get("pool_size", (2, 2))),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=_padding_mode(cfg))

    @staticmethod
    def _map_globalmaxpooling2d(cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.MAX)

    @staticmethod
    def _map_globalaveragepooling2d(cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.AVG)

    @staticmethod
    def _map_globalmaxpooling1d(cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.MAX)

    @staticmethod
    def _map_globalaveragepooling1d(cfg):
        return GlobalPoolingLayer(pooling_type=PoolingType.AVG)

    @staticmethod
    def _map_zeropadding2d(cfg):
        p = cfg.get("padding", (1, 1))
        if isinstance(p, (list, tuple)) and len(p) == 2 \
                and isinstance(p[0], (list, tuple)):
            pads = (int(p[0][0]), int(p[0][1]), int(p[1][0]), int(p[1][1]))
        else:
            ph, pw = _pair(p)
            pads = (ph, ph, pw, pw)
        return ZeroPaddingLayer(padding=pads)

    @staticmethod
    def _map_upsampling2d(cfg):
        return Upsampling2D(size=_pair(cfg.get("size", (2, 2))))

    @staticmethod
    def _map_batchnormalization(cfg):
        return BatchNormalization(
            decay=float(cfg.get("momentum", 0.99)),
            eps=float(cfg.get("epsilon", 1e-3)))

    @staticmethod
    def _map_embedding(cfg):
        return EmbeddingSequenceLayer(
            n_in=int(cfg.get("input_dim")),
            n_out=int(cfg.get("output_dim")),
            activation="identity", has_bias=False)

    @staticmethod
    def _map_lstm(cfg):
        layer = LSTM(n_out=int(cfg.get("units", cfg.get("output_dim"))),
                     activation=_act(cfg.get("activation", "tanh")),
                     gate_activation=_act(cfg.get("recurrent_activation",
                                                  cfg.get("inner_activation",
                                                          "sigmoid"))))
        return _maybe_last_step(layer, cfg)

    @staticmethod
    def _map_simplernn(cfg):
        layer = SimpleRnn(n_out=int(cfg.get("units", cfg.get("output_dim"))),
                          activation=_act(cfg.get("activation", "tanh")))
        return _maybe_last_step(layer, cfg)

    @staticmethod
    def _map_separableconv2d(cfg):
        return SeparableConvolution2D(
            n_out=int(cfg.get("filters", cfg.get("nb_filter"))),
            kernel_size=_pair(cfg.get("kernel_size",
                                      (cfg.get("nb_row", 3),
                                       cfg.get("nb_col", 3)))),
            stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
            dilation=_pair(cfg.get("dilation_rate", (1, 1))),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_padding_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))

    @staticmethod
    def _map_depthwiseconv2d(cfg):
        return DepthwiseConvolution2D(
            kernel_size=_pair(cfg.get("kernel_size", (3, 3))),
            stride=_pair(cfg.get("strides", (1, 1))),
            dilation=_pair(cfg.get("dilation_rate", (1, 1))),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_padding_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)))

    @staticmethod
    def _map_conv1d(cfg):
        k = cfg.get("kernel_size", cfg.get("filter_length", 3))
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = cfg.get("strides", cfg.get("subsample_length", 1))
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        d = cfg.get("dilation_rate", 1)
        d = int(d[0] if isinstance(d, (list, tuple)) else d)
        return Convolution1DLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter"))),
            kernel_size=k, stride=s, dilation=d,
            convolution_mode=_padding_mode(cfg),
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))

    _map_convolution1d = _map_conv1d  # Keras 1 name

    @staticmethod
    def _map_maxpooling1d(cfg):
        p = cfg.get("pool_size", cfg.get("pool_length", 2))
        p = int(p[0] if isinstance(p, (list, tuple)) else p)
        s = cfg.get("strides", cfg.get("stride")) or p
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        return Subsampling1DLayer(pooling_type=PoolingType.MAX,
                                  kernel_size=p, stride=s,
                                  convolution_mode=_padding_mode(cfg))

    @staticmethod
    def _map_averagepooling1d(cfg):
        p = cfg.get("pool_size", cfg.get("pool_length", 2))
        p = int(p[0] if isinstance(p, (list, tuple)) else p)
        s = cfg.get("strides", cfg.get("stride")) or p
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        return Subsampling1DLayer(pooling_type=PoolingType.AVG,
                                  kernel_size=p, stride=s,
                                  convolution_mode=_padding_mode(cfg))

    @staticmethod
    def _map_leakyrelu(cfg):
        # Keras 3 spells it negative_slope; Keras 1/2 alpha. Default 0.3
        # (Keras) ≠ 0.01 (our bare "leakyrelu") — carry it explicitly
        alpha = float(cfg.get("negative_slope", cfg.get("alpha", 0.3)))
        return ActivationLayer(activation=f"leakyrelu:{alpha}")

    @staticmethod
    def _map_elu(cfg):
        return ActivationLayer(
            activation=f"elu:{float(cfg.get('alpha', 1.0))}")

    @staticmethod
    def _map_cropping2d(cfg):
        c = cfg.get("cropping", ((0, 0), (0, 0)))
        if isinstance(c, (list, tuple)) and c and isinstance(c[0], (list, tuple)):
            crops = (int(c[0][0]), int(c[0][1]), int(c[1][0]), int(c[1][1]))
        else:
            ch, cw = _pair(c)
            crops = (ch, ch, cw, cw)
        return Cropping2D(cropping=crops)

    @staticmethod
    def _map_upsampling1d(cfg):
        sz = cfg.get("size", cfg.get("length", 2))
        return Upsampling1D(size=int(sz[0] if isinstance(sz, (list, tuple))
                                     else sz))

    @staticmethod
    def _map_spatialdropout2d(cfg):
        # per-feature-map dropout approximated by elementwise dropout (the
        # reference maps SpatialDropout to plain DropoutLayer too)
        return DropoutLayer(dropout=1.0 - float(cfg.get("rate",
                                                        cfg.get("p", 0.5))))

    _map_spatialdropout1d = _map_spatialdropout2d

    @staticmethod
    def _map_bidirectional(cfg):
        inner_cfg = cfg.get("layer", {})
        inner = KerasLayerMapper.map(inner_cfg.get("class_name"),
                                     inner_cfg.get("config", {}))
        merge = cfg.get("merge_mode", "concat")
        modes = {"concat": "concat", "sum": "add", "ave": "ave", "mul": "mul"}
        if merge not in modes:
            # merge_mode=None means TWO output tensors — structurally
            # unrepresentable as one wrapped layer; fail loudly
            raise ValueError(f"Unsupported Bidirectional merge_mode "
                             f"{merge!r} (supported: {sorted(modes)})")
        mode = modes[merge]
        if type(inner).__name__ == "LastTimeStep":
            # wrap order: Bidirectional over the RNN, LastTimeStep outside
            return LastTimeStep(inner=Bidirectional(inner=inner.inner,
                                                    mode=mode))
        return Bidirectional(inner=inner, mode=mode)

    @staticmethod
    def _map_timedistributed(cfg):
        """TimeDistributed wrapper (reference ``KerasTimeDistributed``,
        dual-name row in ``KerasLayerConfiguration.java``): per-timestep
        application of the wrapped layer. Dense & co. already apply
        per-timestep on [b, T, f] activations, so the mapping is the inner
        layer itself."""
        inner = cfg.get("layer", {})
        return KerasLayerMapper.map(inner.get("class_name"),
                                    inner.get("config", {}))


# --------------------------------------------------------------------- parse
def _decode(v):
    if isinstance(v, bytes):
        return v.decode("utf-8")
    return v


def _tensor_source(entry):
    """Source layer name from one inbound tensor reference: Keras 3
    ``__keras_tensor__`` dicts carry it in ``keras_history``; Keras 1/2 use
    ``[name, node_idx, tensor_idx, ...]`` lists or bare names."""
    if isinstance(entry, dict):
        hist = entry.get("config", {}).get("keras_history", [None])
        return hist[0]
    if isinstance(entry, (list, tuple)):
        return entry[0]
    return entry


def _inbound_names(inbound) -> List[str]:
    """Input layer names from a layer's ``inbound_nodes`` across Keras
    dialects (1/2: nested lists; 3: {"args": [...]} call records)."""
    if not inbound:
        return []
    node = inbound[0]
    if isinstance(node, dict):  # Keras 3
        args = node.get("args", [])
        if not args:
            return []
        first = args[0]
        entries = first if isinstance(first, list) else [first]
        return [_tensor_source(e) for e in entries]
    return [_tensor_source(e) for e in node]


def _io_names(spec) -> List[str]:
    """Model input/output layer names: Keras 2 nests ``[[name, 0, 0], ...]``;
    Keras 3 flattens a single entry to ``[name, 0, 0]``."""
    if not spec:
        return []
    if isinstance(spec[0], (list, tuple)):
        return [s[0] for s in spec]
    if (len(spec) == 3 and isinstance(spec[0], str)
            and isinstance(spec[1], int)):
        return [spec[0]]
    return [s if isinstance(s, str) else s[0] for s in spec]


def _read_model_config(f) -> Dict:
    raw = f.attrs.get("model_config")
    if raw is None:
        raise ValueError("HDF5 file has no 'model_config' attribute — not a "
                         "Keras full-model save (weights-only files need the "
                         "architecture JSON, reference importKerasModelAndWeights"
                         "(json, h5) overload)")
    return json.loads(_decode(raw))


def _layer_list(model_cfg: Dict) -> List[Dict]:
    cfg = model_cfg.get("config")
    if isinstance(cfg, list):  # Keras 1 / early 2
        return cfg
    return cfg["layers"]


#: Keras-1 weight-name suffixes → Keras-2 canonical names (the reference's
#: dual-dialect table, ``KerasLayerConfiguration.java:43-71``). Longest
#: suffixes first so ``_running_mean`` wins over ``_b``-style matches.
_K1_WEIGHT_SUFFIXES = (("running_mean", "moving_mean"),
                       ("running_std", "moving_variance"),
                       ("gamma", "gamma"), ("beta", "beta"),
                       ("U", "recurrent_kernel"),
                       ("W", "kernel"), ("b", "bias"))


def _canonical_weight_name(short: str) -> str:
    for suf, canon in _K1_WEIGHT_SUFFIXES:
        if short == suf or short.endswith("_" + suf):
            return canon
    return short


def _layer_weights(f, name: str) -> Dict[str, np.ndarray]:
    """{short weight name: array} for a layer from model_weights; Keras-1
    ``<layer>_W``-style names normalized to the Keras-2 spellings."""
    mw = f["model_weights"] if "model_weights" in f else f
    if name not in mw:
        return {}
    grp = mw[name]
    weight_names = [_decode(n) for n in grp.attrs.get("weight_names", [])]
    out = {}
    for wn in weight_names:
        short = wn.split("/")[-1].split(":")[0]
        canon = _canonical_weight_name(short)
        # Bidirectional wrappers carry direction in a PATH SEGMENT
        # ('forward_lstm/...'); anchor the match there so a layer merely
        # NAMED 'feedforward' is not misclassified
        segs = wn.split("/")[:-1]
        if any(g == "forward" or g.startswith("forward_") for g in segs):
            canon = "forward_" + canon
        elif any(g == "backward" or g.startswith("backward_") for g in segs):
            canon = "backward_" + canon
        out[canon] = np.asarray(grp[wn])
    return out


def _lstm_reorder(arr: np.ndarray, H: int) -> np.ndarray:
    """Keras gate order (i, f, c, o) → ours (i, f, o, g=c), last axis."""
    i, fgate, cgate, o = (arr[..., 0:H], arr[..., H:2 * H],
                          arr[..., 2 * H:3 * H], arr[..., 3 * H:4 * H])
    return np.concatenate([i, fgate, o, cgate], axis=-1)


def _set_layer_weights(net_params, net_states, key, layer_conf, weights):
    """Copy Keras weights into the param/state pytrees for layer ``key``."""
    import jax.numpy as jnp
    setter = getattr(layer_conf, "_keras_weight_setter", None)
    if setter is not None:  # custom-layer SPI override
        p = dict(net_params.get(key, {}))
        s = dict(net_states.get(key, {}))
        setter(p, s, weights)
        net_params[key] = {k: jnp.asarray(v) for k, v in p.items()}
        net_states[key] = s
        return
    if type(layer_conf).__name__ == "LastTimeStep":
        layer_conf = layer_conf.inner  # params live on the wrapped layer
    t = type(layer_conf).__name__
    p = net_params.get(key, {})

    def put(name, arr):
        tgt = p[name]
        if tuple(arr.shape) != tuple(tgt.shape):
            raise ValueError(f"Layer {key} ({t}): weight '{name}' shape "
                             f"{arr.shape} != expected {tuple(tgt.shape)}")
        p[name] = jnp.asarray(arr, dtype=tgt.dtype)

    if t in ("DenseLayer", "OutputLayer", "RnnOutputLayer"):
        put("W", weights["kernel"] if "kernel" in weights else weights["W"])
        if "b" in p:
            put("b", weights.get("bias", weights.get("b")))
    elif t in ("ConvolutionLayer", "Convolution1DLayer"):
        put("W", weights["kernel"])  # HWIO == HWIO, straight copy
        if "b" in p:
            put("b", weights["bias"])
    elif t == "DepthwiseConvolution2D":
        # Keras 3 names the depthwise kernel plain "kernel"
        dk = weights.get("depthwise_kernel", weights.get("kernel"))  # [kh,kw,C,m]
        kh, kw, cin, m = dk.shape
        put("W", dk.reshape(kh, kw, 1, cin * m))  # grouped-conv layout
        if "b" in p:
            put("b", weights["bias"])
    elif t == "SeparableConvolution2D":
        dk = weights["depthwise_kernel"]
        kh, kw, cin, m = dk.shape
        put("dW", dk.reshape(kh, kw, 1, cin * m))
        put("pW", weights["pointwise_kernel"])
        if "b" in p:
            put("b", weights["bias"])
    elif t == "Bidirectional":
        H = layer_conf.inner.n_out
        for side, pre in (("fwd", "forward_"), ("bwd", "backward_")):
            sub = p[side]
            for ours, theirs in (("W", "kernel"), ("RW", "recurrent_kernel"),
                                 ("b", "bias")):
                if theirs == "bias" and (ours not in sub
                                         or pre + theirs not in weights):
                    if ours in sub:
                        # use_bias=False inner RNN: zero our initialized
                        # bias (forget gate starts at 1.0) instead of
                        # silently keeping it
                        sub[ours] = jnp.zeros_like(sub[ours])
                    continue
                arr = _lstm_reorder(weights[pre + theirs], H)
                tgt = sub[ours]
                if tuple(arr.shape) != tuple(tgt.shape):
                    raise ValueError(f"Layer {key} Bidirectional {side}.{ours}"
                                     f": {arr.shape} != {tuple(tgt.shape)}")
                sub[ours] = jnp.asarray(arr, tgt.dtype)
    elif t == "BatchNormalization":
        # scale=False / center=False models ship only one of gamma/beta —
        # copy each independently
        if "gamma" in p and "gamma" in weights:
            put("gamma", weights["gamma"])
        if "beta" in p and "beta" in weights:
            put("beta", weights["beta"])
        st = dict(net_states.get(key, {}))
        if "moving_mean" in weights:
            st["mean"] = jnp.asarray(weights["moving_mean"],
                                     net_states[key]["mean"].dtype)
            st["var"] = jnp.asarray(weights["moving_variance"],
                                    net_states[key]["var"].dtype)
        net_states[key] = st
    elif t in ("EmbeddingSequenceLayer", "EmbeddingLayer"):
        put("W", weights["embeddings"])
    elif t == "LSTM":
        H = layer_conf.n_out
        put("W", _lstm_reorder(weights["kernel"], H))
        put("RW", _lstm_reorder(weights["recurrent_kernel"], H))
        if "b" in p:
            if "bias" in weights:
                put("b", _lstm_reorder(weights["bias"], H))
            else:
                # use_bias=False: our init sets forget-gate bias to 1.0 —
                # zero it so the imported model computes what Keras did
                p["b"] = jnp.zeros_like(p["b"])
    elif t == "SimpleRnn":
        put("W", weights["kernel"])
        put("RW", weights["recurrent_kernel"])
        if "b" in p:
            if "bias" in weights:
                put("b", weights["bias"])
            else:
                p["b"] = jnp.zeros_like(p["b"])
    elif not weights:
        pass
    else:
        raise ValueError(f"Weight copy not implemented for layer type {t}")
    net_params[key] = p


def _maybe_permute_dense_kernel(weights: Dict[str, np.ndarray],
                                pre) -> Dict[str, np.ndarray]:
    """Keras flattens conv activations in (h, w, c) order; our
    CnnToFeedForward preprocessor flattens channel-major (c, h, w) —
    reference parity, ``CnnToFeedForwardPreProcessor.java``. A Dense kernel
    following a Flatten must have its input rows permuted accordingly
    (reference ``KerasDense`` dim-ordering handling)."""
    if pre is None or type(pre).__name__ != "CnnToFeedForwardPreProcessor":
        return weights
    k = weights.get("kernel")
    if k is None or k.ndim != 2:
        return weights
    h, w, c = int(pre.height), int(pre.width), int(pre.channels)
    if h * w * c != k.shape[0]:
        return weights
    k2 = k.reshape(h, w, c, -1).transpose(2, 0, 1, 3).reshape(k.shape[0], -1)
    out = dict(weights)
    out["kernel"] = k2
    return out


def _input_type_from_shape(shape) -> Optional[Any]:
    """Keras batch_input_shape/input_shape (batch dim already stripped) →
    InputType, classified by RANK so variable-length sequence shapes like
    ``(None, features)`` stay recurrent. NHWC assumed for rank 3 (TF
    ordering)."""
    if shape is None:
        return None
    shape = tuple(shape)
    if len(shape) == 3:
        h, w, c = shape
        if None in (h, w, c):
            return None  # variable spatial dims: let shape inference handle it
        return InputType.convolutional(h, w, c)
    if len(shape) == 2:
        return (None if shape[-1] is None
                else InputType.recurrent(shape[-1]))
    if len(shape) == 1:
        return (None if shape[0] is None
                else InputType.feed_forward(shape[0]))
    return None


# ------------------------------------------------------------------ importers
class KerasModelImport:
    """Entry points (reference ``KerasModelImport.java:50-233``)."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str,
                                                  enforce_training_config=False):
        import h5py
        with h5py.File(path, "r") as f:
            model_cfg = _read_model_config(f)
            if model_cfg.get("class_name") not in ("Sequential",):
                raise ValueError("Not a Sequential model; use "
                                 "import_keras_model_and_weights")
            layer_cfgs = _layer_list(model_cfg)
            training_cfg = f.attrs.get("training_config")
            loss = None
            if training_cfg is not None:
                loss = json.loads(_decode(training_cfg)).get("loss")

            layers, names, input_type = [], [], None
            for lc in layer_cfgs:
                cls = lc["class_name"]
                cfg = lc.get("config", {})
                if input_type is None:
                    shape = cfg.get("batch_input_shape",
                                    cfg.get("batch_shape"))
                    it = _input_type_from_shape(shape[1:] if shape else None)
                    if it is not None:
                        input_type = it
                if cls in KerasLayerMapper.SKIPPED:
                    continue
                mapped = KerasLayerMapper.map(cls, cfg)
                layers.append(mapped)
                names.append(cfg.get("name", cls.lower()))

            recurrent_stream = _ends_recurrent(layers)
            layers = _convert_last_to_output(layers, loss, recurrent_stream)
            lb = NeuralNetConfiguration.builder().list()
            for l in layers:
                lb.layer(l)
            if input_type is not None:
                lb.set_input_type(input_type)
            conf = lb.build()
            net = MultiLayerNetwork(conf).init()

            # weight copy: keras layer name → our layer index (skipped layers
            # carry no weights)
            li = 0
            for lc in layer_cfgs:
                cls = lc["class_name"]
                cfg = lc.get("config", {})
                if cls in KerasLayerMapper.SKIPPED:
                    continue
                w = _layer_weights(f, cfg.get("name", cls.lower()))
                if w:
                    w = _maybe_permute_dense_kernel(w, conf.preprocessor(li))
                    _set_layer_weights(net.params, net.states, str(li),
                                       conf.layers[li], w)
                li += 1
        return net

    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights

    @staticmethod
    def import_keras_model_and_weights(path: str,
                                       enforce_training_config=False):
        import h5py
        with h5py.File(path, "r") as f:
            model_cfg = _read_model_config(f)
            cls_name = model_cfg.get("class_name")
            if cls_name == "Sequential":
                return KerasModelImport.import_keras_sequential_model_and_weights(
                    path, enforce_training_config)
            if cls_name not in ("Model", "Functional"):
                raise ValueError(f"Unsupported Keras model class '{cls_name}'")
            cfg = model_cfg["config"]
            layer_cfgs = cfg["layers"]
            input_layers = _io_names(cfg["input_layers"])
            output_layers = _io_names(cfg["output_layers"])
            training_cfg = f.attrs.get("training_config")
            loss = None
            if training_cfg is not None:
                loss = json.loads(_decode(training_cfg)).get("loss")

            g = NeuralNetConfiguration.builder().graph_builder()
            g.add_inputs(*input_layers)
            input_types = []
            name_to_conf = {}
            skipped_alias: Dict[str, str] = {}  # skipped layer → its input
            for lc in layer_cfgs:
                cls = lc["class_name"]
                kcfg = lc.get("config", {})
                name = lc.get("name", kcfg.get("name"))
                ins = [skipped_alias.get(src, src)
                       for src in _inbound_names(lc.get("inbound_nodes", []))]
                if cls == "InputLayer":
                    shape = kcfg.get("batch_input_shape", kcfg.get("batch_shape"))
                    it = _input_type_from_shape(shape[1:] if shape else None)
                    input_types.append(it)
                    continue
                if cls in KerasLayerMapper.SKIPPED:
                    skipped_alias[name] = ins[0]
                    continue
                if cls in ("Add",):
                    g.add_vertex(name, ElementWiseVertex(op="add"), *ins)
                    continue
                if cls in ("Concatenate", "Merge"):
                    g.add_vertex(name, MergeVertex(), *ins)
                    continue
                mapped = KerasLayerMapper.map(cls, kcfg)
                if name in output_layers and _is_output_candidate(mapped):
                    mapped = _to_output_layer(mapped, loss)
                name_to_conf[name] = mapped
                g.add_layer(name, mapped, *ins)
            g.set_outputs(*[skipped_alias.get(o, o) for o in output_layers])
            if input_types and all(t is not None for t in input_types):
                g.set_input_types(*input_types)
            conf = g.build()
            net = ComputationGraph(conf).init()
            for name, lconf in name_to_conf.items():
                w = _layer_weights(f, name)
                if w:
                    w = _maybe_permute_dense_kernel(
                        w, conf.input_preprocessors.get(name))
                    _set_layer_weights(net.params, net.states, name, lconf, w)
        return net

    importKerasModelAndWeights = import_keras_model_and_weights


def _is_output_candidate(layer) -> bool:
    return isinstance(layer, DenseLayer) and type(layer) is DenseLayer


def _ends_recurrent(layers) -> bool:
    """Does the activation stream reaching the last layer still have a time
    axis? (Decides OutputLayer vs RnnOutputLayer for the converted head.)"""
    rec = False
    for layer in layers[:-1]:
        t = type(layer).__name__
        if t in ("LSTM", "GravesLSTM", "SimpleRnn", "GravesBidirectionalLSTM",
                 "Bidirectional", "EmbeddingSequenceLayer"):
            rec = True
        elif t in ("LastTimeStep", "GlobalPoolingLayer", "ConvolutionLayer",
                   "SubsamplingLayer"):
            # DenseLayer deliberately NOT here: Keras Dense on 3D input applies
            # per-timestep, so LSTM(return_sequences)->Dense keeps the time
            # axis and the head must stay RnnOutputLayer (reference KerasLstm/
            # RnnOutputLayer pairing)
            rec = False
    return rec


def _to_output_layer(layer: DenseLayer, loss, recurrent=False):
    cls = RnnOutputLayer if recurrent else OutputLayer
    return cls(n_out=layer.n_out, activation=layer.activation,
               has_bias=layer.has_bias,
               loss=_LOSSES.get(str(loss), "mcxent"))


def _convert_last_to_output(layers, loss, recurrent=False):
    """The reference converts the final Keras layer + training loss into a
    DL4J output layer; without a training config it defaults to MCXENT, which
    preserves inference behavior exactly. A recurrent stream gets
    RnnOutputLayer (per-timestep head) like the reference's KerasLstm→
    RnnOutputLayer pairing."""
    if not layers:
        return layers
    last = layers[-1]
    if _is_output_candidate(last):
        layers = layers[:-1] + [_to_output_layer(last, loss, recurrent)]
    return layers
