"""Tensor (model) parallelism: param sharding rules over the ``model`` axis.

Net-new vs the reference (SURVEY.md §2.4: only data parallelism exists there);
included because the mesh design makes TP nearly free to express: annotate
parameter shardings, jit the SAME train step, and XLA's SPMD partitioner
inserts the all-gathers/reduce-scatters.

``megatron_rules`` gives the classic pairing for MLP stacks: even layers split
the output dim (column parallel), odd layers split the input dim (row
parallel), so activations stay sharded between the pair and only one collective
per pair is needed.
"""
from __future__ import annotations

import re
from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import DATA_AXIS, MODEL_AXIS, batch_sharded, replicated
from ..monitor.jitwatch import monitored_jit


def megatron_rules(net, axis: str = MODEL_AXIS) -> Dict[str, P]:
    """Alternating column/row parallel specs for the network's dense-family
    params: {param_path_regex: PartitionSpec}. Layer index parity decides the
    split dim; biases follow their weight's output sharding.

    Works for BOTH containers. On a ComputationGraph the vertices walk in
    builder order; `SelfAttentionLayer` gets the Megatron attention block
    pattern (Wq/Wk/Wv column-parallel — the head dim splits — and Wo
    row-parallel, output bias replicated), Dense-family vertices alternate
    column/row so FFN up/down projections pair up, and everything else
    (LayerNorm gain/bias, embeddings, routers) stays replicated by the
    default rule."""
    rules: Dict[str, P] = {}
    layers = getattr(net.conf, "layers", None)
    if layers is not None:                     # MultiLayerNetwork
        for i, _ in enumerate(layers):
            col = (i % 2 == 0)
            if col:
                rules[rf"^{i}/W$"] = P(None, axis)
                rules[rf"^{i}/b$"] = P(axis)
            else:
                rules[rf"^{i}/W$"] = P(axis, None)
                rules[rf"^{i}/b$"] = P()
        return rules
    parity = 0                                 # ComputationGraph
    for name, v in net.conf.vertices.items():
        k = re.escape(name)
        tname = type(v).__name__
        if tname == "SelfAttentionLayer":
            rules[rf"^{k}/W[qkv]$"] = P(None, axis)
            rules[rf"^{k}/Wo$"] = P(axis, None)
            rules[rf"^{k}/b$"] = P()
            parity = 0        # attention output is row-reduced → next col
        elif tname in ("DenseLayer", "OutputLayer", "RnnOutputLayer"):
            if parity % 2 == 0:
                rules[rf"^{k}/W$"] = P(None, axis)
                rules[rf"^{k}/b$"] = P(axis)
            else:
                rules[rf"^{k}/W$"] = P(axis, None)
                rules[rf"^{k}/b$"] = P()
            parity += 1
        elif tname == "MoEDenseLayer":
            # participates in the column/row pairing like a Dense layer so
            # its down-projection partner still gets the row rule (expert W
            # is [E, in, out]; the router Wg stays replicated). Under an
            # ep+tp mesh, expert_rules' expert-dim sharding takes priority
            # via extra_rules ordering.
            if parity % 2 == 0:
                rules[rf"^{k}/W$"] = P(None, None, axis)
                rules[rf"^{k}/b$"] = P(None, axis)
            else:
                rules[rf"^{k}/W$"] = P(None, axis, None)
                rules[rf"^{k}/b$"] = P()
            parity += 1
    return rules


def _spec_for(path: str, rules: Dict[str, P]) -> P:
    for pat, spec in rules.items():
        if re.search(pat, path):
            return spec
    return P()


def param_shardings(params, mesh: Mesh, rules: Dict[str, P]):
    """NamedSharding pytree for ``params`` from path-regex rules."""
    def one(keypath, leaf):
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in keypath)
        spec = _spec_for(path, rules)
        # drop axes that don't divide the dim (falls back to replication)
        dims = np.shape(leaf)
        cleaned = []
        for d, s in zip(dims, tuple(spec) + (None,) * (len(dims) - len(tuple(spec)))):
            if s is None:
                cleaned.append(None)
            else:
                size = mesh.shape[s]
                cleaned.append(s if d % size == 0 else None)
        return NamedSharding(mesh, P(*cleaned))
    return jax.tree_util.tree_map_with_path(one, params)


def tensor_parallel_step(net, mesh: Mesh, rules: Optional[Dict[str, P]] = None,
                         donate: bool = True):
    """Jit the network's train step with TP param shardings (+DP over the
    ``data`` axis when present in the mesh). Returns (step, place) where
    ``place(net)`` device_puts the model state according to the rules."""
    if rules is None:
        rules = megatron_rules(net)
    raw = net._raw_step(False)
    p_sh = param_shardings(net.params, mesh, rules)
    # updater state mirrors its param's sharding (Adam moments etc.)
    upd_sh = _mirror_updater_shardings(net, mesh, rules)
    repl = replicated(mesh)
    data = (batch_sharded(mesh) if DATA_AXIS in mesh.axis_names else repl)
    in_sh = (p_sh, repl, upd_sh, repl, repl, data, data, None, None)
    out_sh = (p_sh, repl, upd_sh, repl)

    step = monitored_jit(raw, name="tensor/step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())

    def place(model):
        model.params = jax.device_put(model.params, p_sh)
        model.states = jax.device_put(model.states, repl)
        model.updater_state = jax.device_put(model.updater_state, upd_sh)

    return step, place


def _mirror_updater_shardings(net, mesh, rules):
    """Updater state entries shaped like a param inherit that param's sharding
    (Adam moments etc. must shard WITH their param, or TP's optimizer-state
    memory saving is silently lost); everything else is replicated.

    Updater-state keypaths look like ``layer/param/slot`` (e.g. ``0/W/0`` for
    Adam's first moment) or ``layer/param`` for single-slot updaters, so the
    param name is searched among ALL path segments, not just the last."""
    p_sh_flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(net.params)[0]:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in keypath)
        p_sh_flat[(path, np.shape(leaf))] = NamedSharding(
            mesh, _clean_spec(_spec_for(path, rules), np.shape(leaf), mesh))

    def one(keypath, leaf):
        parts = [str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath]
        shape = np.shape(leaf)
        for (ppath, pshape), sh in p_sh_flat.items():
            psegs = ppath.split("/")
            # same layer key, same shape, and the param name appears on the
            # state leaf's path (tuple slots append a trailing index segment)
            if (shape == pshape and parts and psegs
                    and parts[0] == psegs[0] and psegs[-1] in parts[1:]):
                return sh
        return NamedSharding(mesh, P())
    return jax.tree_util.tree_map_with_path(one, net.updater_state)


def _clean_spec(spec, dims, mesh):
    cleaned = []
    for d, s in zip(dims, tuple(spec) + (None,) * (len(dims) - len(tuple(spec)))):
        if s is None or d % mesh.shape[s] != 0:
            cleaned.append(None)
        else:
            cleaned.append(s)
    return P(*cleaned)
