"""Tensor (model) parallelism: param sharding rules over the ``model`` axis.

Net-new vs the reference (SURVEY.md §2.4: only data parallelism exists there);
included because the mesh design makes TP nearly free to express: annotate
parameter shardings, jit the SAME train step, and XLA's SPMD partitioner
inserts the all-gathers/reduce-scatters.

``megatron_rules`` gives the classic pairing for MLP stacks: even layers split
the output dim (column parallel), odd layers split the input dim (row
parallel), so activations stay sharded between the pair and only one collective
per pair is needed.

The spec machinery (path-regex rules → NamedSharding pytrees, updater-state
mirroring) lives in ``parallel/mesh.py`` — the unified substrate — so the
same rules compose with data parallelism and ZeRO on a 2-D mesh
(``ParallelWrapper.Builder.tensor_parallel`` /
``sharding.data_parallel_step(tp_rules=...)``).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import (DATA_AXIS, MODEL_AXIS, batch_sharded, replicated,
                   clean_spec as _clean_spec, spec_for_path as _spec_for,
                   mirror_updater_shardings, record_step, require_axes,
                   rule_shardings, zero_update_specs)
from ..monitor.jitwatch import monitored_jit


def megatron_rules(net, axis: str = MODEL_AXIS) -> Dict[str, P]:
    """Alternating column/row parallel specs for the network's dense-family
    params: {param_path_regex: PartitionSpec}. Layer index parity decides the
    split dim; biases follow their weight's output sharding.

    Works for BOTH containers. On a ComputationGraph the vertices walk in
    builder order; `SelfAttentionLayer` gets the Megatron attention block
    pattern (Wq/Wk/Wv column-parallel — the head dim splits — and Wo
    row-parallel, output bias replicated), Dense-family vertices alternate
    column/row so FFN up/down projections pair up, and everything else
    (LayerNorm gain/bias, embeddings, routers) stays replicated by the
    default rule."""
    rules: Dict[str, P] = {}
    layers = getattr(net.conf, "layers", None)
    if layers is not None:                     # MultiLayerNetwork
        for i, _ in enumerate(layers):
            col = (i % 2 == 0)
            if col:
                rules[rf"^{i}/W$"] = P(None, axis)
                rules[rf"^{i}/b$"] = P(axis)
            else:
                rules[rf"^{i}/W$"] = P(axis, None)
                rules[rf"^{i}/b$"] = P()
        return rules
    parity = 0                                 # ComputationGraph
    for name, v in net.conf.vertices.items():
        k = re.escape(name)
        tname = type(v).__name__
        if tname == "SelfAttentionLayer":
            rules[rf"^{k}/W[qkv]$"] = P(None, axis)
            rules[rf"^{k}/Wo$"] = P(axis, None)
            rules[rf"^{k}/b$"] = P()
            parity = 0        # attention output is row-reduced → next col
        elif tname in ("DenseLayer", "OutputLayer", "RnnOutputLayer"):
            if parity % 2 == 0:
                rules[rf"^{k}/W$"] = P(None, axis)
                rules[rf"^{k}/b$"] = P(axis)
            else:
                rules[rf"^{k}/W$"] = P(axis, None)
                rules[rf"^{k}/b$"] = P()
            parity += 1
        elif tname == "MoEDenseLayer":
            # participates in the column/row pairing like a Dense layer so
            # its down-projection partner still gets the row rule (expert W
            # is [E, in, out]; the router Wg stays replicated). Under an
            # ep+tp mesh, expert_rules' expert-dim sharding takes priority
            # via extra_rules ordering.
            if parity % 2 == 0:
                rules[rf"^{k}/W$"] = P(None, None, axis)
                rules[rf"^{k}/b$"] = P(None, axis)
            else:
                rules[rf"^{k}/W$"] = P(None, axis, None)
                rules[rf"^{k}/b$"] = P()
            parity += 1
    return rules


def param_shardings(params, mesh: Mesh, rules: Dict[str, P]):
    """NamedSharding pytree for ``params`` from path-regex rules (thin
    alias of :func:`~deeplearning4j_tpu.parallel.mesh.rule_shardings`)."""
    return rule_shardings(params, mesh, rules)


def tensor_parallel_step(net, mesh: Mesh, rules: Optional[Dict[str, P]] = None,
                         donate: bool = True, shard_update: bool = False,
                         shard_params: bool = False):
    """Jit the network's train step with TP param shardings (+DP over the
    ``data`` axis when present in the mesh). Returns (step, place) where
    ``place(net)`` device_puts the model state according to the rules.

    ``shard_update``/``shard_params`` layer ZeRO-1/ZeRO-3 sharding over the
    ``data`` axis of the given mesh on top of the TP rules (the mesh must
    carry a ``data`` axis) — optimizer state (and param storage) splits
    over the dims TP left free, exactly like
    ``ParallelWrapper``'s ``weight_update_sharding``/``fsdp`` flags."""
    if rules is None:
        rules = megatron_rules(net)
    if shard_update or shard_params:
        require_axes(mesh, (DATA_AXIS,), style="tensor_parallel_step ZeRO")
    raw = net._raw_step(False)
    p_sh = param_shardings(net.params, mesh, rules)
    # updater state mirrors its param's sharding (Adam moments etc.)
    upd_sh = _mirror_updater_shardings(net, mesh, rules)
    if shard_update:
        upd_sh = zero_update_specs(net.updater_state, mesh, DATA_AXIS,
                                   base=upd_sh)
    if shard_params:
        p_sh = zero_update_specs(net.params, mesh, DATA_AXIS, base=p_sh)
    repl = replicated(mesh)
    data = (batch_sharded(mesh) if DATA_AXIS in mesh.axis_names else repl)
    in_sh = (p_sh, repl, upd_sh, repl, repl, data, data, None, None)
    out_sh = (p_sh, repl, upd_sh, repl)

    record_step("tensor/step", mesh, p_sh, upd_sh,
                zero=shard_update or shard_params)
    step = monitored_jit(raw, name="tensor/step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())

    def place(model):
        model.params = jax.device_put(model.params, p_sh)
        model.states = jax.device_put(model.states, repl)
        model.updater_state = jax.device_put(model.updater_state, upd_sh)

    return step, place


def _mirror_updater_shardings(net, mesh, rules):
    """Back-compat shim over :func:`~deeplearning4j_tpu.parallel.mesh.
    mirror_updater_shardings` (takes the net, the substrate takes the
    trees)."""
    return mirror_updater_shardings(net.params, net.updater_state, mesh,
                                    rules)
