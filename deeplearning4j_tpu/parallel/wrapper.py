"""ParallelWrapper: single-host multi-device data-parallel training.

TPU-native equivalent of reference
``deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java`` (898 LoC;
modes enum :59-74, fit :468, dispatch :497-516, averaging barrier :551-562).

Mapping (SURVEY.md §7 Phase 3):
 - ``TrainingMode.AVERAGING`` with ``averaging_frequency=1`` and
   ``TrainingMode.SHARED_GRADIENTS`` → ONE jitted SPMD step whose gradient
   ``psum`` over ICI is the averaging/broadcast. No host barrier, no replica
   copies: the XLA partitioner emits the collective.
 - ``averaging_frequency=N > 1`` → local SGD: a ``shard_map`` step where every
   device advances its own replica for N micro-steps on its private batch
   stream, then parameters AND updater state are ``pmean``-averaged — exactly
   the reference's periodic averaging barrier (``averageUpdatersState`` :339),
   fused into one XLA computation instead of host thread coordination.

The reference's worker threads, MagicQueue device bucketing and AffinityManager
pinning all disappear: batches go to devices by sharding annotation.
"""
from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map
from ..monitor.jitwatch import monitored_jit

from .mesh import MODEL_AXIS, MeshSpec, record_step, require_axes
from .sharding import (DATA_AXIS, replicated, batch_sharded,
                       shard_batch, put_replicated, data_parallel_step,
                       data_parallel_tbptt_step,
                       data_parallel_tbptt_update_step, pvary,
                       composed_specs, put_sharded_tree)
from .accumulation import GradientsAccumulator, EncodedGradientsAccumulator
from ..nn.conf import BackpropType, CacheMode
from ..datasets.dataset import (DataSet, MultiDataSet, DataSetIterator,
                                ListDataSetIterator)
from ..datasets.iterators import AsyncDataSetIterator
from ..datasets.prefetch import PrefetchDataSetIterator

log = logging.getLogger(__name__)
_tm = jax.tree_util.tree_map


class TrainingMode:
    """Reference ``ParallelWrapper.TrainingMode`` (:59-74)."""
    AVERAGING = "averaging"
    SHARED_GRADIENTS = "shared_gradients"
    CUSTOM = "custom"


class ParallelWrapper:
    """Builder-style facade over the SPMD data-parallel step."""

    class Builder:
        def __init__(self, net):
            self._net = net
            self._workers = None
            self._prefetch = 2
            self._prefetch_workers = 2
            self._freq = 1
            self._mode = TrainingMode.AVERAGING
            self._report_after_avg = True
            self._accumulator = None
            self._mesh = None
            self._ws = False
            self._fsdp = False
            self._host_dtype = None
            self._tp = None
            self._tp_rules = None

        def workers(self, n):
            self._workers = int(n)
            return self

        def prefetch_buffer(self, n):
            self._prefetch = int(n)
            return self

        prefetchBuffer = prefetch_buffer

        def prefetch_workers(self, n):
            """Host ETL worker threads feeding the batch grouper
            (``datasets/prefetch.py`` multi-worker pipeline; default 2).
            The device placement itself stays with ``_global_batch`` —
            it shards over the wrapper's mesh — so the workers
            parallelize the iterator/decode/augment side only."""
            self._prefetch_workers = int(n)
            return self

        prefetchWorkers = prefetch_workers

        def averaging_frequency(self, n):
            self._freq = int(n)
            return self

        averagingFrequency = averaging_frequency

        def training_mode(self, mode):
            self._mode = mode
            return self

        trainingMode = training_mode

        def report_score_after_averaging(self, flag=True):
            self._report_after_avg = bool(flag)
            return self

        reportScoreAfterAveraging = report_score_after_averaging

        def gradients_accumulator(self, acc: GradientsAccumulator):
            self._accumulator = acc
            return self

        gradientsAccumulator = gradients_accumulator

        def mesh(self, mesh: Mesh):
            self._mesh = mesh
            return self

        def tensor_parallel(self, n: int = 2, rules=None):
            """Compose tensor parallelism INTO the data-parallel step on a
            2-D ``data × model`` mesh (parallel/mesh.py substrate): the
            wrapper keeps driving the batch over the ``data`` axis while
            ``rules`` ({param-path regex: PartitionSpec}, default
            :func:`~deeplearning4j_tpu.parallel.tensor.megatron_rules`)
            shard the params over a ``model`` axis of extent ``n`` in the
            SAME jitted step. The data extent auto-factorizes to
            ``devices / n``. Stacks with :meth:`weight_update_sharding` /
            :meth:`fsdp` — ZeRO takes the dims TP left free, over the
            ``data`` axis of the composed mesh. Supported for
            ``TrainingMode.AVERAGING`` with ``averaging_frequency=1``
            (including TBPTT); other modes reject loudly."""
            self._tp = int(n)
            self._tp_rules = rules
            return self

        tensorParallel = tensor_parallel

        def weight_update_sharding(self, flag=True):
            """Shard the OPTIMIZER STATE over the data axis instead of
            replicating it (Xu et al. 2020, arXiv:2004.13336; ZeRO-1 as
            sharding annotations) — numerically identical sync DP with ~N×
            less optimizer memory per device. Supported for
            ``TrainingMode.AVERAGING`` with ``averaging_frequency=1``
            (including its TBPTT variant); other modes reject loudly."""
            self._ws = bool(flag)
            return self

        weightUpdateSharding = weight_update_sharding

        def fsdp(self, flag=True):
            """ZeRO-3/FSDP-style sharded STORAGE: parameters AND optimizer
            state shard over the data axis (leaves with a divisible dim;
            the rest replicate). The SPMD partitioner inserts the
            all-gathers at the points of use and reduce-scatters gradients
            into the sharded update — numerically identical to replicated
            DP with ~N× less param+optimizer memory per device. Implies
            :meth:`weight_update_sharding`; same AVERAGING freq=1
            constraint. Non-step uses of the net (``output()``/``score()``/
            serialization) gather transparently."""
            self._fsdp = bool(flag)
            # the ws implication lives in __init__ ("ws or fsdp"), so
            # toggling fsdp back off leaves an explicit ws setting intact
            return self

        def host_transfer_dtype(self, dtype):
            """Cast float FEATURE arrays to ``dtype`` ON THE HOST before the
            device transfer. With ``compute_dtype='bfloat16'`` the layers
            cast inputs to bf16 on device anyway, so casting before the
            wire halves host→device bytes with BIT-IDENTICAL results — the
            lever for host-link-bound pipelines (the 137 MB/step
            299² InceptionV3 batch). EXPLICIT OPT-IN: unsafe for
            float-encoded integer id streams (embedding inputs — bf16
            rounds integers above 256); use only when features are real
            continuous data (images, audio, sensors). Labels and masks are
            not touched."""
            self._host_dtype = dtype
            return self

        hostTransferDtype = host_transfer_dtype

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._net, workers=self._workers,
                                   prefetch_buffer=self._prefetch,
                                   prefetch_workers=self._prefetch_workers,
                                   averaging_frequency=self._freq,
                                   training_mode=self._mode,
                                   report_score_after_averaging=self._report_after_avg,
                                   accumulator=self._accumulator,
                                   mesh=self._mesh,
                                   weight_update_sharding=self._ws,
                                   fsdp=self._fsdp,
                                   host_transfer_dtype=self._host_dtype,
                                   tensor_parallel=self._tp,
                                   tp_rules=self._tp_rules)

    def __init__(self, net, workers: Optional[int] = None,
                 prefetch_buffer: int = 2, prefetch_workers: int = 2,
                 averaging_frequency: int = 1,
                 training_mode: str = TrainingMode.AVERAGING,
                 report_score_after_averaging: bool = True,
                 accumulator: Optional[GradientsAccumulator] = None,
                 mesh: Optional[Mesh] = None,
                 weight_update_sharding: bool = False,
                 fsdp: bool = False,
                 host_transfer_dtype=None,
                 tensor_parallel: Optional[int] = None,
                 tp_rules=None):
        self.net = net
        self.host_transfer_dtype = host_transfer_dtype
        self.fsdp = bool(fsdp)
        self.weight_update_sharding = bool(weight_update_sharding) or self.fsdp
        if tp_rules is not None and tensor_parallel is None and mesh is None:
            raise ValueError("tp_rules needs a model axis: pass "
                             "tensor_parallel=<extent> or a mesh carrying "
                             "a 'model' axis")
        if tensor_parallel is not None and int(tensor_parallel) < 2:
            raise ValueError(f"tensor_parallel extent must be >= 2 "
                             f"(got {tensor_parallel}); without a model "
                             f"split just omit it")
        self.tensor_parallel = (None if tensor_parallel is None
                                else int(tensor_parallel))
        if self.tensor_parallel and tp_rules is None:
            from .tensor import megatron_rules
            tp_rules = megatron_rules(net)
        self.tp_rules = tp_rules
        if (int(getattr(net.gc, "iterations", 1) or 1) > 1
                and not getattr(net, "_warned_pw_iterations", False)):
            net._warned_pw_iterations = True
            log.warning("iterations(%s) is ignored under ParallelWrapper "
                        "(it re-jits the single-iteration step with mesh "
                        "shardings); each dispatched batch runs one "
                        "optimizer iteration",
                        net.gc.iterations)
        devices = jax.devices()
        if workers is not None and workers < len(devices):
            devices = devices[:workers]
        if mesh is not None:
            self.mesh = mesh
        elif self.tensor_parallel:
            # 2-D data × model: the model extent is fixed, the data extent
            # auto-factorizes over the remaining devices (MeshSpec rejects
            # non-dividing extents with an actionable message)
            self.mesh = MeshSpec(axes=(DATA_AXIS, MODEL_AXIS),
                                 shape=(None, self.tensor_parallel),
                                 devices=devices).build()
        else:
            self.mesh = MeshSpec(axes=(DATA_AXIS,), devices=devices).build()
        require_axes(self.mesh, (DATA_AXIS,), style="ParallelWrapper")
        if self.tp_rules is not None:
            require_axes(self.mesh, (MODEL_AXIS,),
                         style="ParallelWrapper.tensor_parallel")
        if (mesh is not None and self.tensor_parallel
                and int(mesh.shape[MODEL_AXIS]) != self.tensor_parallel):
            # an explicit mesh whose model extent disagrees with the
            # requested one must not silently win
            raise ValueError(
                f"tensor_parallel={self.tensor_parallel} but the given "
                f"mesh has model extent {int(mesh.shape[MODEL_AXIS])}; "
                f"drop one of the two or make them agree")
        # the wrapper drives the DATA axis: batch divisibility, round-robin
        # group size and iteration accounting all follow the data extent —
        # model-family axes shard params, not the batch
        n_devices = int(np.prod(self.mesh.devices.shape))
        self.workers_ = int(self.mesh.shape[DATA_AXIS])
        # multi-process (multi-host) awareness: each process feeds only its
        # addressable devices' share of the global batch
        self.process_count = jax.process_count()
        if self.process_count > 1:
            pidx = jax.process_index()
            local_devs = sum(1 for d in self.mesh.devices.flat
                             if d.process_index == pidx)
            # devices per data slice = model-family extents product; a
            # data slice spanning processes would make every process feed
            # a share of the SAME slice (double-fed global batch) — the
            # model-family axes must stay within a process (see
            # parallel/mesh.py axis conventions), so reject loudly
            per_slice = n_devices // self.workers_
            if per_slice > 1 and local_devs % per_slice:
                raise ValueError(
                    f"this process holds {local_devs} of the mesh's "
                    f"devices but each data slice spans {per_slice} "
                    f"(model-family extents); model/pipe/sequence axes "
                    f"must stay within a process — reshape the mesh so "
                    f"the data axis is the one crossing hosts")
            self.local_workers_ = max(1, local_devs // per_slice)
        else:
            self.local_workers_ = self.workers_
        self._mp_batch_size = None  # enforced-uniform size (multi-process)
        if self.weight_update_sharding or self.tp_rules is not None:
            # supported: AVERAGING freq=1 (fused psum step, incl. its TBPTT
            # variant). Loud rejection elsewhere — a silent no-op would let
            # a memory-tight job believe it has the N-fold saving (or the
            # model split)
            if (training_mode != TrainingMode.AVERAGING
                    or max(1, int(averaging_frequency)) != 1):
                what = ("weight_update_sharding"
                        if self.weight_update_sharding else "tensor_parallel")
                raise NotImplementedError(
                    f"{what} applies to "
                    "TrainingMode.AVERAGING with averaging_frequency=1 "
                    "(the fused-psum sync step); the local-SGD shard_map "
                    "and SHARED_GRADIENTS codec paths keep replicated "
                    "model state")
        # CacheMode.DEVICE for the sharded dispatch path: merged+sharded
        # global batches keyed by the group's array identities (see
        # DataSet._device_key). Values retain the KEYED HOST ARRAYS (the
        # same rule as _cached_device_put) so an id/data-pointer can't be
        # recycled into a stale-key collision, and the dict is LRU-evicted
        # under a byte budget so non-repeating data (augmentation,
        # streaming) can't pin unbounded HBM.
        self._sharded_batch_cache = {}   # key -> (out, retained, nbytes)
        self._sharded_cache_bytes = 0
        self.sharded_cache_budget = int(
            os.environ.get("DL4J_TPU_PW_CACHE_BYTES", 4 << 30))
        self.prefetch_buffer = prefetch_buffer
        self.prefetch_workers = max(0, int(prefetch_workers))
        self.averaging_frequency = max(1, int(averaging_frequency))
        self.training_mode = training_mode
        self.report_score_after_averaging = report_score_after_averaging
        self.accumulator = accumulator
        self.iteration_count = 0
        self.last_score = float("nan")
        self._sync_step = None
        self._local_sgd_step = None
        self.averaging_ms = 0.0
        # ComputationGraph steps take tuples of input/label streams (its
        # _raw_step zips network_inputs with the inputs arg); bare arrays
        # would be iterated along the batch axis — row 0 only
        self._is_graph = hasattr(net, "_as_multi")

    # ------------------------------------------------------------------
    def _ensure_sync_step(self):
        if self._sync_step is None:
            self._sync_step = data_parallel_step(
                self.net, self.mesh,
                shard_update=self.weight_update_sharding,
                shard_params=self.fsdp, tp_rules=self.tp_rules)
        return self._sync_step

    def _ensure_sync_tbptt_step(self):
        if getattr(self, "_sync_tbptt_step", None) is None:
            self._sync_tbptt_step = data_parallel_tbptt_step(
                self.net, self.mesh,
                shard_update=self.weight_update_sharding,
                shard_params=self.fsdp, tp_rules=self.tp_rules)
        return self._sync_tbptt_step

    # ------------------------------------------------------------ TBPTT
    def _tbptt_applicable(self, f):
        """True when this (possibly tuple-of-streams) feature batch should be
        trained as TBPTT segments — same predicate the containers use in
        ``_fit_batch``, so sharded, tail and single-device batches all get
        identical truncation semantics (reference: every ParallelWrapper
        worker runs the full fit loop, ``DefaultTrainer.java:244``)."""
        conf = self.net.conf
        if conf.backprop_type != BackpropType.TruncatedBPTT:
            return False
        xs = f if isinstance(f, tuple) else (f,)
        return (all(x.ndim == 3 for x in xs)
                and xs[0].shape[1] > conf.tbptt_fwd_length)

    @staticmethod
    def _tbptt_slices(f, l, fm, lm, sl):
        f_c = _tm(lambda x: x[:, sl], f)
        l_c = _tm(lambda x: x[:, sl] if x.ndim == 3 else x, l)
        fm_c = None if fm is None else _tm(lambda m: m[:, sl], fm)
        lm_c = None if lm is None else _tm(lambda m: m[:, sl], lm)
        return f_c, l_c, fm_c, lm_c

    def _stacked_n_segments(self, fs):
        """Segments per micro-batch for [N, b, T, ...] stacked TBPTT data —
        the stacked-shape sibling of ``_tbptt_applicable``."""
        conf = self.net.conf
        xs = jax.tree_util.tree_leaves(fs)
        if (conf.backprop_type == BackpropType.TruncatedBPTT
                and all(x.ndim == 4 for x in xs)
                and xs[0].shape[2] > conf.tbptt_fwd_length):
            return -(-xs[0].shape[2] // conf.tbptt_fwd_length)
        return 1

    def _fit_tbptt_segments(self, f, l, fm, lm, seg_step):
        """Shared TBPTT segment loop for the sharded paths (mirrors the
        containers' ``_fit_tbptt``: one optimizer update per segment, carry
        detached between segments, one listener event per batch).
        ``seg_step(itc, key, f_c, l_c, fm_c, lm_c, rnn) -> (loss, rnn)``
        applies one segment's update however the training mode does."""
        net = self.net
        leaves = jax.tree_util.tree_leaves(f)
        T, batch = int(leaves[0].shape[1]), int(leaves[0].shape[0])
        L = net.conf.tbptt_fwd_length
        rnn_state = net._init_rnn_state(batch)
        loss = jnp.asarray(float("nan"))
        for start in range(0, T, L):
            sl = slice(start, min(start + L, T))
            f_c, l_c, fm_c, lm_c = self._tbptt_slices(f, l, fm, lm, sl)
            itc = jnp.asarray(net.iteration_count, jnp.int32)
            key = put_replicated(net._next_rng(), self.mesh)
            loss, rnn_state = seg_step(itc, key, f_c, l_c, fm_c, lm_c,
                                       rnn_state)
            net.iteration_count += 1
        self.last_score = float(loss)
        net.score_ = loss
        self.iteration_count += 1
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration_count - 1, float(loss))

    def _fit_sync_tbptt(self, f, l, fm, lm):
        """TBPTT over the fused-psum sharded step."""
        net = self.net
        step = self._ensure_sync_tbptt_step()

        def seg(itc, key, f_c, l_c, fm_c, lm_c, rnn):
            (net.params, net.states, net.updater_state, loss, rnn) = step(
                net.params, net.states, net.updater_state, itc, key, f_c,
                l_c, fm_c, lm_c, rnn)
            return loss, rnn

        self._fit_tbptt_segments(f, l, fm, lm, seg)

    def _ensure_local_sgd_step(self):
        """shard_map local-SGD: [N, b, ...] micro-batch stack per device, N
        local updates, then pmean of params/updater-state/layer-state."""
        if self._local_sgd_step is not None:
            return self._local_sgd_step
        net = self.net
        mesh = self.mesh
        raw = net._raw_step(False)
        raw_t = net._raw_step(True)
        conf = net.conf
        N = self.averaging_frequency

        def one_micro(params, states, upd, it, k, f, l, fm, lm):
            """One micro-batch on one device: TBPTT-segments when the traced
            shapes call for it (``_tbptt_applicable`` is trace-time static),
            else one full-BPTT update."""
            if not self._tbptt_applicable(f):
                return raw(params, states, upd, it, k, f, l, fm, lm)
            xs = jax.tree_util.tree_leaves(f)
            T, L = xs[0].shape[1], conf.tbptt_fwd_length
            rnn = net._init_rnn_state(xs[0].shape[0])
            rnn = _tm(lambda x: pvary(x, (DATA_AXIS,)), rnn)
            loss = pvary(jnp.asarray(0.0, jnp.float32), (DATA_AXIS,))
            for s_i, start in enumerate(range(0, T, L)):
                sl = slice(start, min(start + L, T))
                f_c, l_c, fm_c, lm_c = ParallelWrapper._tbptt_slices(
                    f, l, fm, lm, sl)
                params, states, upd, loss, rnn = raw_t(
                    params, states, upd, it + s_i,
                    jax.random.fold_in(k, s_i), f_c, l_c, fm_c, lm_c, rnn)
            return params, states, upd, loss

        def local_run(params, states, upd, it0, rng, fs, ls, fms, lms):
            # runs per-device under shard_map: fs/ls/fms/lms [N, b_local, ...]
            dev = jax.lax.axis_index(DATA_AXIS)
            rng = jax.random.fold_in(rng, dev)
            n_seg = self._stacked_n_segments(fs)

            def body(i, carry):
                params, states, upd, _ = carry
                # tree_map: arrays (MLN) or stream tuples (CG); None masks
                # are empty pytrees and pass through
                idx = lambda a: jax.lax.dynamic_index_in_dim(a, i,
                                                             keepdims=False)
                f, l, fm, lm = (_tm(idx, t) for t in (fs, ls, fms, lms))
                k = jax.random.fold_in(rng, i)
                params, states, upd, loss = one_micro(
                    params, states, upd, it0 + i * n_seg, k, f, l, fm, lm)
                return params, states, upd, loss

            # mark the carry as device-varying: replicas diverge locally
            # between averaging barriers. Under check_vma=False (below)
            # this is a no-op kept for documentation value and in case the
            # vma check is ever re-enabled — the pmean barrier after the
            # loop is what actually restores replica agreement; vma typing
            # does NOT verify it here
            init = jax.tree_util.tree_map(
                lambda x: pvary(x, (DATA_AXIS,)),
                (params, states, upd, jnp.asarray(0.0, jnp.float32)))
            params, states, upd, loss = jax.lax.fori_loop(0, N, body, init)
            # periodic averaging barrier (params + updater state + layer state)
            params = jax.lax.pmean(params, DATA_AXIS)
            states = jax.lax.pmean(states, DATA_AXIS)
            upd = jax.lax.pmean(upd, DATA_AXIS)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            return params, states, upd, loss

        repl = P()
        data = P(None, DATA_AXIS)  # [N, global_b, ...] split on batch dim
        # check_vma=False: the step may route through Pallas kernels
        # (persistent/fused LSTM), whose out_shape ShapeDtypeStructs carry
        # no vma typing — same setting as every other shard_map in
        # parallel/ (sequence.py, pipeline.py)
        fn = shard_map(local_run, mesh=mesh,
                       in_specs=(repl, repl, repl, repl, repl, data, data,
                                 data, data),
                       out_specs=(repl, repl, repl, repl),
                       check_vma=False)
        record_step("wrapper/local_sgd", mesh)
        self._local_sgd_step = monitored_jit(
            fn, name="wrapper/local_sgd_step", donate_argnums=(0, 2))
        return self._local_sgd_step

    # ------------------------------------------------------------------ fit
    def fit(self, data, epochs: int = 1):
        """Train over the iterator with all devices (reference ``fit`` :468)."""
        import time
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        it = data
        owned = False
        if (isinstance(it, DataSetIterator)
                and not isinstance(it, (AsyncDataSetIterator,
                                        PrefetchDataSetIterator))
                and it.async_supported()
                and self.prefetch_workers > 0):
            # multi-worker host ETL ahead of the batch grouper. NO
            # device_put here: placement is _global_batch's job — it
            # merges one batch per device then shards over the mesh
            it = PrefetchDataSetIterator(it, workers=self.prefetch_workers,
                                         queue_size=self.prefetch_buffer,
                                         device_put=False)
            owned = True
        net = self.net
        try:
            for _ in range(epochs):
                if self.training_mode == TrainingMode.SHARED_GRADIENTS:
                    self._fit_shared(it)
                elif self.averaging_frequency == 1:
                    self._fit_sync(it)
                else:
                    self._fit_local_sgd(it)
                net.epoch_count += 1
        finally:
            if owned:
                it.shutdown()
        return self

    def _device_put_model(self):
        """Place params/updater-state with EXACTLY the specs the jitted
        step was built with (``composed_specs`` is the single source of
        truth for both) — TP rules claim the model axis, ZeRO flags layer
        the data axis; everything else replicates."""
        net = self.net
        put = lambda t: _tm(lambda x: put_replicated(x, self.mesh), t)
        par, upd = composed_specs(net, self.mesh, tp_rules=self.tp_rules,
                                  shard_update=self.weight_update_sharding,
                                  shard_params=self.fsdp)
        net.params = put_sharded_tree(net.params, par)
        net.states = put(net.states)
        net.updater_state = put_sharded_tree(net.updater_state, upd)

    def _resolve_score(self, pending):
        """Resolve a deferred ``(loss, iteration_idx)`` score fetch. The
        value fetch is THE device-sync point (axon ``block_until_ready`` is
        unreliable — see StepTimerListener), so it is deferred by exactly
        one step: when it blocks here, the NEXT step's host→device transfer
        and dispatch are already enqueued, overlapping H2D with compute —
        the device-side half of the AsyncDataSetIterator promise
        (reference ``ParallelWrapper.java:468-516`` keeps workers busy via
        queues; XLA's async dispatch plays that role, and an eager per-step
        ``float(loss)`` would serialize it away).

        Deferral only happens with NO listeners attached (the bench/
        throughput shape): a deferred callback would hand listeners a model
        whose params/iteration_count had already advanced one step
        (CheckpointListener would save the wrong params under the label,
        ParamAndGradient would attribute the wrong delta), so with
        listeners the fetch stays eager and exact."""
        if pending is None:
            return
        loss, idx = pending
        v = float(loss)
        self.last_score = v
        net = self.net
        for lst in net.listeners:
            lst.iteration_done(net, idx, v)

    def _fit_sync(self, it):
        """AVERAGING freq=1 / SHARED_GRADIENTS: fused psum step per global
        batch (the reference's per-iteration averaging ≡ gradient all-reduce).

        Batch semantics match the reference's round-robin dispatch
        (``ParallelWrapper.java:497-516``): each device consumes ONE iterator
        batch per parallel iteration, so ``workers_`` iterator batches are
        merged into the global batch of a step. A tail group smaller than
        ``workers_`` is still trained (sharded across all devices) so no data
        is dropped.

        The per-step score fetch is double-buffered (``_resolve_score``)
        when no listeners are attached: step k's H2D + dispatch are
        enqueued before step k-1's loss is fetched, so the host link
        streams the next global batch while the chip computes the current
        one. With listeners the fetch is eager (exact model state per
        callback — see ``_resolve_score``)."""
        net = self.net
        step = self._ensure_sync_step()
        self._device_put_model()
        pending = None
        try:
            for group in self._batch_groups(it):
                if group is None:
                    continue  # tail handled unsharded by _batch_groups
                f, l, fm, lm = self._global_batch(group)
                if self._tbptt_applicable(f):
                    prev, pending = pending, None
                    self._resolve_score(prev)
                    self._fit_sync_tbptt(f, l, fm, lm)
                    continue
                itc = jnp.asarray(net.iteration_count, jnp.int32)
                key = put_replicated(net._next_rng(), self.mesh)
                net.params, net.states, net.updater_state, loss = step(
                    net.params, net.states, net.updater_state, itc, key, f, l,
                    fm, lm)
                net.score_ = loss
                net.iteration_count += 1
                self.iteration_count += 1
                cur = (loss, net.iteration_count - 1)
                if net.listeners:
                    self._resolve_score(cur)       # eager: exact state
                else:
                    # clear BEFORE resolving: a raise mid-resolve must not
                    # let the finally replay the same iteration
                    prev, pending = pending, cur
                    self._resolve_score(prev)
        finally:
            prev, pending = pending, None
            self._resolve_score(prev)

    def _batch_groups(self, it):
        """Yield groups of iterator batches (reference round-robin dispatch):
        one batch per LOCAL device per parallel iteration — under multi-process
        each process feeds only its addressable share of the global batch.

        Single-process, a group whose example total is not divisible by the
        device count is trained unsharded right here (net's own replicated
        step) and yielded as None so no data is dropped or crashed on.
        Multi-process, an unsharded step would desync the collective schedule
        across processes, so the odd tail is dropped with a warning instead."""
        net = self.net
        group_size = self.local_workers_
        pending = []
        it = iter(it)
        exhausted = False
        while not exhausted:
            try:
                pending.append(next(it))
            except StopIteration:
                exhausted = True
            if not pending or (len(pending) < group_size and not exhausted):
                continue
            group, pending = pending, []
            total = sum(b.num_examples() for b in group)
            if self.process_count > 1:
                # the divisibility decision must be identical on every process
                # or collective schedules desync (hang); uniform batch sizes
                # guarantee that, so enforce them loudly instead
                sizes = {b.num_examples() for b in group}
                if self._mp_batch_size is None:
                    self._mp_batch_size = next(iter(sizes))
                sizes.add(self._mp_batch_size)
                if len(sizes) != 1:
                    raise ValueError(
                        f"multi-process training requires uniform iterator "
                        f"batch sizes; saw {sorted(sizes)}")
            if total % group_size:
                if self.process_count > 1:
                    log.warning("Dropping %d-example tail group (not divisible "
                                "by %d local devices; unsharded fallback would "
                                "desync processes)", total, group_size)
                    yield None
                    continue
                if len(group) == 1:
                    merged = group[0]
                elif self._is_graph:
                    merged = MultiDataSet.merge([net._as_multi(b)
                                                 for b in group])
                else:
                    merged = DataSet.merge(group)
                log.info("Batch group of %d examples not divisible by %d "
                         "devices; training it unsharded", total,
                         self.workers_)
                self._fit_unsharded(net, merged)
                self.iteration_count += 1
                self.last_score = float(net.score_)
                yield None
                continue
            yield group

    def _fit_unsharded(self, net, merged):
        """Train one unsharded fallback batch with exactly ONE optimizer
        iteration per step dispatch — consistent with every sharded dispatch
        (the net's own cached step may be an ``iterations(n)`` scan, which
        would give tail batches n× the updates and desync the iteration
        accounting). Routed through the container's own ``_fit_batch`` so
        feature/label masks and TBPTT segmentation are preserved exactly as
        on the sharded path (round-3 advisor finding)."""
        net._fit_batch(merged, single_iteration=True)

    def _ensure_shared_steps(self):
        """Two jitted halves around the host codec seam: compute the
        updater-transformed update (gradient psum on ICI), then apply a
        decoded update. The host hop between them is the DCN boundary the
        encoding exists for."""
        if getattr(self, "_shared_steps", None) is not None:
            return self._shared_steps
        net = self.net
        repl = replicated(self.mesh)
        data = batch_sharded(self.mesh)
        update_step = monitored_jit(
            net._raw_update_step(), name="wrapper/shared_update_step",
            in_shardings=(repl, repl, repl, repl, repl, data, data, data, data),
            out_shardings=(repl, repl, repl, repl),
            donate_argnums=(2,))

        def apply_fn(params, update):
            new = _tm(lambda p, u: p - u.astype(p.dtype), params, update)
            return net._apply_constraints(new)

        apply_step = monitored_jit(apply_fn, name="wrapper/shared_apply_step",
                                   out_shardings=repl, donate_argnums=(0,))
        record_step("wrapper/shared", self.mesh)
        self._shared_steps = (update_step, apply_step)
        return self._shared_steps

    def _fit_shared(self, it):
        """SHARED_GRADIENTS (reference ``SymmetricTrainer`` +
        ``EncodedGradientsAccumulator.java:257``): every round the all-reduced
        update is threshold-encoded — sub-threshold mass stays in the host
        residual, the quantized decode is what peers (other slices over DCN)
        would receive — and ALL replicas apply the decoded update, keeping
        them bit-identical while the wire carries ``encoded_bytes()`` instead
        of dense tensors. Trajectories genuinely differ from AVERAGING."""
        net = self.net
        if self.accumulator is None:
            self.accumulator = EncodedGradientsAccumulator()
        update_step, apply_step = self._ensure_shared_steps()
        self._device_put_model()
        for group in self._batch_groups(it):
            if group is None:
                continue
            f, l, fm, lm = self._global_batch(group)
            if self._tbptt_applicable(f):
                self._fit_shared_tbptt(f, l, fm, lm, apply_step)
                continue
            itc = jnp.asarray(net.iteration_count, jnp.int32)
            key = put_replicated(net._next_rng(), self.mesh)
            update, net.states, net.updater_state, loss = update_step(
                net.params, net.states, net.updater_state, itc, key, f, l,
                fm, lm)
            self._apply_encoded(apply_step, update)
            self.last_score = float(loss)
            net.score_ = loss
            net.iteration_count += 1
            self.iteration_count += 1
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration_count - 1, float(loss))

    def _apply_encoded(self, apply_step, update):
        """Host hop: encode (residual kept) → apply the decoded quantized
        update — what peers over DCN would receive."""
        net = self.net
        decoded = self.accumulator.store_update(_tm(np.asarray, update))
        net.params = apply_step(net.params, _tm(jnp.asarray, decoded))

    def _fit_shared_tbptt(self, f, l, fm, lm, apply_step):
        """SHARED_GRADIENTS × TBPTT: every segment's updater-transformed
        update passes through the threshold codec (one wire message per
        applied update — reference ``SymmetricTrainer`` encodes per
        iteration, and TBPTT iterations are per segment)."""
        net = self.net
        if getattr(self, "_shared_tbptt_step", None) is None:
            self._shared_tbptt_step = data_parallel_tbptt_update_step(
                net, self.mesh)
        step = self._shared_tbptt_step

        def seg(itc, key, f_c, l_c, fm_c, lm_c, rnn):
            (update, net.states, net.updater_state, loss, rnn) = step(
                net.params, net.states, net.updater_state, itc, key, f_c,
                l_c, fm_c, lm_c, rnn)
            self._apply_encoded(apply_step, update)
            return loss, rnn

        self._fit_tbptt_segments(f, l, fm, lm, seg)

    def _fit_local_sgd(self, it):
        """AVERAGING freq=N: collect N micro-batches, one fused local-SGD +
        averaging computation."""
        import time
        net = self.net
        step = self._ensure_local_sgd_step()
        self._device_put_model()
        pending: List[DataSet] = []
        for ds in it:
            pending.append(ds)
            if len(pending) < self.averaging_frequency:
                continue
            fs, ls, fms, lms = self._stacked_batches(pending)
            pending = []
            # TBPTT segments count as extra optimizer iterations per micro-
            # batch (mirror of the trace-time predicate in one_micro)
            n_seg = self._stacked_n_segments(fs)
            itc = jnp.asarray(net.iteration_count, jnp.int32)
            key = put_replicated(net._next_rng(), self.mesh)
            t0 = time.perf_counter()
            net.params, net.states, net.updater_state, loss = step(
                net.params, net.states, net.updater_state, itc, key, fs, ls,
                fms, lms)
            # value fetch = completion barrier (block_until_ready can return
            # early on tunneled backends — see StepTimerListener docstring)
            self.last_score = float(loss)
            self.averaging_ms = (time.perf_counter() - t0) * 1e3
            net.iteration_count += self.averaging_frequency * n_seg
            self.iteration_count += self.averaging_frequency
            net.score_ = loss
            if self.report_score_after_averaging:
                for lst in net.listeners:
                    lst.iteration_done(net, net.iteration_count - 1, float(loss))
        if pending:
            log.info("Dropping %d tail micro-batches (< averaging_frequency)",
                     len(pending))

    # ---------------------------------------------------------------- helpers
    def _global_batch(self, batches):
        """Merge iterator batches into one sharded global batch.

        Source dtypes are preserved (integer embedding indices, f64 nets);
        the layers' own ``cast_in`` decides the compute dtype. For a
        ComputationGraph the step takes tuples of input/label streams.

        Under ``CacheMode.DEVICE`` the merged+sharded result is cached on
        the group's array identities, so repeated epochs over the same
        iterator batches skip the host→device transfer entirely — the
        reference's ``CacheMode.DEVICE`` semantics (`nn/conf/CacheMode.java`)
        applied to the ParallelWrapper dispatch path."""
        return self._cached_sharded((), batches, self._global_batch_uncached)

    def _cached_sharded(self, prefix, batches, build):
        """LRU device-batch cache shared by the sync and local-SGD paths.
        Keyed on the batches' ``_device_key`` tuples; each entry retains the
        keyed host arrays (so ids/data pointers stay pinned for the entry's
        lifetime — the `_cached_device_put` rule) and records the device
        bytes it pins; total pinned bytes are bounded by
        ``sharded_cache_budget`` (env ``DL4J_TPU_PW_CACHE_BYTES``, default
        4 GiB) with least-recently-used eviction.

        CONTRACT — cached arrays must not be mutated in place: the key is
        (id, data pointer, shape, dtype), so a pipeline that WRITES into a
        reused batch buffer (e.g. augmentation into the same ndarray) keeps
        the same key and the step silently trains on the STALE device copy.
        Feed ``CacheMode.DEVICE`` fresh arrays per distinct batch, or call
        ``clear_device_cache()`` after mutating."""
        if getattr(self.net.gc, "cache_mode", None) != CacheMode.DEVICE:
            return build(batches)
        ckey = prefix + tuple(b._device_key() for b in batches)
        cache = self._sharded_batch_cache
        hit = cache.pop(ckey, None)
        if hit is not None:
            cache[ckey] = hit                     # re-insert: LRU freshness
            return hit[0]
        out = build(batches)

        def _retained(b):
            if isinstance(b, MultiDataSet):
                seqs = (b.features, b.labels, b.features_masks, b.labels_masks)
                return tuple(tuple(s) for s in seqs if s is not None)
            return (b.features, b.labels, b.features_mask, b.labels_mask)

        nbytes = sum(getattr(a, "nbytes", 0)
                     for a in jax.tree_util.tree_leaves(out))
        cache[ckey] = (out, tuple(_retained(b) for b in batches), nbytes)
        self._sharded_cache_bytes += nbytes
        # plain-dict insertion order + re-insert-on-hit above ⇒ first key
        # is the least recently used
        while (self._sharded_cache_bytes > self.sharded_cache_budget
               and len(cache) > 1):
            oldest = next(iter(cache))
            _, _, old_bytes = cache.pop(oldest)
            self._sharded_cache_bytes -= old_bytes
        return out

    def gather_model(self):
        """Re-replicate a sharded-storage model (``fsdp``/
        ``weight_update_sharding``) so its params/updater state are plain
        host-accessible arrays again — REQUIRED before ``np.asarray``/
        serialization/scoring on a MULTI-PROCESS mesh, where a sharded
        leaf spans non-addressable devices (single-process shards gather
        transparently). Uses ``process_allgather`` across hosts."""
        net = self.net
        if self.process_count > 1:
            from jax.experimental import multihost_utils

            def regather(t):
                return _tm(
                    lambda x: multihost_utils.process_allgather(
                        x, tiled=True)
                    if hasattr(x, "sharding") and x.sharding.spec else x, t)

            net.params = regather(net.params)
            net.updater_state = regather(net.updater_state)
        else:
            # leave HOST arrays (like the multi-process branch): the whole
            # point of fsdp is that a full copy may not fit one device
            host = lambda t: _tm(np.asarray, t)
            net.params = host(net.params)
            net.updater_state = host(net.updater_state)
        return net

    gatherModel = gather_model

    def clear_device_cache(self):
        """Drop every cached sharded batch (and the host arrays it retains).
        Use when training under ``CacheMode.DEVICE`` with data that does NOT
        repeat across epochs (augmentation, streaming): non-repeating batches
        insert entries that can never hit, and although the LRU byte budget
        bounds the HBM pinned, that budget is better spent on activations.
        ALSO required for correctness if batch arrays were mutated IN PLACE:
        the cache keys on array identity, so an in-place write leaves a
        stale device copy behind the same key (see ``_cached_sharded``)."""
        self._sharded_batch_cache.clear()
        self._sharded_cache_bytes = 0

    def _host_cast(self, x):
        """``host_transfer_dtype``: cast float feature arrays on the HOST so
        the device transfer carries half the bytes (bit-identical when the
        layers would cast to the same compute dtype anyway — see the
        Builder option's docstring for the embedding-id hazard)."""
        if self.host_transfer_dtype is None:
            return x
        a = np.asarray(x)
        if a.dtype not in (np.float32, np.float64):
            return x                       # ints/bools: never touched
        # ml_dtypes (a jax dependency) registers 'bfloat16' with numpy
        dt = np.dtype("bfloat16" if str(self.host_transfer_dtype) == "bf16"
                      else self.host_transfer_dtype)
        compute = str(getattr(self.net.gc, "compute_dtype", "float32"))
        if compute != str(dt) and not getattr(self, "_warned_host_cast",
                                              False):
            self._warned_host_cast = True
            log.warning(
                "host_transfer_dtype=%s with compute_dtype=%s: inputs are "
                "rounded BEFORE the (wider) compute — results will differ "
                "from the uncast run. Bit-identical only when the two "
                "dtypes match.", dt, compute)
        return a.astype(dt)

    def _global_batch_uncached(self, batches):
        if self._is_graph:
            mds_list = [self.net._as_multi(b) for b in batches]
            mds = mds_list[0] if len(mds_list) == 1 else MultiDataSet.merge(mds_list)
            b = mds.num_examples()
            if b % self.local_workers_:
                raise ValueError(
                    f"Local batch {b} not divisible by "
                    f"{self.local_workers_} local devices")
            f = tuple(shard_batch(jnp.asarray(self._host_cast(x)), self.mesh)
                      for x in mds.features)
            l = tuple(shard_batch(jnp.asarray(x), self.mesh)
                      for x in mds.labels)
            fm = (None if mds.features_masks is None else tuple(
                None if m is None else shard_batch(jnp.asarray(m), self.mesh)
                for m in mds.features_masks))
            lm = (None if mds.labels_masks is None else tuple(
                None if m is None else shard_batch(jnp.asarray(m), self.mesh)
                for m in mds.labels_masks))
            return f, l, fm, lm
        ds = batches[0] if len(batches) == 1 else DataSet.merge(batches)
        f = self._host_cast(np.asarray(ds.features))
        l = np.asarray(ds.labels)
        b = f.shape[0]
        if b % self.local_workers_:
            raise ValueError(
                f"Local batch {b} not divisible by "
                f"{self.local_workers_} local devices")
        fm = (None if ds.features_mask is None
              else shard_batch(jnp.asarray(ds.features_mask), self.mesh))
        lm = (None if ds.labels_mask is None
              else shard_batch(jnp.asarray(ds.labels_mask), self.mesh))
        return (shard_batch(jnp.asarray(f), self.mesh),
                shard_batch(jnp.asarray(l), self.mesh), fm, lm)

    def _stacked_batches(self, batches):
        """[N, global_b, ...] with the global batch dim sharded. Masks ride
        along (all-ones filled when presence is mixed across micro-batches).
        ``CacheMode.DEVICE`` reuses the stacked+sharded device copy across
        epochs (same cache as :meth:`_global_batch`)."""
        return self._cached_sharded(("stack",), batches,
                                    self._stacked_batches_uncached)

    def _stacked_batches_uncached(self, batches):
        def stack_masks(masks, data):
            if all(m is None for m in masks):
                return None
            ndim = next(m.ndim for m in masks if m is not None)
            return np.stack([m if m is not None
                             else np.ones(np.asarray(d).shape[:ndim],
                                          np.float32)
                             for m, d in zip(masks, data)])

        if self._is_graph:
            mds_list = [self.net._as_multi(b) for b in batches]
            n_in = len(mds_list[0].features)
            n_out = len(mds_list[0].labels)
            fs = tuple(np.stack([self._host_cast(m.features[i])
                                 for m in mds_list])
                       for i in range(n_in))
            ls = tuple(np.stack([np.asarray(m.labels[i]) for m in mds_list])
                       for i in range(n_out))
            fms = tuple(stack_masks(
                [None if m.features_masks is None else m.features_masks[i]
                 for m in mds_list],
                [m.features[i] for m in mds_list]) for i in range(n_in))
            lms = tuple(stack_masks(
                [None if m.labels_masks is None else m.labels_masks[i]
                 for m in mds_list],
                [m.labels[i] for m in mds_list]) for i in range(n_out))
            if all(m is None for m in fms):
                fms = None
            if all(m is None for m in lms):
                lms = None
            gb = fs[0].shape[1]
        else:
            fs = np.stack([self._host_cast(b.features) for b in batches])
            ls = np.stack([np.asarray(b.labels) for b in batches])
            fms = stack_masks([b.features_mask for b in batches],
                              [b.features for b in batches])
            lms = stack_masks([b.labels_mask for b in batches],
                              [b.labels for b in batches])
            gb = fs.shape[1]
        if gb % self.local_workers_:
            raise ValueError(f"Local batch {gb} not divisible by "
                             f"{self.local_workers_} local devices")
        sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
        if self.process_count > 1:
            put_leaf = lambda a: jax.make_array_from_process_local_data(
                sh, np.asarray(a))
        else:
            put_leaf = lambda a: jax.device_put(jnp.asarray(a), sh)
        put = lambda t: (None if t is None else jax.tree_util.tree_map(
            put_leaf, t))
        return put(fs), put(ls), put(fms), put(lms)

    def shutdown(self):
        pass  # no worker threads to stop — SPMD has no zoo of replicas
