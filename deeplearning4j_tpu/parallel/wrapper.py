"""ParallelWrapper: single-host multi-device data-parallel training.

TPU-native equivalent of reference
``deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java`` (898 LoC;
modes enum :59-74, fit :468, dispatch :497-516, averaging barrier :551-562).

Mapping (SURVEY.md §7 Phase 3):
 - ``TrainingMode.AVERAGING`` with ``averaging_frequency=1`` and
   ``TrainingMode.SHARED_GRADIENTS`` → ONE jitted SPMD step whose gradient
   ``psum`` over ICI is the averaging/broadcast. No host barrier, no replica
   copies: the XLA partitioner emits the collective.
 - ``averaging_frequency=N > 1`` → local SGD: a ``shard_map`` step where every
   device advances its own replica for N micro-steps on its private batch
   stream, then parameters AND updater state are ``pmean``-averaged — exactly
   the reference's periodic averaging barrier (``averageUpdatersState`` :339),
   fused into one XLA computation instead of host thread coordination.

The reference's worker threads, MagicQueue device bucketing and AffinityManager
pinning all disappear: batches go to devices by sharding annotation.
"""
from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from .sharding import (DATA_AXIS, make_mesh, replicated, batch_sharded,
                       shard_batch, data_parallel_step, pvary)
from .accumulation import GradientsAccumulator, EncodedGradientsAccumulator
from ..datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator
from ..datasets.iterators import AsyncDataSetIterator

log = logging.getLogger(__name__)
_tm = jax.tree_util.tree_map


class TrainingMode:
    """Reference ``ParallelWrapper.TrainingMode`` (:59-74)."""
    AVERAGING = "averaging"
    SHARED_GRADIENTS = "shared_gradients"
    CUSTOM = "custom"


class ParallelWrapper:
    """Builder-style facade over the SPMD data-parallel step."""

    class Builder:
        def __init__(self, net):
            self._net = net
            self._workers = None
            self._prefetch = 2
            self._freq = 1
            self._mode = TrainingMode.AVERAGING
            self._report_after_avg = True
            self._accumulator = None
            self._mesh = None

        def workers(self, n):
            self._workers = int(n)
            return self

        def prefetch_buffer(self, n):
            self._prefetch = int(n)
            return self

        prefetchBuffer = prefetch_buffer

        def averaging_frequency(self, n):
            self._freq = int(n)
            return self

        averagingFrequency = averaging_frequency

        def training_mode(self, mode):
            self._mode = mode
            return self

        trainingMode = training_mode

        def report_score_after_averaging(self, flag=True):
            self._report_after_avg = bool(flag)
            return self

        reportScoreAfterAveraging = report_score_after_averaging

        def gradients_accumulator(self, acc: GradientsAccumulator):
            self._accumulator = acc
            return self

        gradientsAccumulator = gradients_accumulator

        def mesh(self, mesh: Mesh):
            self._mesh = mesh
            return self

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._net, workers=self._workers,
                                   prefetch_buffer=self._prefetch,
                                   averaging_frequency=self._freq,
                                   training_mode=self._mode,
                                   report_score_after_averaging=self._report_after_avg,
                                   accumulator=self._accumulator,
                                   mesh=self._mesh)

    def __init__(self, net, workers: Optional[int] = None,
                 prefetch_buffer: int = 2, averaging_frequency: int = 1,
                 training_mode: str = TrainingMode.AVERAGING,
                 report_score_after_averaging: bool = True,
                 accumulator: Optional[GradientsAccumulator] = None,
                 mesh: Optional[Mesh] = None):
        self.net = net
        devices = jax.devices()
        if workers is not None and workers < len(devices):
            devices = devices[:workers]
        self.mesh = mesh if mesh is not None else make_mesh(devices,
                                                            axes=(DATA_AXIS,))
        self.workers_ = int(np.prod(self.mesh.devices.shape))
        self.prefetch_buffer = prefetch_buffer
        self.averaging_frequency = max(1, int(averaging_frequency))
        self.training_mode = training_mode
        self.report_score_after_averaging = report_score_after_averaging
        self.accumulator = accumulator
        self.iteration_count = 0
        self.last_score = float("nan")
        self._sync_step = None
        self._local_sgd_step = None
        self.averaging_ms = 0.0

    # ------------------------------------------------------------------
    def _ensure_sync_step(self):
        if self._sync_step is None:
            self._sync_step = data_parallel_step(self.net, self.mesh)
        return self._sync_step

    def _ensure_local_sgd_step(self):
        """shard_map local-SGD: [N, b, ...] micro-batch stack per device, N
        local updates, then pmean of params/updater-state/layer-state."""
        if self._local_sgd_step is not None:
            return self._local_sgd_step
        net = self.net
        mesh = self.mesh
        raw = net._raw_step(False)
        N = self.averaging_frequency

        def local_run(params, states, upd, it0, rng, fs, ls):
            # runs per-device under shard_map: fs/ls [N, b_local, ...]
            dev = jax.lax.axis_index(DATA_AXIS)
            rng = jax.random.fold_in(rng, dev)

            def body(i, carry):
                params, states, upd, _ = carry
                f = jax.lax.dynamic_index_in_dim(fs, i, keepdims=False)
                l = jax.lax.dynamic_index_in_dim(ls, i, keepdims=False)
                k = jax.random.fold_in(rng, i)
                params, states, upd, loss = raw(params, states, upd, it0 + i,
                                                k, f, l, None, None)
                return params, states, upd, loss

            # mark the carry as device-varying: replicas diverge locally
            # between averaging barriers (shard_map vma typing)
            init = jax.tree_util.tree_map(
                lambda x: pvary(x, (DATA_AXIS,)),
                (params, states, upd, jnp.asarray(0.0, jnp.float32)))
            params, states, upd, loss = jax.lax.fori_loop(0, N, body, init)
            # periodic averaging barrier (params + updater state + layer state)
            params = jax.lax.pmean(params, DATA_AXIS)
            states = jax.lax.pmean(states, DATA_AXIS)
            upd = jax.lax.pmean(upd, DATA_AXIS)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            return params, states, upd, loss

        repl = P()
        data = P(None, DATA_AXIS)  # [N, global_b, ...] split on batch dim
        fn = shard_map(local_run, mesh=mesh,
                       in_specs=(repl, repl, repl, repl, repl, data, data),
                       out_specs=(repl, repl, repl, repl))
        self._local_sgd_step = jax.jit(fn, donate_argnums=(0, 2))
        return self._local_sgd_step

    # ------------------------------------------------------------------ fit
    def fit(self, data, epochs: int = 1):
        """Train over the iterator with all devices (reference ``fit`` :468)."""
        import time
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        it = data
        if (isinstance(it, DataSetIterator)
                and not isinstance(it, AsyncDataSetIterator)
                and it.async_supported()):
            it = AsyncDataSetIterator(it, queue_size=self.prefetch_buffer)
        net = self.net
        for _ in range(epochs):
            if self.averaging_frequency == 1:
                self._fit_sync(it)
            else:
                self._fit_local_sgd(it)
            net.epoch_count += 1
        return self

    def _device_put_model(self):
        repl = replicated(self.mesh)
        net = self.net
        net.params = jax.device_put(net.params, repl)
        net.states = jax.device_put(net.states, repl)
        net.updater_state = jax.device_put(net.updater_state, repl)

    def _fit_sync(self, it):
        """AVERAGING freq=1 / SHARED_GRADIENTS: fused psum step per global
        batch (the reference's per-iteration averaging ≡ gradient all-reduce)."""
        net = self.net
        step = self._ensure_sync_step()
        self._device_put_model()
        for ds in it:
            f, l = self._global_batch([ds])
            itc = jnp.asarray(net.iteration_count, jnp.int32)
            key = jax.device_put(net._next_rng(), replicated(self.mesh))
            net.params, net.states, net.updater_state, loss = step(
                net.params, net.states, net.updater_state, itc, key, f, l,
                None, None)
            self.last_score = float(loss)
            net.score_ = loss
            net.iteration_count += 1
            self.iteration_count += 1
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration_count - 1, float(loss))

    def _fit_local_sgd(self, it):
        """AVERAGING freq=N: collect N micro-batches, one fused local-SGD +
        averaging computation."""
        import time
        net = self.net
        step = self._ensure_local_sgd_step()
        self._device_put_model()
        pending: List[DataSet] = []
        for ds in it:
            pending.append(ds)
            if len(pending) < self.averaging_frequency:
                continue
            fs, ls = self._stacked_batches(pending)
            pending = []
            itc = jnp.asarray(net.iteration_count, jnp.int32)
            key = jax.device_put(net._next_rng(), replicated(self.mesh))
            t0 = time.perf_counter()
            net.params, net.states, net.updater_state, loss = step(
                net.params, net.states, net.updater_state, itc, key, fs, ls)
            jax.block_until_ready(net.params)
            self.averaging_ms = (time.perf_counter() - t0) * 1e3
            net.iteration_count += self.averaging_frequency
            self.iteration_count += self.averaging_frequency
            self.last_score = float(loss)
            net.score_ = loss
            if self.report_score_after_averaging:
                for lst in net.listeners:
                    lst.iteration_done(net, net.iteration_count - 1, float(loss))
        if pending:
            log.info("Dropping %d tail micro-batches (< averaging_frequency)",
                     len(pending))

    # ---------------------------------------------------------------- helpers
    def _global_batch(self, batches):
        ds = batches[0] if len(batches) == 1 else DataSet.merge(batches)
        f = np.asarray(ds.features, np.float32)
        l = np.asarray(ds.labels, np.float32)
        b = f.shape[0]
        if b % self.workers_:
            raise ValueError(
                f"Global batch {b} not divisible by {self.workers_} devices")
        return (shard_batch(jnp.asarray(f), self.mesh),
                shard_batch(jnp.asarray(l), self.mesh))

    def _stacked_batches(self, batches):
        """[N, global_b, ...] with the global batch dim sharded."""
        fs = np.stack([np.asarray(b.features, np.float32) for b in batches])
        ls = np.stack([np.asarray(b.labels, np.float32) for b in batches])
        if fs.shape[1] % self.workers_:
            raise ValueError(f"Global batch {fs.shape[1]} not divisible by "
                             f"{self.workers_} devices")
        spec = P(None, DATA_AXIS)
        sh = NamedSharding(self.mesh, spec)
        return jax.device_put(jnp.asarray(fs), sh), jax.device_put(jnp.asarray(ls), sh)

    def shutdown(self):
        pass  # no worker threads to stop — SPMD has no zoo of replicas
