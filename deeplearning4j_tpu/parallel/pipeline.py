"""Pipeline parallelism: GPipe microbatch schedule over a ``pipe`` mesh axis.

Net-new capability vs the 0.9.x reference (SURVEY.md §2.4: only data
parallelism exists there), completing the mesh-axis family alongside tensor
(``parallel/tensor.py``) and sequence (``parallel/sequence.py``) parallelism.

TPU-first design (the standard XLA pipelining pattern, not a thread-per-stage
port): the S pipeline stages must be structurally identical blocks — their
parameters are STACKED on a leading stage axis and sharded across the ``pipe``
mesh axis, so each device holds 1/S of the body parameters. The whole GPipe
schedule — M microbatches flowing through S stages in M+S-1 ticks, activations
hopping stage→stage over ICI via ``ppermute`` — is ONE jitted ``lax.scan``
inside ``shard_map``. Because ``scan``/``ppermute``/``where`` are all
differentiable, reverse-mode AD of the scheduled forward IS the reverse
pipeline schedule (backward bubbles included) — no hand-written backward pass,
the exact analogue of how the containers get backprop from AD.

The homogeneous-stage constraint is the same one production TPU pipelining
makes (stacked transformer blocks); heterogeneous nets pipeline their
homogeneous middle and keep entry/head replicated, which is what
:class:`GPipe` does with its ``head_fn``.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map
from ..monitor.jitwatch import monitored_jit

from .mesh import PIPELINE_AXIS, record_step, require_axes
from .sharding import pvary

_tm = jax.tree_util.tree_map


def spmd_pipeline(stage_fn: Callable[..., Any],
                  mesh: Mesh, axis: str = PIPELINE_AXIS,
                  data_axis: Optional[str] = None, squeeze_stage: bool = True,
                  _needs_x_grad: bool = False, stateful: bool = False,
                  with_masks: bool = False, with_rng: bool = False):
    """Build ``pipelined(stacked_params, xs) -> ys`` (stateless) or
    ``pipelined(stacked_params, stacked_state, xs) -> (ys, new_state)``
    (``stateful=True``).

    ``with_masks=True`` adds a ``masks`` argument ([M, mb, ...] like ``xs``,
    no stage transform): at tick t, stage s receives the mask of the
    microbatch it is processing (t − s) — how padded-sequence masking rides
    the schedule. ``with_rng=True`` adds a PRNG ``key`` argument; each tick
    hands ``stage_fn`` a key folded per (stage, microbatch), giving
    dropout/weight-noise inside the pipeline the same per-microbatch
    freshness as the container step. The extra arguments are appended to
    ``stage_fn``'s signature in the order (…, x[, mask][, key]).

    ``stacked_params``: pytree whose leaves carry a leading stage dim of
    extent S = mesh.shape[axis] (sharded over ``axis``). ``xs``: microbatches
    ``[M, mb, ...]``. ``stage_fn(params_slice, x) -> y`` — or
    ``stage_fn(params_slice, state_slice, x) -> (y, new_state)`` when
    stateful — must map ``[mb, F] → [mb, F]`` (same shape family every stage
    — the SPMD homogeneity rule). Returns ``ys`` ``[M, mb, ...]``, the last
    stage's outputs, replicated across ``axis``. When ``data_axis`` is given
    the microbatch dim stays sharded over it (combined DP×PP).

    Stateful stages (e.g. BatchNorm running stats) carry their state through
    the GPipe scan: a stage's state advances only on its LIVE ticks (tick t
    processes microbatch t - stage on stage ``stage``), so each stage folds
    its per-microbatch updates in microbatch order — the standard GPipe
    treatment of batch-statistics layers (per-microbatch normalization,
    running stats accumulated across microbatches).

    ``squeeze_stage=True`` (the classic one-block-per-stage case) strips the
    local leading stage dim of extent 1 before calling ``stage_fn``. With
    ``squeeze_stage=False`` the stage dim may pack SEVERAL layers per device
    (leading extent B/S) and ``stage_fn`` receives the whole local slice —
    how ``pipeline_parallel_step`` maps a B-layer homogeneous body onto S
    stages."""
    S = mesh.shape[axis]

    def per_device(params, state, xs, masks, key):
        if squeeze_stage:
            params = _tm(lambda p: p[0], params)  # [1, ...] local slice → stage
            if stateful:
                state = _tm(lambda s: s[0], state)
        idx = lax.axis_index(axis)
        M = xs.shape[0]
        if with_rng and data_axis is not None:
            # decorrelate noise across data shards (the container DP path
            # folds by data-axis index too — wrapper.py's per-worker rng)
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        if not _needs_x_grad:
            # mark the feed device-varying over the pipe axis. NOT done when
            # upstream (entry) layers need ∂loss/∂xs: pvary's transpose is a
            # psum over 'pipe', which under check_vma=False sees an untyped
            # cotangent and rejects it — and with check_vma=False the
            # varying mark is only documentation anyway.
            xs = pvary(xs, (axis,))
        perm = [(j, (j + 1) % S) for j in range(S)]
        buf0 = jnp.zeros_like(xs[0])

        def tick(carry, t):
            # stage 0 ingests microbatch t (zeros once the feed is drained);
            # everyone else consumes the activation received last tick
            buf, st = carry
            x_t = jnp.where(t < M, xs[jnp.minimum(t, M - 1)],
                            jnp.zeros_like(xs[0]))
            inp = jnp.where(idx == 0, x_t, buf)
            args = [inp]
            mi = jnp.clip(t - idx, 0, M - 1)   # microbatch this stage holds
            if with_masks:
                args.append(None if masks is None
                            else _tm(lambda m: m[mi], masks))
            if with_rng:
                # distinct stream per (stage, microbatch) — folding by mi
                # (not t) keeps a microbatch's noise independent of WHERE in
                # the schedule it meets each stage
                args.append(jax.random.fold_in(jax.random.fold_in(key, idx),
                                               mi))
            if stateful:
                out, st_new = stage_fn(params, st, *args)
                # state advances only while this stage is processing a real
                # microbatch (bubble ticks compute on garbage buffers)
                live = jnp.logical_and(t >= idx, t < idx + M)
                st = _tm(lambda a, b: jnp.where(live, b, a), st, st_new)
            else:
                out = stage_fn(params, *args)
            nxt = lax.ppermute(out, axis, perm)
            return (nxt, st), out

        (_, st_fin), outs = lax.scan(tick, (buf0, state),
                                     jnp.arange(M + S - 1))
        # tick t on the last stage finishes microbatch t-(S-1): ticks
        # S-1 .. M+S-2 are exactly microbatches 0..M-1
        ys = outs[S - 1:]
        ys = lax.psum(jnp.where(idx == S - 1, ys, jnp.zeros_like(ys)), axis)
        if not stateful:
            return ys
        if data_axis is not None:
            # under DP×PP each data shard folded batch statistics from its
            # own microbatch shard only — reconcile by averaging across the
            # data axis (the reference ParallelWrapper's worker-state
            # averaging applied to e.g. BatchNorm running stats), restoring
            # the replication the out-sharding declares
            st_fin = _tm(lambda s: lax.pmean(s, data_axis), st_fin)
        if squeeze_stage:
            st_fin = _tm(lambda s: s[None], st_fin)
        return ys, st_fin

    pspec = _leading_axis_spec(axis)
    xspec = P(None, data_axis) if data_axis else P()
    repl = P()

    def wrapper(params, *rest):
        i = 0
        state = rest[i] if stateful else {}
        i += int(stateful)
        xs = rest[i]
        i += 1
        masks = rest[i] if with_masks else None
        i += int(with_masks)
        key = rest[i] if with_rng else None
        return per_device(params, state, xs, masks, key)

    specs = ([pspec] + ([pspec] if stateful else []) + [xspec]
             + ([xspec] if with_masks else [])
             + ([repl] if with_rng else []))
    out_specs = (xspec, pspec) if stateful else xspec
    return shard_map(wrapper, mesh=mesh, in_specs=tuple(specs),
                     out_specs=out_specs, check_vma=False)


def _leading_axis_spec(axis: str):
    """PartitionSpec pytree-prefix: shard every leaf's leading dim."""
    return P(axis)


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of S identical pytrees along a new leading stage axis."""
    return _tm(lambda *leaves: jnp.stack(leaves), *per_stage_params)


class GPipe:
    """GPipe trainer: pipelined homogeneous body + replicated head.

    ``block_fn(block_params, x) -> x`` is one stage; ``head_fn(head_params,
    y_feats, labels) -> scalar mean loss`` closes the step. ``params`` is
    ``{"blocks": stacked-pytree [S, ...], "head": pytree}``. The jitted
    ``train_step`` does fwd + AD bwd (reverse pipeline schedule) + updater +
    apply in one XLA computation, with body params/updater-state sharded over
    ``pipe`` and the head replicated — the same whole-step-compile shape as
    the containers' ``_ensure_step``.
    """

    def __init__(self, block_fn, head_fn, mesh: Mesh, n_microbatches: int,
                 updater, axis: str = PIPELINE_AXIS,
                 data_axis: Optional[str] = None):
        require_axes(mesh, (axis, data_axis), style="GPipe")
        record_step("pipeline/gpipe", mesh,
                    {"blocks": P(axis), "head": P()})
        self.mesh = mesh
        self.axis = axis
        self.data_axis = data_axis
        self.n_microbatches = int(n_microbatches)
        self.updater = updater
        self._pipeline = spmd_pipeline(block_fn, mesh, axis, self.data_axis)
        self._head_fn = head_fn
        self._step = None

    # -- placement --------------------------------------------------------
    def block_sharding(self):
        return NamedSharding(self.mesh, P(self.axis))

    def place(self, params, upd_state=None):
        """device_put params (+ mirrored updater state) onto the mesh:
        blocks stage-sharded, head replicated."""
        repl = NamedSharding(self.mesh, P())
        blk = self.block_sharding()

        def put(tree):
            return {"blocks": _tm(lambda p: jax.device_put(p, blk),
                                  tree["blocks"]),
                    "head": _tm(lambda p: jax.device_put(p, repl),
                                tree["head"])}
        return put(params) if upd_state is None else (put(params),
                                                      put(upd_state))

    # -- the step ----------------------------------------------------------
    def _loss(self, params, x_mb, y_mb):
        feats = self._pipeline(params["blocks"], x_mb)
        # head applied per-microbatch; mean of means == global mean when
        # microbatches are equal-sized
        losses = jax.vmap(lambda f, y: self._head_fn(params["head"], f, y)
                          )(feats, y_mb)
        return jnp.mean(losses)

    def _build_step(self):
        upd = self.updater

        def step(params, upd_state, it, x, y):
            M = self.n_microbatches
            x_mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            y_mb = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            loss, grads = jax.value_and_grad(self._loss)(params, x_mb, y_mb)
            updates, new_state = upd.apply(upd_state, grads, it)
            new_params = _tm(lambda p, u: p - u, params, updates)
            return new_params, new_state, loss

        repl = NamedSharding(self.mesh, P())
        blk = self.block_sharding()
        tree_sh = {"blocks": blk, "head": repl}
        dsh = (NamedSharding(self.mesh, P(self.data_axis))
               if self.data_axis else repl)
        return monitored_jit(
            step, name="pipeline/step",
            in_shardings=(tree_sh, tree_sh, repl, dsh, dsh),
            out_shardings=(tree_sh, tree_sh, repl),
            donate_argnums=(0, 1))

    def train_step(self, params, upd_state, iteration, x, y):
        """One pipelined training step. Returns (params, upd_state, loss)."""
        if self._step is None:
            self._step = self._build_step()
        it = jnp.asarray(iteration, jnp.int32)
        return self._step(params, upd_state, it, x, y)


# ---------------------------------------------------------------------------
# Container-level pipeline parallelism
# ---------------------------------------------------------------------------
def _layer_confs_equal(a, b):
    import dataclasses
    return (type(a) is type(b)
            and dataclasses.asdict(a) == dataclasses.asdict(b))


def _best_periodic_run(confs, n_stages: int, max_period: int):
    """Longest lag-p periodic run over a list of layer configs, trimmed to a
    multiple of ``p * n_stages``: returns (offset, usable_len, period) with
    usable_len == 0 when nothing fits. Smaller periods win ties."""
    n = len(confs)
    best = (0, 0, 1)                          # (offset, usable_len, period)
    for p in range(1, max(1, min(max_period, n // max(1, n_stages))) + 1):
        j = 0
        while j + p < n:
            if not _layer_confs_equal(confs[j], confs[j + p]):
                j += 1
                continue
            a = j                              # maximal lag-p match run
            while j + p < n and _layer_confs_equal(confs[j], confs[j + p]):
                j += 1
            run = (j + p) - a                  # segment [a, a + run)
            usable = (run // (p * n_stages)) * (p * n_stages)
            if usable > best[1]:
                best = (a, usable, p)
    return best


def partition_network(net, n_stages: int, max_period: int = 8):
    """Find ``(start, length, period)`` of the body to pipeline: the longest
    PERIODIC run of layer configs — ``layers[j] == layers[j + period]``
    throughout — trimmed to the largest multiple of ``period * n_stages``.
    ``period == 1`` is the classic identical-layer stack (LSTM cells);
    ``period > 1`` pipelines repeated BLOCKS (Dense→BatchNorm→…, attention→
    FFN transformer blocks) — each stage then holds the same layer sequence,
    preserving the SPMD stage-homogeneity rule. Everything before the run is
    the replicated entry, everything after (plus any trimmed tail) the
    replicated head. Smaller periods win ties (simplest stage program)."""
    start, body, period = _best_periodic_run(net.conf.layers, n_stages,
                                             max_period)
    if body < n_stages:
        raise ValueError(
            f"No periodic run of ≥ {n_stages} repeated layers/blocks to map "
            f"onto {n_stages} pipeline stages (best: {body} layers at "
            f"{start}). Stack identical middle layers or blocks (e.g. "
            f"TextGenerationLSTM(num_layers=...)) or use fewer stages.")
    return start, body, period


def _graph_consumers(conf):
    """vertex/input name → list of vertex names consuming it."""
    consumers = {}
    for name, ins in conf.vertex_inputs.items():
        for i in ins:
            consumers.setdefault(i, []).append(name)
    return consumers


def partition_graph(cg, n_stages: int, max_period: int = 8):
    """ComputationGraph counterpart of :func:`partition_network`: find the
    best pipelinable CHAIN of layer vertices. A chain is a maximal path
    v₀ → v₁ → … where every vᵢ is a single-input Layer vertex, every
    interior vᵢ has exactly one consumer (no branches escape the chain) and
    none is a network output; the chain's layer configs are then trimmed to
    the longest lag-p periodic run (same rule as the MLN partition).
    Returns (chain_names list, period)."""
    conf = cg.conf
    from ..nn.conf.layers import Layer

    consumers = _graph_consumers(conf)

    def chainable(name):
        v = conf.vertices.get(name)
        return (isinstance(v, Layer)
                and len(conf.vertex_inputs.get(name, ())) == 1
                and name not in conf.network_outputs
                and conf.input_preprocessors.get(name) is None)

    chains, seen = [], set()
    for name in cg.topo:
        if name in seen or not chainable(name):
            continue
        # only start where the predecessor cannot extend the chain backward
        prev = conf.vertex_inputs[name][0]
        if (chainable(prev) and consumers.get(prev, []) == [name]):
            continue
        chain, cur = [name], name
        seen.add(name)
        while True:
            cons = consumers.get(cur, [])
            if len(cons) != 1 or not chainable(cons[0]):
                break
            cur = cons[0]
            chain.append(cur)
            seen.add(cur)
        chains.append(chain)

    best = None                               # (names, period)
    for chain in chains:
        confs = [conf.vertices[n] for n in chain]
        off, ln, p = _best_periodic_run(confs, n_stages, max_period)
        if ln >= n_stages and (best is None or ln > len(best[0])):
            best = (chain[off:off + ln], p)
    if best is None:
        raise ValueError(
            f"No periodic chain of ≥ {n_stages} repeated layer vertices to "
            f"map onto {n_stages} pipeline stages. Pipeline-parallel CGs "
            f"need a linear run of repeated single-input layer vertices "
            f"(e.g. stacked transformer blocks); use fewer stages or "
            f"restructure the graph.")
    return best


def _vertex_eq(a, b):
    """Structural equality of two vertex configs: every vertex/layer conf
    is a dataclass, whose generated ``__eq__`` compares class + fields."""
    return type(a) is type(b) and a == b


def partition_graph_blocks(cg, n_stages: int, max_block: int = 16):
    """Find repeated single-input/single-output SUBGRAPH windows along the
    topo order — the residual-transformer case :func:`partition_graph`'s
    linear-chain rule cannot express (skip connections live INSIDE each
    block: ``x + Attn(LN(x)); x + FFN(LN(x))``).

    A valid body is windows ``W_r = topo[s + r·p : s + (r+1)·p]`` where,
    for every repeat r: (1) vertex configs match offset-wise across
    repeats; (2) each vertex's inputs resolve to the SAME relative
    positions — an in-window offset or the window's single external input
    (window r's external input = window r-1's LAST vertex; window 0's =
    whatever name the pattern references); (3) interior vertices have no
    consumers outside their window, so the last offset is the only spine.
    Returns (body_names, period, template) with ``template`` a list of
    per-offset ``(is_layer, rel_inputs)`` where ``rel_inputs`` entries are
    ``("ext",)`` or ``("in", offset)`` — enough for a stage to execute the
    block without the global DAG. Raises like :func:`partition_graph` when
    nothing qualifies."""
    conf = cg.conf
    from ..nn.conf.layers import Layer

    topo = list(cg.topo)
    consumers = _graph_consumers(conf)
    n = len(topo)

    def window_tmpl(s, p, r, ext):
        """Template of window r = topo[s+r·p : s+(r+1)·p] given its single
        allowed external input name ``ext``; None when invalid."""
        base = s + r * p
        if base + p > n:
            return None
        names = topo[base:base + p]
        index = {nm: j for j, nm in enumerate(names)}
        tmpl = []
        for j, nm in enumerate(names):
            v = conf.vertices.get(nm)
            if v is None or nm in conf.network_outputs \
                    or conf.input_preprocessors.get(nm) is not None:
                return None
            rel = []
            for i_name in conf.vertex_inputs.get(nm, ()):
                if i_name in index:
                    if index[i_name] >= j:
                        return None
                    rel.append(("in", index[i_name]))
                elif i_name == ext:
                    rel.append(("ext",))
                else:
                    return None
            # interior vertices must not leak outside the window (the last
            # offset is the sole spine; its consumers are checked by the
            # caller against the NEXT window)
            if j < p - 1:
                if any(c not in index for c in consumers.get(nm, ())):
                    return None
            tmpl.append((isinstance(v, Layer), tuple(rel)))
        return tmpl

    def spine_pure(s, p, r):
        """Window r's last vertex may only feed window r+1."""
        last = topo[s + r * p + p - 1]
        nxt = set(topo[s + (r + 1) * p:s + (r + 2) * p])
        return all(c in nxt for c in consumers.get(last, ()))

    best = None                               # (start, period, R, template)
    for p in range(1, max_block + 1):
        for s in range(n - p * n_stages + 1):
            # window 0's external input: the single out-of-window name its
            # vertices reference (there must be exactly one)
            names0 = set(topo[s:s + p])
            refs = {i for nm in topo[s:s + p]
                    for i in conf.vertex_inputs.get(nm, ())
                    if i not in names0}
            if len(refs) != 1:
                continue
            ext0 = next(iter(refs))
            tmpl = window_tmpl(s, p, 0, ext0)
            if not tmpl or not any(("ext",) in rel for _, rel in tmpl):
                continue
            R = 1
            while spine_pure(s, p, R - 1):
                base = s + R * p
                t2 = window_tmpl(s, p, R, topo[base - 1])
                if (t2 != tmpl
                        or not all(_vertex_eq(conf.vertices[topo[s + j]],
                                              conf.vertices[topo[base + j]])
                                   for j in range(p))):
                    break
                R += 1
            R = (R // n_stages) * n_stages    # stage homogeneity
            if R >= n_stages and R * p > (0 if best is None
                                          else best[2] * best[1]):
                best = (s, p, R, tmpl)
    if best is None:
        raise ValueError(
            f"No repeated single-input/single-output block pattern of ≥ "
            f"{n_stages} repeats found to map onto {n_stages} pipeline "
            f"stages; stack identical blocks (e.g. TransformerLM(num_blocks"
            f"=...)) or use fewer stages.")
    s, p, R, tmpl = best
    return topo[s:s + R * p], p, tmpl


class _PipelinedBase:
    """Shared machinery for the container-level pipeline trainers
    (:class:`PipelinedNetwork` for MultiLayerNetwork, :class:`PipelinedGraph`
    for ComputationGraph): {entry, blocks, head} placement, the jitted
    donated train step (microbatch split → loss+AD → updater → constraints),
    and the container-layout import/export. Subclasses provide the
    partitioning, the stage/entry/head forward pieces and the loss."""

    def _init_common(self, net, mesh, n_microbatches, axis, data_axis):
        require_axes(mesh, (axis, data_axis), style=type(self).__name__)
        record_step("pipeline/" + type(self).__name__, mesh,
                    {"entry": P(), "blocks": P(axis), "head": P()})
        if int(getattr(net.gc, "iterations", 1) or 1) > 1:
            import logging
            logging.getLogger(__name__).warning(
                "iterations(%s) is ignored under %s; each fit_batch applies "
                "one optimizer iteration", net.gc.iterations,
                type(self).__name__)
        self.net = net
        self.mesh = mesh
        self.axis = axis
        self.data_axis = data_axis
        self.n_microbatches = int(n_microbatches)
        self.n_stages = mesh.shape[axis]
        self.updater = net.gc.updater
        self._step = None
        self.iteration_count = 0
        # per-step dropout/weight-noise stream, seeded like the container
        self._base_key = jax.random.PRNGKey(
            int(getattr(net.gc, "seed", None) or 0))

    def _check_layer_conf(self, where, lc):
        if getattr(lc, "updater", None) is not None:
            raise ValueError(
                f"{where} sets a per-layer updater override; the pipelined "
                f"step trains every partition with the network-level updater")
        if getattr(lc, "aux_loss_weight", 0.0):
            raise ValueError(
                f"{where} ({type(lc).__name__}) produces an activation-"
                f"dependent auxiliary loss (aux_loss_weight="
                f"{lc.aux_loss_weight}); the pipelined step does not collect "
                f"ctx['aux_loss'] — set aux_loss_weight=0 or train "
                f"unpipelined")

    # -- placement ---------------------------------------------------------
    def _shardings(self):
        repl = NamedSharding(self.mesh, P())
        blk = NamedSharding(self.mesh, P(self.axis))
        return {"entry": repl, "blocks": blk, "head": repl}

    def _place(self, tree):
        sh = self._shardings()
        # host round-trip = genuine copy: the jitted step DONATES these
        # buffers, and device_put with an equal sharding can alias — donation
        # must never invalidate the source container's params
        return {k: _tm(lambda p: jax.device_put(np.asarray(p), sh[k]),
                       tree[k])
                for k in tree}

    # -- container-layout import/export ------------------------------------
    def _from_layer_keyed(self, d):
        return self._partition_tree(d)

    def export_params(self):
        """Back to the container's per-layer/vertex keying (for
        ModelSerializer / evaluation on the unpipelined net)."""
        return {k: _tm(np.asarray, v)
                for k, v in self._to_layer_keyed(self.params).items()}

    def export_states(self):
        """Trained layer state (BatchNorm running stats, …) back to the
        container's per-layer/vertex keying."""
        return {k: _tm(np.asarray, v)
                for k, v in self._to_layer_keyed(self.states).items()}

    # -- the shared body stage -------------------------------------------
    def _stage_fn(self, params_slice, state_slice, x, *rest):
        """One pipeline stage = repeats_per_stage repeats of the period-p
        block (leaves carry the local [R/S, ...] repeat dim). ``rest`` is
        (mask, key) — both pipelines stream masks (the MLN's [b, T] mask,
        the CG's propagated body-input mask; None when unmasked); ``key``
        is the per-(stage, microbatch) PRNG key driving dropout/weight
        noise exactly like the container's per-layer keys. Returns the
        activations and the functionally-updated state slice."""
        mask, key = rest
        new_state = {str(l): state_slice[str(l)] for l in range(self.period)}
        for j in range(self.repeats_per_stage):
            for l, impl in enumerate(self.body_impls):
                k = jax.random.fold_in(key, j * self.period + l)
                p_j = _tm(lambda q: q[j], params_slice[str(l)])
                s_j = _tm(lambda q: q[j], new_state[str(l)])
                p_n = impl.noised_params(p_j, True, k)
                x, ns = impl.forward(p_n, s_j, x, train=True, rng=k,
                                     mask=mask, ctx={})
                new_state[str(l)] = _tm(lambda buf, v: buf.at[j].set(v),
                                        new_state[str(l)], ns)
        return x, new_state

    # -- the step ----------------------------------------------------------
    def _build_step(self):
        from ..optimize.updater import normalize_gradients

        gn_mode = self.net.gc.gradient_normalization
        gn_thresh = self.net.gc.gradient_normalization_threshold
        minimize = self.net.gc.minimize
        upd = self.updater
        M = self.n_microbatches

        def step(tree, states, upd_state, it, key, f, l, fm, lm):
            mb = lambda t: _tm(
                lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), t)
            (loss, new_states), grads = jax.value_and_grad(
                self._loss, has_aux=True)(tree, states, mb(f), mb(l),
                                          mb(fm), mb(lm), key)
            if not minimize:
                grads = _tm(lambda g: -g, grads)
            from ..nn.conf import GradientNormalization
            if gn_mode not in (None, GradientNormalization.None_, "none"):
                # per-layer normalization modes must see the container's
                # per-layer grouping, not {entry, blocks, head}
                grads = self._from_layer_keyed(normalize_gradients(
                    self._to_layer_keyed(grads), gn_mode, gn_thresh))
            updates, new_state = upd.apply(upd_state, grads, it)
            new_tree = _tm(lambda p, u: p - u.astype(p.dtype), tree, updates)
            new_tree = self._apply_constraints(new_tree)
            return new_tree, new_states, new_state, loss

        sh = self._shardings()
        repl = NamedSharding(self.mesh, P())
        dsh = (NamedSharding(self.mesh, P(self.data_axis))
               if self.data_axis else repl)
        return monitored_jit(
            step, name="pipeline/container_step",
            in_shardings=(sh, sh, sh, repl, repl, dsh, dsh, dsh, dsh),
            out_shardings=(sh, sh, sh, repl),
            donate_argnums=(0, 1, 2))

    def fit_batch(self, f, l, features_mask=None, labels_mask=None):
        """One pipelined optimizer step on a (features, labels) batch — each
        a single array (MultiLayerNetwork) or tuple of arrays
        (ComputationGraph) whose leading dim divides into
        ``n_microbatches`` equal chunks. Optional masks ride the schedule
        with their microbatch."""
        if self._step is None:
            self._step = self._build_step()
        it = jnp.asarray(self.iteration_count, jnp.int32)
        key = jax.random.fold_in(self._base_key, self.iteration_count)
        f = _tm(jnp.asarray, f)
        l = _tm(jnp.asarray, l)
        fm = _tm(jnp.asarray, features_mask)
        lm = _tm(jnp.asarray, labels_mask)
        self.params, self.states, self.upd_state, loss = self._step(
            self.params, self.states, self.upd_state, it, key, f, l, fm, lm)
        self.iteration_count += 1
        return loss


class PipelinedNetwork(_PipelinedBase):
    """Train a ``MultiLayerNetwork``'s homogeneous middle as GPipe stages
    (VERDICT round-3 item 3: container-level pipeline parallelism).

    The network is partitioned entry | body | head by
    :func:`partition_network` — the body is the longest PERIODIC run of
    layer configs, so stacked identical layers (period 1: LSTM cells) AND
    stacked blocks (period p: Dense→BatchNorm→…, attention→FFN) both
    pipeline. Body layer params are STACKED per in-block offset on a leading
    repeat axis and sharded over the mesh ``pipe`` axis (B/S layers per
    stage), entry/head stay replicated, and the body forward runs through
    :func:`spmd_pipeline` — reverse-mode AD of that schedule is the reverse
    pipeline, exactly like :class:`GPipe`. Combined DP×PP: pass a mesh with
    a ``data`` axis too and the (micro)batch dim stays sharded over it.

    STATEFUL layers (BatchNorm running stats, CenterLoss centers) are
    supported everywhere (v2): body state rides the GPipe scan (advancing
    only on live ticks), entry/head apply per microbatch via ``lax.scan``
    threading state in microbatch order. Note the GPipe-standard semantics:
    batch statistics are computed PER MICROBATCH (running stats fold across
    microbatches in order), which intentionally differs from the
    full-batch statistics of the unpipelined step.

    Container-step semantics carried over: l1/l2 regularization,
    ``minimize=False`` (sign flip), gradient normalization, per-layer
    parameter constraints after each update, [b, T] feature/label MASKS
    (each microbatch's mask rides the schedule with it), and dropout/
    weight-noise (per-(stage, microbatch, layer) folded keys — same
    freshness as the container's per-layer keys). Remaining constraints
    (checked loudly): no per-layer updater overrides, no preprocessors
    inside the body run; ``iterations(n)`` is ignored (one update per
    ``fit_batch``, like ParallelWrapper).
    """

    def __init__(self, net, mesh: Mesh, n_microbatches: int,
                 axis: str = PIPELINE_AXIS, data_axis: Optional[str] = None):
        if not hasattr(net.conf, "layers"):
            raise ValueError("PipelinedNetwork supports MultiLayerNetwork; "
                             "ComputationGraph pipelines via PipelinedGraph")
        for i, lc in enumerate(net.conf.layers):
            self._check_layer_conf(f"layer {i}", lc)
        self._init_common(net, mesh, n_microbatches, axis, data_axis)
        S = self.n_stages
        self.start, self.body_len, self.period = partition_network(net, S)
        self.layers_per_stage = self.body_len // S
        self.repeats_per_stage = self.layers_per_stage // self.period
        self.body_impls = [net.impls[self.start + l]
                           for l in range(self.period)]
        for i in range(self.start, self.start + self.body_len):
            if net.conf.preprocessor(i) is not None:
                raise ValueError("preprocessors inside the pipelined body "
                                 "are not supported")
        self._pipeline = spmd_pipeline(self._stage_fn, mesh, axis, data_axis,
                                       squeeze_stage=False,
                                       _needs_x_grad=self.start > 0,
                                       stateful=True, with_masks=True,
                                       with_rng=True)
        # partitioned + placed params/states and mirrored updater state
        self.params = self._place(self._partition_tree(net.params))
        self.states = self._place(self._partition_tree(net.states))
        self.upd_state = self._place(
            self.updater.init_state(self.params))

    # -- param/state layout ------------------------------------------------
    def _partition_tree(self, net_tree):
        """Container {layer-index: tree} → {entry, blocks, head}: body
        layers grouped by in-block offset l (0..period-1), stacked across
        the R = body/period repeats on a leading axis (sharded over
        ``pipe``)."""
        s, b, p = self.start, self.body_len, self.period
        n = len(self.net.impls)
        entry = {str(i): net_tree[str(i)] for i in range(s)}
        head = {str(i): net_tree[str(i)] for i in range(s + b, n)}
        blocks = {str(l): stack_stage_params(
            [net_tree[str(s + r * p + l)] for r in range(b // p)])
            for l in range(p)}
        return {"entry": entry, "blocks": blocks, "head": head}

    # -- forward pieces ----------------------------------------------------
    def _entry_apply(self, params, states, f_mb, fm_mb, keys_mb):
        """Entry layers over the [M, mb, ...] microbatches. Stateless entry
        (the common case) applies as ONE vmapped computation; a stateful
        entry (BatchNorm running stats) goes through ``lax.scan`` so state
        threads through microbatches in order, matching the body's
        live-tick order."""
        s = self.start

        def step(st, xmk):
            x, m, k = xmk
            ctx = {}
            new_st = dict(st)
            for i in range(s):
                ki = jax.random.fold_in(k, i)
                pre = self.net.conf.preprocessor(i)
                if pre is not None:
                    x = pre(x, ctx)
                impl = self.net.impls[i]
                p_n = impl.noised_params(params[str(i)], True, ki)
                x, ns = impl.forward(p_n, st[str(i)], x, train=True, rng=ki,
                                     mask=m, ctx=ctx)
                new_st[str(i)] = ns
            return new_st, x

        if not jax.tree_util.tree_leaves(states):
            return states, jax.vmap(
                lambda x, m, k: step(states, (x, m, k))[1],
                in_axes=(0, None if fm_mb is None else 0, 0))(
                    f_mb, fm_mb, keys_mb)
        return lax.scan(step, states, (f_mb, fm_mb, keys_mb))

    def _head_apply(self, params, states, feats, l_mb, fm_mb, lm_mb,
                    keys_mb):
        """Head layers + output loss per microbatch; returns
        (final head state, per-microbatch losses). Stateless head → one
        vmapped computation; stateful → scan threading state in microbatch
        order (see :meth:`_entry_apply`)."""
        net, s, b = self.net, self.start, self.body_len
        n = len(net.impls)
        out_impl = net.impls[-1]

        def step(st, xy):
            x, l, fm, lm, k = xy
            ctx = {}
            new_st = dict(st)
            for i in range(s + b, n - 1):
                ki = jax.random.fold_in(k, i)
                pre = net.conf.preprocessor(i)
                if pre is not None:
                    x = pre(x, ctx)
                impl = net.impls[i]
                p_n = impl.noised_params(params[str(i)], True, ki)
                x, ns = impl.forward(p_n, st[str(i)], x, train=True, rng=ki,
                                     mask=fm, ctx=ctx)
                new_st[str(i)] = ns
            pre = net.conf.preprocessor(n - 1)
            if pre is not None:
                x = pre(x, ctx)
            # container mask rule (MultiLayerNetwork._loss_fn): label mask,
            # else the feature mask for sequence outputs
            mask = lm if lm is not None else (fm if x.ndim == 3 else None)
            loss = out_impl.loss_on(params[str(n - 1)], st[str(n - 1)], x, l,
                                    mask=mask, train=True,
                                    rng=jax.random.fold_in(k, n - 1))
            if hasattr(out_impl, "update_state"):
                # e.g. CenterLoss EMA centers — updated outside AD
                new_st[str(n - 1)] = out_impl.update_state(
                    st[str(n - 1)], jax.lax.stop_gradient(x), l)
            return new_st, loss

        if not jax.tree_util.tree_leaves(states):
            return states, jax.vmap(
                lambda x, l, fm, lm, k: step(states, (x, l, fm, lm, k))[1],
                in_axes=(0, 0, None if fm_mb is None else 0,
                         None if lm_mb is None else 0, 0))(
                    feats, l_mb, fm_mb, lm_mb, keys_mb)
        return lax.scan(step, states, (feats, l_mb, fm_mb, lm_mb, keys_mb))

    def _loss(self, tree, states, f_mb, l_mb, fm_mb, lm_mb, key):
        s, b, p = self.start, self.body_len, self.period
        M = f_mb.shape[0]
        S = self.n_stages
        # disjoint streams: body stages fold (idx < S, mi); entry/head fold
        # (S, m) / (S + 1, m)
        ek = jax.random.split(jax.random.fold_in(key, S), M)
        hk = jax.random.split(jax.random.fold_in(key, S + 1), M)
        entry_st, entry = self._entry_apply(tree["entry"], states["entry"],
                                            f_mb, fm_mb, ek)
        feats, blocks_st = self._pipeline(tree["blocks"], states["blocks"],
                                          entry, fm_mb, key)
        head_st, losses = self._head_apply(tree["head"], states["head"],
                                           feats, l_mb, fm_mb, lm_mb, hk)
        # mean of per-microbatch means == global mean (equal-size chunks)
        loss = jnp.mean(losses)
        # l1/l2 (param-only → computable per partition; keeps loss parity
        # with MultiLayerNetwork._loss_fn's reg term)
        reg = 0.0
        n = len(self.net.impls)
        for i in range(s):
            reg = reg + self.net.impls[i].regularization(
                tree["entry"][str(i)])
        for r in range(b // p):   # unrolled: regularization may be plain 0.0
            for l in range(p):
                reg = reg + self.body_impls[l].regularization(
                    _tm(lambda q: q[r], tree["blocks"][str(l)]))
        for i in range(s + b, n):
            reg = reg + self.net.impls[i].regularization(tree["head"][str(i)])
        new_states = {"entry": entry_st, "blocks": blocks_st,
                      "head": head_st}
        return loss + reg, new_states

    # -- the step ----------------------------------------------------------
    def _to_layer_keyed(self, tree):
        """{entry|blocks|head} tree → the container's per-layer-index keying
        (body repeats unstacked) so per-layer gradient-normalization modes
        see the same grouping as MultiLayerNetwork."""
        s, b, p = self.start, self.body_len, self.period
        n = len(self.net.impls)
        out = {str(i): tree["entry"][str(i)] for i in range(s)}
        for r in range(b // p):
            for l in range(p):
                out[str(s + r * p + l)] = _tm(lambda q: q[r],
                                              tree["blocks"][str(l)])
        out.update({str(i): tree["head"][str(i)] for i in range(s + b, n)})
        return out

    def _layer_constraints(self, i):
        lc = self.net.conf.layers[i]
        return getattr(lc, "constraints", None) or \
            getattr(getattr(lc, "inner", None), "constraints", None)

    def fit_batch(self, f, l, features_mask=None, labels_mask=None):
        """One pipelined step; user-facing conv features are NCHW and
        adapted to internal NHWC exactly like ``MultiLayerNetwork.fit``.
        ``features_mask``/``labels_mask``: [b, T] sequence masks — streamed
        through every entry/body/head layer and the output loss, same
        semantics as the container's masked ``fit``."""
        return super().fit_batch(self.net._adapt_input(jnp.asarray(f)), l,
                                 features_mask, labels_mask)

    def _apply_constraints(self, tree):
        """Per-layer parameter constraints after each update — same timing
        as the containers' ``_apply_constraints``. Body constraints apply
        per STAGE SLICE (norms must not mix layers across the stacked dim)."""
        from ..nn.conf.dropout import apply_constraints

        s, b, p = self.start, self.body_len, self.period
        n = len(self.net.impls)
        out = {"entry": dict(tree["entry"]), "blocks": dict(tree["blocks"]),
               "head": dict(tree["head"])}
        for i in list(range(s)) + list(range(s + b, n)):
            cons = self._layer_constraints(i)
            if cons:
                part = "entry" if i < s else "head"
                out[part][str(i)] = apply_constraints(cons,
                                                      out[part][str(i)])
        for l in range(p):
            cons = self._layer_constraints(self.start + l)
            if cons:
                # per REPEAT slice: norms must not mix layers across the
                # stacked repeat dim
                per_rep = [apply_constraints(cons,
                                             _tm(lambda q: q[r],
                                                 tree["blocks"][str(l)]))
                           for r in range(b // p)]
                out["blocks"][str(l)] = stack_stage_params(per_rep)
        return out


class PipelinedGraph(_PipelinedBase):
    """Pipeline-parallel training for a ``ComputationGraph``: the best
    periodic CHAIN of single-input layer vertices (found by
    :func:`partition_graph` — e.g. stacked transformer blocks) becomes the
    GPipe body; the rest of the DAG splits into the replicated entry
    (everything the body does NOT depend on transitively downstream) and the
    replicated head (everything downstream of the chain end), so skip
    connections AROUND the body and multi-input/multi-output graphs work.
    Entry/head run per microbatch (vmapped when stateless, scanned when
    stateful); losses follow the container's multi-output sum with the
    fused-softmax skip. Same GPipe-standard caveat as
    :class:`PipelinedNetwork`: batch statistics are per microbatch."""

    def __init__(self, net, mesh: Mesh, n_microbatches: int,
                 axis: str = PIPELINE_AXIS, data_axis: Optional[str] = None):
        conf = net.conf
        if not hasattr(conf, "vertices"):
            raise ValueError("PipelinedGraph needs a ComputationGraph")
        from ..nn.conf.layers import Layer

        for name, v in conf.vertices.items():
            if isinstance(v, Layer):
                self._check_layer_conf(f"vertex '{name}'", v)
        self._init_common(net, mesh, n_microbatches, axis, data_axis)
        try:
            self.body, self.period = partition_graph(net, self.n_stages)
            self.body_tmpl = None            # linear chain of layer vertices
        except ValueError as chain_err:
            # residual-transformer case: repeated single-input/single-output
            # SUBGRAPH blocks (skip connections inside each block)
            try:
                self.body, self.period, self.body_tmpl = \
                    partition_graph_blocks(net, self.n_stages)
            except ValueError as block_err:
                raise ValueError(
                    f"Neither pipelining rule fits this graph.\n"
                    f"- linear chain: {chain_err}\n"
                    f"- block pattern: {block_err}") from block_err
        self.body_len = len(self.body)
        self.layers_per_stage = self.body_len // self.n_stages
        self.repeats_per_stage = self.layers_per_stage // self.period
        self.body_impls = [net.impls.get(n) for n in self.body[:self.period]]
        # masks through a block body need every vertex to propagate "first
        # (non-None) input mask" — true for the default rule and Merge;
        # Stack/Unstack/Reshape transform masks and are rejected at fit time
        from ..nn.conf.graph import GraphVertexConf, MergeVertex
        self._block_masks_ok = self.body_tmpl is None or all(
            is_layer or type(conf.vertices[self.body[off]]).propagate_mask
            in (GraphVertexConf.propagate_mask, MergeVertex.propagate_mask)
            for off, (is_layer, _) in enumerate(self.body_tmpl))
        body_set = set(self.body)
        # head = everything downstream of the chain end; entry = the rest
        consumers = _graph_consumers(conf)
        reach, stack = set(), [self.body[-1]]
        while stack:
            for c in consumers.get(stack.pop(), ()):
                if c not in reach:
                    reach.add(c)
                    stack.append(c)
        self.head_names = [n for n in net.topo
                           if n in reach and n not in body_set]
        self.entry_names = [n for n in net.topo
                            if n not in reach and n not in body_set]
        self.body_input = conf.vertex_inputs[self.body[0]][0]
        from ..nn.graph import fused_softmax_skip_set
        self._skip_outputs = fused_softmax_skip_set(conf, net.impls)
        # outputs NOT downstream of the body (auxiliary heads fed from the
        # entry): loss still computed, but their params/state live in the
        # entry tree. An entry-side output with running state updates
        # (update_state, e.g. CenterLoss) cannot update exactly per
        # microbatch from the head pass — reject loudly.
        self._entry_outputs = frozenset(n for n in conf.network_outputs
                                        if n not in reach
                                        and n not in body_set)
        for n in self._entry_outputs:
            impl = net.impls.get(n)
            if (impl is not None and hasattr(impl, "update_state")
                    and jax.tree_util.tree_leaves(net.states.get(n, {}))):
                raise ValueError(
                    f"auxiliary output '{n}' on the entry side carries "
                    f"running state (update_state); train unpipelined or "
                    f"restructure so it sits downstream of the body")
        self._pipeline = spmd_pipeline(self._stage_fn, mesh, axis, data_axis,
                                       squeeze_stage=False,
                                       _needs_x_grad=True, stateful=True,
                                       with_masks=True, with_rng=True)
        self.params = self._place(self._partition_tree(net.params))
        self.states = self._place(self._partition_tree(net.states))
        self.upd_state = self._place(self.updater.init_state(self.params))

    # -- param/state layout ------------------------------------------------
    def _layer_offsets(self):
        """Body offsets that are LAYER vertices (all of them for a chain
        body; the template's layer entries for a block body) — the offsets
        that own params/state."""
        if self.body_tmpl is None:
            return list(range(self.period))
        return [off for off, (is_layer, _) in enumerate(self.body_tmpl)
                if is_layer]

    def _partition_tree(self, net_tree):
        p = self.period
        entry = {n: net_tree[n] for n in self.entry_names
                 if n in net_tree}
        head = {n: net_tree[n] for n in self.head_names if n in net_tree}
        blocks = {str(l): stack_stage_params(
            [net_tree[self.body[r * p + l]]
             for r in range(self.body_len // p)])
            for l in self._layer_offsets()
            if self.body[l] in net_tree}
        return {"entry": entry, "blocks": blocks, "head": head}

    def _to_layer_keyed(self, tree):
        p = self.period
        out = dict(tree["entry"])
        for r in range(self.body_len // p):
            for l in self._layer_offsets():
                if str(l) in tree["blocks"]:
                    out[self.body[r * p + l]] = _tm(lambda q: q[r],
                                                    tree["blocks"][str(l)])
        out.update(tree["head"])
        return out

    # -- the block-body stage ---------------------------------------------
    def _stage_fn(self, params_slice, state_slice, x, *rest):
        """Chain bodies use the shared linear stage; a BLOCK body executes
        its template sub-DAG per repeat — in-window vertices resolve their
        inputs by relative offset, the window's single external input is
        the carried activation, and only layer offsets carry stacked
        params/state."""
        if self.body_tmpl is None:
            return super()._stage_fn(params_slice, state_slice, x, *rest)
        mask, key = rest
        conf = self.net.conf
        new_state = {k: state_slice[k] for k in state_slice}
        for j in range(self.repeats_per_stage):
            vals = {}
            for off, (is_layer, rel) in enumerate(self.body_tmpl):
                xs = [x if r[0] == "ext" else vals[r[1]] for r in rel]
                name0 = self.body[off]          # template (window-0) name
                if is_layer:
                    impl = self.net.impls[name0]
                    k = jax.random.fold_in(key, j * self.period + off)
                    p_j = _tm(lambda q: q[j], params_slice[str(off)])
                    s_j = (_tm(lambda q: q[j], new_state[str(off)])
                           if str(off) in new_state else {})
                    p_n = impl.noised_params(p_j, True, k)
                    y, ns = impl.forward(p_n, s_j, xs[0], train=True,
                                         rng=k, mask=mask, ctx={})
                    if str(off) in new_state:
                        new_state[str(off)] = _tm(
                            lambda buf, v: buf.at[j].set(v),
                            new_state[str(off)], ns)
                    vals[off] = y
                else:
                    vals[off] = conf.vertices[name0].forward(xs, {})
            x = vals[self.period - 1]
        return x, new_state

    # -- forward pieces ----------------------------------------------------
    def _apply_vertices(self, names, params, states, acts, masks, ctx, key):
        """Run the given vertices (already topo-ordered) functionally over
        ``acts``; returns (acts, masks, new_states) for the sub-DAG. ``key``
        seeds per-vertex dropout/weight-noise streams (folded by position).
        ``masks`` propagates [b, T] sequence masks exactly like
        ``ComputationGraph._apply_graph`` (layers carry their single input's
        mask; vertices combine via ``propagate_mask``)."""
        from ..nn.conf.layers import Layer

        conf = self.net.conf
        new_st = dict(states)
        acts = dict(acts)
        masks = dict(masks)
        for pos, name in enumerate(names):
            if name in self._skip_outputs:
                continue
            v = conf.vertices[name]
            in_names = conf.vertex_inputs[name]
            xs = [acts[i] for i in in_names]
            if isinstance(v, Layer):
                x = xs[0]
                pre = conf.input_preprocessors.get(name)
                if pre is not None:
                    x = pre(x, ctx)
                m = masks.get(in_names[0])
                impl = self.net.impls[name]
                k = jax.random.fold_in(key, pos)
                p_n = impl.noised_params(params[name], True, k)
                y, ns = impl.forward(p_n, states[name], x,
                                     train=True, rng=k, mask=m,
                                     ctx=ctx)
                new_st[name] = ns
                acts[name] = y
                masks[name] = m
            else:
                acts[name] = v.forward(xs, ctx)
                masks[name] = v.propagate_mask(
                    [masks.get(i) for i in in_names])
        return acts, masks, new_st

    def _entry_apply(self, params, states, inputs_mb, fm_mb, keys_mb):
        """Entry sub-DAG per microbatch → stacked activations AND propagated
        masks for every entry vertex (the head may consume any of them —
        skip connections around the body). ``fm_mb``: per-network-input
        [M, mb, T] masks (or None)."""
        conf = self.net.conf
        n_in = len(conf.network_inputs)

        def step(st, xk):
            inputs, in_masks, k = xk
            acts = dict(zip(conf.network_inputs, inputs))
            masks = dict(zip(conf.network_inputs,
                             in_masks or [None] * n_in))
            ctx = {"inputs": acts, "input_masks": masks}
            acts, masks, new_st = self._apply_vertices(
                self.entry_names, params, st, acts, masks, ctx, k)
            return new_st, (acts, masks)

        if not jax.tree_util.tree_leaves(states):
            acts, masks = jax.vmap(
                lambda i, m, k: step(states, (i, m, k))[1])(
                    inputs_mb, fm_mb, keys_mb)
            return states, acts, masks
        st, (acts, masks) = lax.scan(step, states,
                                     (inputs_mb, fm_mb, keys_mb))
        return st, acts, masks

    def _head_apply(self, params, states, entry_params, entry_states,
                    entry_acts, entry_masks, feats, l_mb, lm_mb, keys_mb):
        """Head sub-DAG + the container's multi-output summed loss per
        microbatch; returns (final head state, per-microbatch losses).
        Entry-side auxiliary outputs resolve their params from
        ``entry_params`` (their state is empty — checked at construction).
        ``entry_masks``: per-microbatch propagated masks of the entry
        vertices; the body is a chain of layers so its output carries the
        body input's mask unchanged (``_apply_graph``'s layer rule)."""
        conf = self.net.conf
        impls = self.net.impls

        def step(st, xy):
            acts, in_masks, feat, labels, lmasks, key = xy
            acts = dict(acts)
            acts[self.body[-1]] = feat
            masks = dict(in_masks)
            masks[self.body[-1]] = in_masks.get(self.body_input)
            ctx = {"inputs": {k: acts.get(k) for k in conf.network_inputs},
                   "input_masks": {k: masks.get(k)
                                   for k in conf.network_inputs}}
            acts, masks, new_st = self._apply_vertices(
                self.head_names, params, st, acts, masks, ctx, key)
            total = 0.0
            for oi, (out_name, lbl) in enumerate(zip(conf.network_outputs,
                                                     labels)):
                impl = impls.get(out_name)
                if impl is None or not hasattr(impl, "loss_on"):
                    raise ValueError(f"Output vertex '{out_name}' is not an "
                                     f"output layer")
                entry_side = out_name in self._entry_outputs
                p_o = (entry_params if entry_side else params)[out_name]
                s_o = (entry_states if entry_side else st)[out_name]
                in_name = conf.vertex_inputs[out_name][0]
                x = acts[in_name]
                pre = conf.input_preprocessors.get(out_name)
                if pre is not None:
                    x = pre(x, ctx)
                # container mask rule (ComputationGraph._loss_fn): label
                # mask, else the propagated mask for sequence outputs
                lm = None if lmasks is None else lmasks[oi]
                mask = lm if lm is not None else (
                    masks.get(in_name) if x.ndim == 3 else None)
                ko = jax.random.fold_in(key, len(self.head_names) + oi)
                total = total + impl.loss_on(p_o, s_o, x, lbl, mask=mask,
                                             train=True, rng=ko)
                if not entry_side and hasattr(impl, "update_state"):
                    new_st[out_name] = impl.update_state(
                        s_o, jax.lax.stop_gradient(x), lbl)
            return new_st, total

        if not jax.tree_util.tree_leaves(states):
            return states, jax.vmap(
                lambda a, m, f, l, lm, k: step(states,
                                               (a, m, f, l, lm, k))[1])(
                    entry_acts, entry_masks, feats, l_mb, lm_mb, keys_mb)
        return lax.scan(step, states, (entry_acts, entry_masks, feats, l_mb,
                                       lm_mb, keys_mb))

    def _loss(self, tree, states, inputs_mb, labels_mb, fm_mb, lm_mb, key):
        p = self.period
        M = inputs_mb[0].shape[0]
        S = self.n_stages
        ek = jax.random.split(jax.random.fold_in(key, S), M)
        hk = jax.random.split(jax.random.fold_in(key, S + 1), M)
        entry_st, entry_acts, entry_masks = self._entry_apply(
            tree["entry"], states["entry"], inputs_mb, fm_mb, ek)
        feats, blocks_st = self._pipeline(tree["blocks"], states["blocks"],
                                          entry_acts[self.body_input],
                                          entry_masks.get(self.body_input),
                                          key)
        head_st, losses = self._head_apply(tree["head"], states["head"],
                                           tree["entry"], states["entry"],
                                           entry_acts, entry_masks, feats,
                                           labels_mb, lm_mb, hk)
        loss = jnp.mean(losses)
        reg = 0.0
        for part, names in (("entry", self.entry_names),
                            ("head", self.head_names)):
            for n in names:
                impl = self.net.impls.get(n)
                if impl is not None:
                    reg = reg + impl.regularization(tree[part][n])
        for r in range(self.body_len // p):
            for l in self._layer_offsets():
                if str(l) in tree["blocks"]:
                    reg = reg + self.body_impls[l].regularization(
                        _tm(lambda q: q[r], tree["blocks"][str(l)]))
        return loss + reg, {"entry": entry_st, "blocks": blocks_st,
                            "head": head_st}

    def _apply_constraints(self, tree):
        from ..nn.conf.dropout import apply_constraints

        def cons_of(name):
            v = self.net.conf.vertices[name]
            return getattr(v, "constraints", None) or \
                getattr(getattr(v, "inner", None), "constraints", None)

        out = {"entry": dict(tree["entry"]), "blocks": dict(tree["blocks"]),
               "head": dict(tree["head"])}
        for part in ("entry", "head"):
            for n in list(out[part]):
                cons = cons_of(n)
                if cons:
                    out[part][n] = apply_constraints(cons, out[part][n])
        for l in self._layer_offsets():
            cons = cons_of(self.body[l])
            if cons and str(l) in tree["blocks"]:
                per_rep = [apply_constraints(cons,
                                             _tm(lambda q: q[r],
                                                 tree["blocks"][str(l)]))
                           for r in range(self.body_len // self.period)]
                out["blocks"][str(l)] = stack_stage_params(per_rep)
        return out

    def fit_batch(self, inputs, labels, features_mask=None,
                  labels_mask=None):
        """One pipelined step; ``inputs``/``labels`` are tuples of arrays
        (the ComputationGraph convention) — single arrays are wrapped.
        ``features_mask``/``labels_mask``: per-input / per-output [b, T]
        sequence masks (single arrays wrapped), propagated through
        entry/body/head with ``ComputationGraph._apply_graph``'s rules and
        applied to each output loss — same semantics as the container's
        masked ``fit``. User-facing conv inputs are NCHW (the container
        boundary rule) and adapted to internal NHWC exactly like
        ``ComputationGraph.fit``."""
        def as_tuple(t):
            return None if t is None else (
                tuple(t) if isinstance(t, (tuple, list)) else (t,))

        inputs = as_tuple(inputs)
        labels = as_tuple(labels)
        fm = as_tuple(features_mask)
        lm = as_tuple(labels_mask)
        if (fm is not None or lm is not None) and not self._block_masks_ok:
            raise ValueError(
                "this pipelined body contains a vertex whose mask "
                "propagation is not the identity (Stack/Unstack/Reshape "
                "class); masked training through the block pipeline would "
                "silently diverge — train unpipelined")
        if fm is not None and len(fm) != len(self.net.conf.network_inputs):
            raise ValueError(f"features_mask needs one entry per network "
                             f"input ({len(self.net.conf.network_inputs)})")
        if lm is not None and len(lm) != len(self.net.conf.network_outputs):
            raise ValueError(f"labels_mask needs one entry per network "
                             f"output ({len(self.net.conf.network_outputs)})")
        inputs = self.net._adapt_inputs(tuple(jnp.asarray(i)
                                              for i in inputs))
        return super().fit_batch(tuple(inputs), tuple(labels), fm, lm)


def pipeline_parallel_step(net, mesh: Mesh, n_microbatches: int = 4,
                           axis: str = PIPELINE_AXIS,
                           data_axis: Optional[str] = None):
    """Container-level entry: partition ``net``'s homogeneous middle into
    GPipe stages over ``mesh[axis]`` and return a :class:`PipelinedNetwork`
    (MultiLayerNetwork) or :class:`PipelinedGraph` (ComputationGraph) ready
    to ``fit_batch``. (Reference frame: the reference has no pipeline
    parallelism at all — SURVEY.md §2.4; this is the net-new ``pp`` member
    of the dp/tp/pp/sp/ep family, reachable from BOTH real containers
    instead of hand-written block functions.)"""
    if hasattr(net.conf, "vertices"):
        return PipelinedGraph(net, mesh, n_microbatches, axis, data_axis)
    return PipelinedNetwork(net, mesh, n_microbatches, axis, data_axis)
