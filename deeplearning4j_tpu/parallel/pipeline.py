"""Pipeline parallelism: GPipe microbatch schedule over a ``pipe`` mesh axis.

Net-new capability vs the 0.9.x reference (SURVEY.md §2.4: only data
parallelism exists there), completing the mesh-axis family alongside tensor
(``parallel/tensor.py``) and sequence (``parallel/sequence.py``) parallelism.

TPU-first design (the standard XLA pipelining pattern, not a thread-per-stage
port): the S pipeline stages must be structurally identical blocks — their
parameters are STACKED on a leading stage axis and sharded across the ``pipe``
mesh axis, so each device holds 1/S of the body parameters. The whole GPipe
schedule — M microbatches flowing through S stages in M+S-1 ticks, activations
hopping stage→stage over ICI via ``ppermute`` — is ONE jitted ``lax.scan``
inside ``shard_map``. Because ``scan``/``ppermute``/``where`` are all
differentiable, reverse-mode AD of the scheduled forward IS the reverse
pipeline schedule (backward bubbles included) — no hand-written backward pass,
the exact analogue of how the containers get backprop from AD.

The homogeneous-stage constraint is the same one production TPU pipelining
makes (stacked transformer blocks); heterogeneous nets pipeline their
homogeneous middle and keep entry/head replicated, which is what
:class:`GPipe` does with its ``head_fn``.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from .sharding import pvary

PIPELINE_AXIS = "pipe"

_tm = jax.tree_util.tree_map


def spmd_pipeline(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  mesh: Mesh, axis: str = PIPELINE_AXIS,
                  data_axis: Optional[str] = None):
    """Build ``pipelined(stacked_params, xs) -> ys``.

    ``stacked_params``: pytree whose leaves carry a leading stage dim of
    extent S = mesh.shape[axis] (sharded over ``axis``). ``xs``: microbatches
    ``[M, mb, ...]``. ``stage_fn(params_slice, x) -> y`` must map ``[mb, F] →
    [mb, F]`` (same shape family every stage — the SPMD homogeneity rule).
    Returns ``ys`` ``[M, mb, ...]``, the last stage's outputs, replicated
    across ``axis``. When ``data_axis`` is given the microbatch dim stays
    sharded over it (combined DP×PP).
    """
    S = mesh.shape[axis]

    def per_device(params, xs):
        params = _tm(lambda p: p[0], params)      # [1, ...] local slice → stage
        idx = lax.axis_index(axis)
        M = xs.shape[0]
        xs = pvary(xs, (axis,))
        perm = [(j, (j + 1) % S) for j in range(S)]
        buf0 = jnp.zeros_like(xs[0])

        def tick(buf, t):
            # stage 0 ingests microbatch t (zeros once the feed is drained);
            # everyone else consumes the activation received last tick
            x_t = jnp.where(t < M, xs[jnp.minimum(t, M - 1)],
                            jnp.zeros_like(xs[0]))
            inp = jnp.where(idx == 0, x_t, buf)
            out = stage_fn(params, inp)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = lax.scan(tick, buf0, jnp.arange(M + S - 1))
        # tick t on the last stage finishes microbatch t-(S-1): ticks
        # S-1 .. M+S-2 are exactly microbatches 0..M-1
        ys = outs[S - 1:]
        ys = lax.psum(jnp.where(idx == S - 1, ys, jnp.zeros_like(ys)), axis)
        return ys

    pspec = _leading_axis_spec(axis)
    xspec = P(None, data_axis) if data_axis else P()
    return shard_map(per_device, mesh=mesh,
                     in_specs=(pspec, xspec), out_specs=xspec,
                     check_vma=False)


def _leading_axis_spec(axis: str):
    """PartitionSpec pytree-prefix: shard every leaf's leading dim."""
    return P(axis)


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of S identical pytrees along a new leading stage axis."""
    return _tm(lambda *leaves: jnp.stack(leaves), *per_stage_params)


class GPipe:
    """GPipe trainer: pipelined homogeneous body + replicated head.

    ``block_fn(block_params, x) -> x`` is one stage; ``head_fn(head_params,
    y_feats, labels) -> scalar mean loss`` closes the step. ``params`` is
    ``{"blocks": stacked-pytree [S, ...], "head": pytree}``. The jitted
    ``train_step`` does fwd + AD bwd (reverse pipeline schedule) + updater +
    apply in one XLA computation, with body params/updater-state sharded over
    ``pipe`` and the head replicated — the same whole-step-compile shape as
    the containers' ``_ensure_step``.
    """

    def __init__(self, block_fn, head_fn, mesh: Mesh, n_microbatches: int,
                 updater, axis: str = PIPELINE_AXIS,
                 data_axis: Optional[str] = None):
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no '{axis}' axis: {mesh.axis_names}")
        if data_axis is not None and data_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no '{data_axis}' axis: "
                             f"{mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.data_axis = data_axis
        self.n_microbatches = int(n_microbatches)
        self.updater = updater
        self._pipeline = spmd_pipeline(block_fn, mesh, axis, self.data_axis)
        self._head_fn = head_fn
        self._step = None

    # -- placement --------------------------------------------------------
    def block_sharding(self):
        return NamedSharding(self.mesh, P(self.axis))

    def place(self, params, upd_state=None):
        """device_put params (+ mirrored updater state) onto the mesh:
        blocks stage-sharded, head replicated."""
        repl = NamedSharding(self.mesh, P())
        blk = self.block_sharding()

        def put(tree):
            return {"blocks": _tm(lambda p: jax.device_put(p, blk),
                                  tree["blocks"]),
                    "head": _tm(lambda p: jax.device_put(p, repl),
                                tree["head"])}
        return put(params) if upd_state is None else (put(params),
                                                      put(upd_state))

    # -- the step ----------------------------------------------------------
    def _loss(self, params, x_mb, y_mb):
        feats = self._pipeline(params["blocks"], x_mb)
        # head applied per-microbatch; mean of means == global mean when
        # microbatches are equal-sized
        losses = jax.vmap(lambda f, y: self._head_fn(params["head"], f, y)
                          )(feats, y_mb)
        return jnp.mean(losses)

    def _build_step(self):
        upd = self.updater

        def step(params, upd_state, it, x, y):
            M = self.n_microbatches
            x_mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            y_mb = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            loss, grads = jax.value_and_grad(self._loss)(params, x_mb, y_mb)
            updates, new_state = upd.apply(upd_state, grads, it)
            new_params = _tm(lambda p, u: p - u, params, updates)
            return new_params, new_state, loss

        repl = NamedSharding(self.mesh, P())
        blk = self.block_sharding()
        tree_sh = {"blocks": blk, "head": repl}
        dsh = (NamedSharding(self.mesh, P(self.data_axis))
               if self.data_axis else repl)
        return jax.jit(step,
                       in_shardings=(tree_sh, tree_sh, repl, dsh, dsh),
                       out_shardings=(tree_sh, tree_sh, repl),
                       donate_argnums=(0, 1))

    def train_step(self, params, upd_state, iteration, x, y):
        """One pipelined training step. Returns (params, upd_state, loss)."""
        if self._step is None:
            self._step = self._build_step()
        it = jnp.asarray(iteration, jnp.int32)
        return self._step(params, upd_state, it, x, y)
