"""Peer-to-peer update transport for SHARED_GRADIENTS across hosts.

TPU-native replacement for the reference's Aeron UDP data plane
(``nd4j-aeron`` dependency driven from ``SharedTrainingWrapper.java:206-244``;
update frames are ``networking/messages/SilentUpdatesMessage.java`` relayed by
``networking/SilentTrainingDriver.java``). On TPU pods the *gradient*
all-reduce rides ICI inside the jitted step; this channel carries the
threshold-encoded update frames (``parallel/accumulation.py`` wire form) when
updates must cross DCN between independently-jitted slices — the situation the
reference's Ethernet-era compression was built for.

Topology: full mesh of TCP streams between N processes (N is small — one per
slice/host). Frames are length-prefixed. ``broadcast`` sends the local frame
to every peer; ``gather`` collects one frame from each peer, so a round trip
is: encode → broadcast → gather → decode+apply all — exactly the reference's
"each worker applies everyone's quantized update" semantics.
"""
from __future__ import annotations

import socket
import struct
import time
from typing import Dict, List, Sequence

from ..monitor import get_flight_recorder, get_registry, get_tracer

__all__ = ["UpdateChannel", "PeerFailedError", "send_frame", "recv_exact",
           "recv_frame"]


class PeerFailedError(ConnectionError):
    """A specific peer's connection died mid-round. ``rank`` names the
    failing process so survivors can log/evict it instead of dying on an
    anonymous socket error (the reference's Aeron layer reports the
    disconnected session id the same way)."""

    def __init__(self, rank: int, message: str):
        super().__init__(message)
        self.rank = int(rank)


# Shared length-prefixed framing (little-endian i64 length + payload). Also
# used by the streaming pub/sub layer (datasets/streaming.py) so the two wire
# formats cannot diverge.
def send_frame(sock: "socket.socket", payload: bytes):
    sock.sendall(struct.pack("<q", len(payload)) + payload)


def recv_exact(sock: "socket.socket", n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: "socket.socket"):
    """One frame, or None when the peer closed cleanly before a header."""
    try:
        header = recv_exact(sock, 8)
    except ConnectionError:
        return None
    (n,) = struct.unpack("<q", header)
    return recv_exact(sock, n)


class UpdateChannel:
    """Full-mesh, length-prefixed frame exchange between training processes.

    ``process_id``/``addresses``: this process's rank and the listen address
    of every process (index-aligned). Lower ranks accept connections from
    higher ranks; higher ranks dial lower ranks — a deterministic handshake
    with no coordinator (the reference needed a shard/client role split,
    ``VoidConfiguration`` — multi-controller symmetry removes it).
    """

    def __init__(self, process_id: int, addresses: Sequence[str],
                 timeout: float = 60.0):
        self.p = int(process_id)
        self.addrs = [(h, int(pt)) for h, pt in
                      (a.rsplit(":", 1) for a in addresses)]
        self.P = len(self.addrs)
        self._peers: Dict[int, socket.socket] = {}
        self._listener = None
        if self.P > 1:
            try:
                self._connect(timeout)
            except BaseException:
                # half-built mesh: release the listen port and any peer
                # sockets so a retrying caller can bind again immediately
                self.close()
                raise

    # ------------------------------------------------------------- handshake
    def _connect(self, timeout: float):
        host, port = self.addrs[self.p]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(self.P)
        self._listener = srv
        expected_in = [q for q in range(self.P) if q > self.p]
        expected_out = [q for q in range(self.P) if q < self.p]
        deadline = time.monotonic() + timeout
        for q in expected_out:
            while True:
                try:
                    s = socket.create_connection(self.addrs[q], timeout=2.0)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"peer {q} unreachable")
                    time.sleep(0.05)
            # the 2s timeout is for the dial only — steps can legitimately
            # take longer (compile skew, data stalls), so frames block forever
            s.settimeout(None)
            s.sendall(struct.pack("<i", self.p))
            self._peers[q] = s
        for _ in expected_in:
            srv.settimeout(max(deadline - time.monotonic(), 0.1))
            try:
                s, _ = srv.accept()
            except socket.timeout:
                missing = sorted(set(expected_in) - set(self._peers))
                raise TimeoutError(
                    f"rank {self.p}: handshake timed out after {timeout:.1f}s;"
                    f" ranks {missing} never connected") from None
            s.settimeout(None)
            q = struct.unpack("<i", recv_exact(s, 4))[0]
            self._peers[q] = s

    # ----------------------------------------------------------------- frames
    def _peer_failed(self, rank: int, op: str, exc: OSError):
        get_registry().counter(
            "transport_peer_failures_total",
            "peers that died mid-round (PeerFailedError)",
            peer=str(rank)).inc()
        # black-box record: the merged fleet timeline needs WHICH rank died
        # and during which collective, not just an exception in one log
        get_flight_recorder().record("peer_failed", rank=int(rank), op=op,
                                     local_rank=self.p, error=str(exc))
        raise PeerFailedError(
            rank, f"peer {rank} failed during {op}: {exc}") from exc

    def broadcast(self, frame: bytes):
        """Send one frame to every peer (``SilentUpdatesMessage`` fan-out).
        Per-peer wire bytes and send latency land in the monitor registry
        (``transport_bytes_total{direction="out"}`` /
        ``transport_send_ms{peer=...}``)."""
        reg = get_registry()
        header = struct.pack("<q", len(frame))
        with get_tracer().span("transport/broadcast", cat="transport",
                               bytes=len(frame), peers=len(self._peers)):
            for q in sorted(self._peers):
                s = self._peers[q]
                t0 = time.perf_counter()
                try:
                    s.sendall(header)
                    s.sendall(frame)
                except OSError as e:
                    self._peer_failed(q, "broadcast", e)
                reg.histogram("transport_send_ms",
                              "per-peer frame send latency",
                              peer=str(q)).observe(
                    (time.perf_counter() - t0) * 1e3)
                reg.counter("transport_bytes_total", "update-frame bytes "
                            "on the wire", direction="out",
                            peer=str(q)).inc(len(frame) + 8)

    def gather(self) -> List[bytes]:
        """Receive exactly one frame from every peer, rank order. A dead
        peer surfaces as :class:`PeerFailedError` naming the rank, not an
        anonymous socket error. Per-peer wait latency and received bytes
        land in the monitor registry (``transport_recv_ms`` includes the
        blocking wait for the peer — the straggler signal)."""
        reg = get_registry()
        out = []
        with get_tracer().span("transport/gather", cat="transport",
                               peers=len(self._peers)):
            for q in sorted(self._peers):
                s = self._peers[q]
                t0 = time.perf_counter()
                try:
                    (n,) = struct.unpack("<q", recv_exact(s, 8))
                    out.append(recv_exact(s, n))
                except OSError as e:
                    self._peer_failed(q, "gather", e)
                reg.histogram("transport_recv_ms",
                              "per-peer frame receive latency (incl. wait)",
                              peer=str(q)).observe(
                    (time.perf_counter() - t0) * 1e3)
                reg.counter("transport_bytes_total", "update-frame bytes "
                            "on the wire", direction="in",
                            peer=str(q)).inc(n + 8)
        return out

    def exchange(self, frame: bytes) -> List[bytes]:
        """broadcast + gather — one SHARED_GRADIENTS wire round. The send
        runs on a helper thread while this thread receives: with every rank
        sending before reading, frames larger than the kernel socket buffers
        would otherwise deadlock the full mesh pairwise."""
        import threading
        exc: List[BaseException] = []

        def send():
            try:
                self.broadcast(frame)
            except BaseException as e:  # surfaced after the join
                exc.append(e)

        t = threading.Thread(target=send, daemon=True)
        t.start()
        out = self.gather()
        t.join()
        if exc:
            raise exc[0]
        return out

    def close(self):
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.close()
