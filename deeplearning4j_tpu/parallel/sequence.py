"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Net-new capability vs the 0.9.x reference (SURVEY.md §5: "Long-context /
sequence parallelism: absent" — the reference handles long sequences only
temporally via TBPTT), made first-class here because long-context training is
a core requirement of the TPU build.

Two standard schemes over the mesh ``sequence`` axis:
 - :func:`ring_attention` — blockwise attention with online (flash-style)
   softmax; K/V blocks rotate around the ring via ``ppermute`` so every device
   sees every key block while holding only its own sequence shard. Memory per
   device is O(T/n), comm rides neighbor links (ICI-friendly).
 - :func:`ulysses_attention` — all-to-all swaps sequence sharding for head
   sharding, runs dense local attention on full sequences for h/n heads, then
   swaps back. Fewer round-trips when head count ≥ devices.

Both are exact (same math as full attention, up to fp reassociation).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map
from ..monitor.jitwatch import monitored_jit

from .mesh import record_step, require_axes
from .sharding import SEQUENCE_AXIS, pvary

_NEG = -1e30
#: within-device K/V chunk for the ring inner loop (keeps live logits at
#: [b, h, Tl, 512] no matter how long the local shard is)
_LOCAL_CHUNK = 512


def _ring_inner(q, k, v, axis: str, causal: bool, scale: float):
    """Per-device body. q,k,v: [b, Tl, h, d] local shards."""
    n = lax.psum(1, axis)
    p = lax.axis_index(axis)
    b, Tl, h, d = q.shape
    qf = q.astype(jnp.float32)
    # accumulators are device-varying state (shard_map vma typing)
    m = pvary(jnp.full((b, h, Tl), _NEG, jnp.float32), (axis,))
    l = pvary(jnp.zeros((b, h, Tl), jnp.float32), (axis,))
    acc = pvary(jnp.zeros((b, Tl, h, d), jnp.float32), (axis,))
    perm = [(j, (j + 1) % n) for j in range(n)]
    iota_q = jnp.arange(Tl)

    # local K sub-chunking: without it each ring step materializes a
    # [b, h, Tl, Tl] logits tensor — O(Tl²) memory that defeats the point of
    # sharding long sequences. Chunk the arriving K/V block so the live
    # logits stay [b, h, Tl, chunk] (flash-style blockwise softmax at BOTH
    # levels: across devices via the ring, within a device via this scan).
    # non-divisible shards fall back to one chunk (dynamic_slice clamps its
    # start, which would double-count boundary keys)
    chunk = _LOCAL_CHUNK if Tl % _LOCAL_CHUNK == 0 else Tl
    n_chunks = Tl // chunk
    iota_c = jnp.arange(chunk)

    def one_chunk(c, carry, k, v, blk):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, c * chunk, chunk, axis=1)
        vs = lax.dynamic_slice_in_dim(v, c * chunk, chunk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32)) * scale
        if causal:
            q_idx = p * Tl + iota_q               # global query positions
            k_idx = blk * Tl + c * chunk + iota_c  # global key positions
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l = l * corr + pexp.sum(axis=-1)
        acc = (acc * jnp.transpose(corr, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", pexp, vs.astype(jnp.float32)))
        return m_new, l, acc

    def body(i, carry):
        m, l, acc, k, v = carry
        blk = (p - i) % n  # which global block this device currently holds
        m, l, acc = lax.fori_loop(
            0, n_chunks, lambda c, mc: one_chunk(c, mc, k, v, blk),
            (m, l, acc))
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        return m, l, acc, k, v

    m, l, acc, k, v = lax.fori_loop(0, n, body, (m, l, acc, k, v))
    out = acc / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                   causal: bool = False):
    """Exact attention with the sequence dim sharded over ``axis``.

    q, k, v: [b, T, h, d] global arrays (T divisible by the axis size).
    Returns [b, T, h, d] with the same sharding.
    """
    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ring_inner, axis=axis, causal=causal, scale=scale),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def _seq_to_heads(x, axis):
    """Ulysses layout swap: split heads across devices, gather the full
    sequence — [b, Tl, h, d] → [b, T, h/n, d]."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x, axis):
    """Inverse of :func:`_seq_to_heads`."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _ulysses_inner(q, k, v, axis: str, causal: bool, scale: float):
    """All-to-all: [b, Tl, h, d] → [b, T, h/n, d] → local dense attention →
    back. Head count must be divisible by the axis size."""
    seq_to_heads = lambda x: _seq_to_heads(x, axis)
    heads_to_seq = lambda x: _heads_to_seq(x, axis)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = jnp.einsum("bqhd,bkhd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                      causal: bool = False):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.
    q, k, v: [b, T, h, d]; h divisible by the axis size."""
    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ulysses_inner, axis=axis, causal=causal,
                           scale=scale),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def _ulysses_flash_inner(q, k, v, axis: str, causal: bool):
    """Ulysses layout with the FLASH kernel as the local compute: after the
    sequence→heads all_to_all each device holds the FULL sequence for h/n
    heads, so ONE Pallas kernel (O(T) memory, in-kernel causal grid skip)
    replaces both the dense [T, T] logits of ``_ulysses_inner`` and the
    ring's n sequential per-block launches — 2 all_to_alls on ICI + one
    big MXU-friendly kernel. Exact; differentiable through the kernel's
    custom VJP (all_to_all is linear, no custom ring backward needed)."""
    from ..ops import flash_attention as _fa

    qh = _seq_to_heads(q, axis)
    kh = _seq_to_heads(k, axis)
    vh = _seq_to_heads(v, axis)
    out = _fa.flash_attention(qh, kh, vh, causal=causal)
    return _heads_to_seq(out.astype(q.dtype), axis)


def ulysses_flash_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                            causal: bool = False):
    """Sequence-parallel attention: Ulysses all_to_all layout + the flash
    kernel over the gathered sequence (see :func:`_ulysses_flash_inner`).
    q, k, v: [b, T, h, d]; h divisible by the axis size, T divisible by
    the flash block × axis size, head_dim ≤ 256
    (:func:`ulysses_flash_supported`)."""
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ulysses_flash_inner, axis=axis,
                           causal=bool(causal)),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    return fn(q, k, v)


def ulysses_flash_supported(T: int, n_shards: int, h: int, d: int) -> bool:
    from ..ops import flash_attention as _fa
    n = max(1, n_shards)
    return (h % n == 0 and T % n == 0 and T % _fa.MIN_BLOCK == 0 and d <= 256
            and (_fa._FORCE_INTERPRET
                 or _fa.supported(max(T, _fa.MIN_SEQ), d, 0.0, None)))


# --------------------------------------------------------------- ring-flash
def _bh(x):
    b, T, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, T, d)


def _from_bh(x, b, h):
    bh, T, d = x.shape
    return jnp.transpose(x.reshape(b, h, T, d), (0, 2, 1, 3))


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_flash_inner(q, k, v, seed, axis, causal, scale, rate):
    out, _ = _ring_flash_fwd_loop(q, k, v, seed, axis, causal, scale, rate)
    return out


def _ring_flash_fwd_loop(q, k, v, seed, axis, causal, scale, rate):
    """Per-device fwd: the Pallas flash kernel runs on each arriving K/V
    ring block (O(1) VMEM — the [Tl, Tl] logits never materialize, unlike
    ``_ring_inner``'s dense [b, h, Tl, chunk] chunks), and per-block
    (o, lse) pairs merge with the standard log-sum-exp combine. Blocks a
    causal query can't see at all are skipped via ``lax.cond`` (compute
    AND DMA): the same bubble the in-kernel causal grid skip exploits.

    ``rate`` > 0 runs attention-probability dropout IN the per-block
    kernels at GLOBAL coordinates (each ring step passes its shard
    offsets, :func:`ops.flash_attention.seed3`), so the result equals the
    single-kernel dropout over the full sequence bit-for-bit: the per-block
    kernel normalizes by its UNDROPPED block mass l_blk and the lse-combine
    weights the block by that same mass, so the dropped numerators and
    undropped denominators recombine to drop(softmax(s)) @ v globally."""
    from ..ops import flash_attention as _fa

    n = lax.psum(1, axis)
    p = lax.axis_index(axis)
    b, Tl, h, d = q.shape
    qb, kb, vb = _bh(q), _bh(k), _bh(v)
    bh = qb.shape[0]
    m_run = pvary(jnp.full((bh, Tl), _NEG, jnp.float32), (axis,))
    den = pvary(jnp.zeros((bh, Tl), jnp.float32), (axis,))
    num = pvary(jnp.zeros((bh, Tl, d), jnp.float32), (axis,))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m_run, den, num, kc, vc = carry
        blk = (p - i) % n
        s3 = (None if rate == 0.0
              else _fa.seed3(seed, p * Tl, blk * Tl))

        def diag(_):
            o, lse = _fa._fwd(qb, kc, vc, None, s3, True, scale, rate)
            return o, lse[..., 0]

        def full(_):
            o, lse = _fa._fwd(qb, kc, vc, None, s3, False, scale, rate)
            return o, lse[..., 0]

        def skip(_):
            return (jnp.zeros_like(qb),
                    jnp.full((bh, Tl), _NEG, jnp.float32))

        if causal:
            o_i, lse_i = lax.cond(
                blk == p, diag,
                lambda _: lax.cond(blk < p, full, skip, None), None)
            valid = blk <= p
        else:
            o_i, lse_i = full(None)
            valid = True
        m_new = jnp.maximum(m_run, lse_i)
        w_old = jnp.exp(m_run - m_new)
        # gate, not just exp: when every lse so far is -NEG the subtraction
        # is 0 and exp would say 1
        w_new = jnp.where(jnp.logical_and(valid, lse_i > _NEG / 2),
                          jnp.exp(lse_i - m_new), 0.0)
        num = num * w_old[..., None] + o_i.astype(jnp.float32) \
            * w_new[..., None]
        den = den * w_old + w_new
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return m_new, den, num, kc, vc

    m_run, den, num, _, _ = lax.fori_loop(0, n, body,
                                          (m_run, den, num, kb, vb))
    out = (num / jnp.maximum(den, 1e-30)[..., None]).astype(q.dtype)
    lse_tot = m_run + jnp.log(jnp.maximum(den, 1e-30))
    return _from_bh(out, b, h), (out, lse_tot)


def _ring_flash_fwd(q, k, v, seed, axis, causal, scale, rate):
    y, (out_bh, lse) = _ring_flash_fwd_loop(q, k, v, seed, axis, causal,
                                            scale, rate)
    return y, (q, k, v, seed, out_bh, lse)


def _ring_flash_bwd(axis, causal, scale, rate, res, g):
    """Ring backward: dk/dv accumulators TRAVEL WITH their k/v blocks around
    the ring (n rotations return them home); per block the shared Pallas
    backward kernels recompute probabilities from the GLOBAL lse/delta —
    and, under dropout, regenerate the forward's keep decisions from the
    same global (seed, shard-offset) coordinates — so the per-block
    gradients sum exactly to the full-attention gradient."""
    from ..ops import flash_attention as _fa

    q, k, v, seed, out_bh, lse = res
    n = lax.psum(1, axis)
    p = lax.axis_index(axis)
    b, Tl, h, d = q.shape
    qb, kb, vb = _bh(q), _bh(k), _bh(v)
    bh = qb.shape[0]
    do = _bh(g).astype(qb.dtype)
    delta = _fa.rowwise_delta(do, out_bh)
    lse8 = jnp.broadcast_to(lse[..., None], lse.shape + (8,))
    dq = pvary(jnp.zeros_like(qb), (axis,))
    dk = pvary(jnp.zeros_like(kb), (axis,))
    dv = pvary(jnp.zeros_like(vb), (axis,))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        dq, dk, dv, kc, vc = carry
        blk = (p - i) % n
        s3 = (None if rate == 0.0
              else _fa.seed3(seed, p * Tl, blk * Tl))

        def run(causal_blk):
            def f(_):
                dq_i = _fa.dq_block(qb, kc, vc, None, do, delta, lse8,
                                    causal_blk, scale, s3, rate)
                dk_i, dv_i = _fa.dkv_block(qb, kc, vc, None, do, delta,
                                           lse8, causal_blk, scale, s3,
                                           rate)
                return dq_i, dk_i, dv_i
            return f

        def skip(_):
            return (jnp.zeros_like(qb), jnp.zeros_like(kb),
                    jnp.zeros_like(vb))

        if causal:
            dq_i, dk_i, dv_i = lax.cond(
                blk == p, run(True),
                lambda _: lax.cond(blk < p, run(False), skip, None), None)
        else:
            dq_i, dk_i, dv_i = run(False)(None)
        dq = dq + dq_i.astype(dq.dtype)
        dk = dk + dk_i.astype(dk.dtype)
        dv = dv + dv_i.astype(dv.dtype)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        dk = lax.ppermute(dk, axis, perm)
        dv = lax.ppermute(dv, axis, perm)
        return dq, dk, dv, kc, vc

    dq, dk, dv, _, _ = lax.fori_loop(0, n, body, (dq, dk, dv, kb, vb))
    import numpy as _np
    dseed = _np.zeros(_np.shape(seed), jax.dtypes.float0)
    return (_from_bh(dq, b, h).astype(q.dtype),
            _from_bh(dk, b, h).astype(k.dtype),
            _from_bh(dv, b, h).astype(v.dtype),
            dseed)


_ring_flash_inner.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                         causal: bool = False, dropout_rate: float = 0.0,
                         dropout_seed=None):
    """Ring attention with the Pallas flash kernel as the per-block compute
    (round-3 VERDICT item 5: the sp path at O(T/n) HBM and O(1) VMEM —
    ``ring_attention``'s dense per-chunk logits never materialize).
    Same contract as :func:`ring_attention`; requires the local shard length
    divisible by the flash block (128) and head_dim ≤ 256 — call
    ``ring_flash_supported`` to pre-check, fall back to
    :func:`ring_attention` otherwise.

    ``dropout_rate`` > 0 applies attention-probability dropout IN the
    per-ring-block kernels at global coordinates — equal to the
    single-device flash kernel's dropout with the same ``dropout_seed``
    (int32 scalar, same on every shard), forward and backward."""
    d = q.shape[-1]
    # one dtype policy for all flash paths (widest-operand promotion +
    # DL4J_TPU_FLASH_F32 hatch): shared helper in ops.flash_attention
    from ..ops.flash_attention import normalize_operand_dtypes
    q, k, v, _out_dtype = normalize_operand_dtypes(q, k, v)
    scale = 1.0 / float(d) ** 0.5
    rate = float(dropout_rate)
    if rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs dropout_seed")
    seed = jnp.asarray(0 if dropout_seed is None else dropout_seed,
                       jnp.int32).reshape(())
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ring_flash_inner, axis=axis, causal=bool(causal),
                           scale=scale, rate=rate),
                   mesh=mesh, in_specs=(spec, spec, spec, P()),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v, seed).astype(_out_dtype)


def ring_flash_supported(T: int, n_shards: int, d: int) -> bool:
    from ..ops import flash_attention as _fa
    Tl = T // max(1, n_shards)
    return (T % max(1, n_shards) == 0 and Tl % _fa.MIN_BLOCK == 0 and d <= 256
            and (_fa._FORCE_INTERPRET
                 or _fa.supported(max(Tl, _fa.MIN_SEQ), d, 0.0, None)))


import threading

_SP_TLS = threading.local()


def current_sp_axis():
    """The sequence-parallel axis the CURRENT trace runs under, or None.
    Set (trace-scoped, try/finally) by ``sequence_parallel_step``'s device
    body — attention layers read it to route through the ring. A plain
    attribute on layer impls would leak into later output()/fit() traces
    and crash on the unbound axis name."""
    return getattr(_SP_TLS, "axis", None)


def sp_attend(q, k, v, axis: str, causal: bool, dropout_rate: float = 0.0,
              dropout_seed=None):
    """Per-device attention body for the sequence-parallel NET step: the
    flash-in-ring path when the local shard suits the kernel (128-divisible,
    head_dim ≤ 256, TPU or forced-interpret), else the dense-per-chunk ring.
    Called from ``SelfAttentionLayer.forward`` inside ``shard_map`` —
    q/k/v: [b, Tl, h, d] local shards. Attention-probability dropout
    (``dropout_rate`` > 0, replicated int32 ``dropout_seed``) runs in the
    ring-flash kernels at global coordinates; the dense-chunk fallback
    does not support it and raises at trace time when dropout is requested
    but the shard shape cannot take the flash path (shard length not
    128-divisible or head_dim > 256 — ``sequence_parallel_step`` checks
    head_dim at construction, the shard length is only known here)."""
    from ..ops import flash_attention as _fa

    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    b, Tl, h, _ = q.shape
    n = lax.psum(1, axis)            # static under shard_map
    rate = float(dropout_rate)
    if rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs dropout_seed")
    flash_ok = (Tl % _fa.MIN_BLOCK == 0 and d <= 256
                and (_fa._FORCE_INTERPRET or not _fa._interpret()))
    # dropout-free + head-divisible: Ulysses layout — 2 all_to_alls on ICI
    # and ONE full-sequence kernel beats the ring's n sequential launches
    # (dropout stays on the ring, whose global-coordinate PRNG is bit-equal
    # to the single-kernel mask; Ulysses splits heads across devices, which
    # would re-index the PRNG's batch-head coordinate)
    if rate == 0.0 and ulysses_flash_supported(Tl * n, n, h, d):
        return _ulysses_flash_inner(q, k, v, axis, causal)
    if flash_ok:
        seed = jnp.asarray(0 if dropout_seed is None else dropout_seed,
                           jnp.int32).reshape(())
        return _ring_flash_inner(q, k, v, seed, axis, causal, scale, rate)
    if rate > 0.0:
        raise ValueError(
            "attention dropout on the sp path needs the ring-flash kernel: "
            "a TPU backend (or the tests' forced interpret mode), local "
            f"shard length {Tl} divisible by {_fa.MIN_BLOCK}, and head_dim "
            f"{d} <= 256")
    return _ring_inner(q, k, v, axis=axis, causal=causal, scale=scale)


def sequence_parallel_step(net, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                           data_axis=None, donate: bool = True):
    """Container-level sequence parallelism: jit the network's train step
    with the TIME dimension of inputs/labels/masks sharded over ``axis``
    and ring(-flash) attention doing the cross-shard mixing.

    Same ``(step, place)`` contract as
    :func:`~deeplearning4j_tpu.parallel.tensor.tensor_parallel_step` —
    params/updater state replicated, per-shard gradients ``pmean``-reduced
    (equal shards ⇒ mean-of-means == the global-batch gradient, the same
    argument the loss makes), so the sp net trains numerically like the
    unsharded net.

    v1 constraints (checked loudly): MultiLayerNetwork with NO
    time-recurrent layers (LSTM scans cannot split the time dim — that is
    what TBPTT is for), no global pooling over time, no masks at step time,
    and the per-device attention is causal/dense exact via the ring. The
    reference has nothing to map here (SURVEY §5: long context is
    TBPTT-only); this is the net-new ``sp`` member completing container
    integration for all five mesh axes. ``data_axis``: optional second
    mesh axis for combined DP×SP — the batch dim shards over it and the
    gradient reduction becomes psum over time × pmean over batch.

    Works for MultiLayerNetwork AND ComputationGraph (the graph step takes
    tuples of input/label streams; every stream's time dim shards)."""
    is_graph = not hasattr(net.conf, "layers")
    if is_graph and not hasattr(net.conf, "vertices"):
        raise ValueError("sequence_parallel_step supports MultiLayerNetwork "
                         "and ComputationGraph")
    layer_items = (list(net.conf.vertices.items()) if is_graph
                   else list(enumerate(net.conf.layers)))
    _TIME_COLLAPSING = ("GlobalPoolingLayer", "LastTimeStepVertex",
                        "LastTimeStep", "ReshapeVertex",
                        "DuplicateToTimeSeriesVertex")
    for i, lc in layer_items:
        # validate the WRAPPED layer too (FrozenLayer/Bidirectional etc.
        # carry the real config on .inner)
        for cand in (lc, getattr(lc, "inner", None)):
            if cand is None:
                continue
            name = type(cand).__name__
            if name in ("LSTM", "GravesLSTM", "GravesBidirectionalLSTM",
                        "SimpleRnn", "Bidirectional"):
                raise ValueError(
                    f"layer {i} ({name}) is time-recurrent; the time dim "
                    f"cannot be sharded across devices — use TBPTT/dp for "
                    f"RNNs")
            if name in _TIME_COLLAPSING:
                raise ValueError(
                    f"layer/vertex {i} ({name}) collapses or reshapes the "
                    f"sharded time dim — per-shard results would silently "
                    f"diverge; unsupported in the sp step (v1)")
            if name == "BatchNormalization":
                raise ValueError(
                    f"layer {i} ({name}) computes train-time statistics "
                    f"over the batch AND time dims; each time shard would "
                    f"normalize with shard-local mean/var and diverge from "
                    f"the unsharded step — unsupported in the sp step (v1). "
                    f"Use LayerNormalization (per-token statistics, "
                    f"shard-invariant) instead")
            if getattr(cand, "aux_loss_weight", 0.0):
                raise ValueError(
                    f"layer {i} ({name}) has an activation-dependent aux "
                    f"loss; its token statistics do not decompose across "
                    f"time shards (v1) — set aux_loss_weight=0")
            if getattr(cand, "dropout", None) or name == "DropoutLayer":
                raise ValueError(
                    f"layer {i} ({name}) uses activation dropout; the sp "
                    f"step's replicated rng would draw the SAME mask on "
                    f"every time shard — unsupported in v1. (Attention-"
                    f"probability dropout on SelfAttentionLayer IS "
                    f"supported: it runs in the ring-flash kernels at "
                    f"global coordinates.)")
            if (getattr(cand, "dropout_rate", 0.0)
                    and name != "SelfAttentionLayer"):
                raise ValueError(
                    f"layer {i} ({name}) uses dropout_rate; only "
                    f"SelfAttentionLayer's attention-probability dropout "
                    f"is threaded through the ring in the sp step")
            if (name == "SelfAttentionLayer"
                    and getattr(cand, "dropout_rate", 0.0)):
                # same head_dim resolution as the impl (attention._dims):
                # explicit head_dim wins over n_out // num_heads
                hd = (getattr(cand, "head_dim", None)
                      or cand.n_out // max(1, cand.num_heads))
                if hd > 256:
                    raise ValueError(
                        f"layer {i}: attention dropout on the sp path runs "
                        f"in the ring-flash kernel, which needs head_dim "
                        f"<= 256 (got {hd}); drop dropout_rate or reduce "
                        f"head_dim. (The per-shard length must also be "
                        f"128-divisible — checked at step time.)")

    require_axes(mesh, (axis, data_axis), style="sequence_parallel_step")
    n_shards = mesh.shape[axis]

    # the framework's sequence losses SUM over time (mean over batch,
    # reference convention) — a time shard therefore holds an additive
    # SLICE of the loss, and the cross-shard reduction is psum. The l1/l2
    # term rides inside _loss_fn identically on every shard, so the psum
    # counts it n times; has_reg subtracts the (n-1) extra copies from
    # both the loss and its gradient (reg is param-only — cheap).
    impl_items = (list(net.impls.items()) if is_graph
                  else [(str(i), im) for i, im in enumerate(net.impls)])
    has_reg = any(getattr(impl, "l1", 0) or getattr(impl, "l2", 0)
                  or getattr(impl, "l1_bias", 0)
                  or getattr(impl, "l2_bias", 0)
                  for _, impl in impl_items)

    def reg_fn(p):
        r = 0.0
        for key, impl in impl_items:
            r = r + impl.regularization(p[key])
        return r

    def sp_reduce(grads, loss, new_states):
        grads = lax.psum(grads, axis)            # time-sliced additive loss
        loss = lax.psum(loss, axis)
        if data_axis is not None:
            # batch-mean losses: shards over the data axis average
            grads = lax.pmean(grads, data_axis)
            loss = lax.pmean(loss, data_axis)
        if has_reg:
            # the replicated l1/l2 term was psum'd n times; subtract the
            # n-1 extra copies from the loss and its gradient (param-only)
            def reg_loss(p):
                return reg_fn(p)
            reg_val, reg_grads = jax.value_and_grad(reg_loss)(
                _sp_reduce_params[0])
            extra = n_shards - 1
            grads = jax.tree_util.tree_map(
                lambda g, rg: g - extra * rg, grads, reg_grads)
            loss = loss - extra * reg_val
        # allowed layers are stateless today; pmean keeps any future
        # float state replicated-consistent rather than silently racy
        new_states = lax.pmean(new_states, axis)
        if data_axis is not None:
            new_states = lax.pmean(new_states, data_axis)
        return grads, loss, new_states

    _sp_reduce_params = [None]                  # closed over by sp_reduce
    core = net._raw_update_core(grads_reduce=sp_reduce)

    # [b, T] token-id streams (TransformerLM-style) ARE temporal on dim 1,
    # so the P(data, time) prefix shards them correctly — detect them from
    # the config: an input whose every consumer is an EmbeddingSequenceLayer
    # carries ids. (Everything else rank-2 stays rejected: a [b, F] static
    # stream would silently get its FEATURE dim sharded.)
    if is_graph:
        consumers = {}
        for name, ins in net.conf.vertex_inputs.items():
            for i_name in ins:
                consumers.setdefault(i_name, []).append(name)
        id_inputs = set()
        for i_idx, i_name in enumerate(net.conf.network_inputs):
            cons = consumers.get(i_name, [])
            if cons and all(type(net.conf.vertices[c]).__name__
                            == "EmbeddingSequenceLayer" for c in cons):
                id_inputs.add(i_idx)
    else:
        id_inputs = ({0} if type(net.conf.layers[0]).__name__
                     == "EmbeddingSequenceLayer" else set())

    def device_step(params, states, upd, it, rng, f, l):
        # every stream must be [b, T, ...] — except declared id streams,
        # which are [b, T]: the time-dim spec is a pytree prefix, so any
        # OTHER rank-2 stream would silently get its feature dim sharded
        f_streams = tuple(f) if isinstance(f, (tuple, list)) else (f,)
        for si, leaf in enumerate(f_streams):
            if leaf.ndim < 3 and not (leaf.ndim == 2 and si in id_inputs):
                raise ValueError(
                    f"sp step streams must be rank-3 [b, T, ...] (got shape "
                    f"{leaf.shape}); static side-inputs are unsupported in "
                    f"v1 ([b, T] is accepted only for token-id inputs "
                    f"feeding EmbeddingSequenceLayer)")
        for leaf in jax.tree_util.tree_leaves(l):
            if leaf.ndim < 3:
                raise ValueError(
                    f"sp step labels must be rank-3 [b, T, ...] (got shape "
                    f"{leaf.shape}); non-temporal labels are unsupported "
                    f"in v1")
        # trace-scoped routing flag for SelfAttentionLayer (see
        # current_sp_axis): set only while THIS body traces, so later
        # output()/fit() traces keep the dense path
        _sp_reduce_params[0] = params
        _SP_TLS.axis = axis
        try:
            updates, new_states, new_upd, loss, _ = core(
                params, states, upd, it, rng, f, l, None, None)
        finally:
            _SP_TLS.axis = None
            _sp_reduce_params[0] = None
        new_params = jax.tree_util.tree_map(
            lambda p, u: p - u.astype(p.dtype), params, updates)
        new_params = net._apply_constraints(new_params)
        return new_params, new_states, new_upd, loss

    repl = P()
    tsh = P(data_axis, axis)          # [b, T, F]: batch × time sharded
    record_step("sequence/step", mesh, {"inputs": tsh})
    fn = shard_map(device_step, mesh=mesh,
                   in_specs=(repl, repl, repl, repl, repl, tsh, tsh),
                   out_specs=(repl, repl, repl, repl),
                   check_vma=False)
    step = monitored_jit(fn, name="sequence/step",
                         donate_argnums=(0, 2) if donate else ())

    def place(model):
        r = NamedSharding(mesh, P())
        model.params = jax.device_put(model.params, r)
        model.states = jax.device_put(model.states, r)
        model.updater_state = jax.device_put(model.updater_state, r)

    return step, place


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference (testing oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / float(d) ** 0.5
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
