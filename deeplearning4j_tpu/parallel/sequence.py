"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Net-new capability vs the 0.9.x reference (SURVEY.md §5: "Long-context /
sequence parallelism: absent" — the reference handles long sequences only
temporally via TBPTT), made first-class here because long-context training is
a core requirement of the TPU build.

Two standard schemes over the mesh ``sequence`` axis:
 - :func:`ring_attention` — blockwise attention with online (flash-style)
   softmax; K/V blocks rotate around the ring via ``ppermute`` so every device
   sees every key block while holding only its own sequence shard. Memory per
   device is O(T/n), comm rides neighbor links (ICI-friendly).
 - :func:`ulysses_attention` — all-to-all swaps sequence sharding for head
   sharding, runs dense local attention on full sequences for h/n heads, then
   swaps back. Fewer round-trips when head count ≥ devices.

Both are exact (same math as full attention, up to fp reassociation).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from .sharding import SEQUENCE_AXIS, pvary

_NEG = -1e30
#: within-device K/V chunk for the ring inner loop (keeps live logits at
#: [b, h, Tl, 512] no matter how long the local shard is)
_LOCAL_CHUNK = 512


def _ring_inner(q, k, v, axis: str, causal: bool, scale: float):
    """Per-device body. q,k,v: [b, Tl, h, d] local shards."""
    n = lax.psum(1, axis)
    p = lax.axis_index(axis)
    b, Tl, h, d = q.shape
    qf = q.astype(jnp.float32)
    # accumulators are device-varying state (shard_map vma typing)
    m = pvary(jnp.full((b, h, Tl), _NEG, jnp.float32), (axis,))
    l = pvary(jnp.zeros((b, h, Tl), jnp.float32), (axis,))
    acc = pvary(jnp.zeros((b, Tl, h, d), jnp.float32), (axis,))
    perm = [(j, (j + 1) % n) for j in range(n)]
    iota_q = jnp.arange(Tl)

    # local K sub-chunking: without it each ring step materializes a
    # [b, h, Tl, Tl] logits tensor — O(Tl²) memory that defeats the point of
    # sharding long sequences. Chunk the arriving K/V block so the live
    # logits stay [b, h, Tl, chunk] (flash-style blockwise softmax at BOTH
    # levels: across devices via the ring, within a device via this scan).
    # non-divisible shards fall back to one chunk (dynamic_slice clamps its
    # start, which would double-count boundary keys)
    chunk = _LOCAL_CHUNK if Tl % _LOCAL_CHUNK == 0 else Tl
    n_chunks = Tl // chunk
    iota_c = jnp.arange(chunk)

    def one_chunk(c, carry, k, v, blk):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, c * chunk, chunk, axis=1)
        vs = lax.dynamic_slice_in_dim(v, c * chunk, chunk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32)) * scale
        if causal:
            q_idx = p * Tl + iota_q               # global query positions
            k_idx = blk * Tl + c * chunk + iota_c  # global key positions
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l = l * corr + pexp.sum(axis=-1)
        acc = (acc * jnp.transpose(corr, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", pexp, vs.astype(jnp.float32)))
        return m_new, l, acc

    def body(i, carry):
        m, l, acc, k, v = carry
        blk = (p - i) % n  # which global block this device currently holds
        m, l, acc = lax.fori_loop(
            0, n_chunks, lambda c, mc: one_chunk(c, mc, k, v, blk),
            (m, l, acc))
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        return m, l, acc, k, v

    m, l, acc, k, v = lax.fori_loop(0, n, body, (m, l, acc, k, v))
    out = acc / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                   causal: bool = False):
    """Exact attention with the sequence dim sharded over ``axis``.

    q, k, v: [b, T, h, d] global arrays (T divisible by the axis size).
    Returns [b, T, h, d] with the same sharding.
    """
    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ring_inner, axis=axis, causal=causal, scale=scale),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def _ulysses_inner(q, k, v, axis: str, causal: bool, scale: float):
    """All-to-all: [b, Tl, h, d] → [b, T, h/n, d] → local dense attention →
    back. Head count must be divisible by the axis size."""

    def seq_to_heads(x):
        # split heads across devices, gather full sequence
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = jnp.einsum("bqhd,bkhd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = SEQUENCE_AXIS,
                      causal: bool = False):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention.
    q, k, v: [b, T, h, d]; h divisible by the axis size."""
    d = q.shape[-1]
    scale = 1.0 / float(d) ** 0.5
    spec = P(None, axis, None, None)
    fn = shard_map(partial(_ulysses_inner, axis=axis, causal=causal,
                           scale=scale),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference (testing oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / float(d) ** 0.5
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
