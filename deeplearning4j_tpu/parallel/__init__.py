"""Parallelism & distribution (reference ``deeplearning4j-scaleout/``,
SURVEY.md §2.4): the unified mesh substrate (``mesh.py`` — MeshSpec
validation/auto-factorization, partition-spec machinery, the /profile
topology registry), ParallelWrapper (sync + local-SGD data parallelism,
DP × TP composition via ``.tensor_parallel()``, ZeRO via
``.weight_update_sharding()``/``.fsdp()`` on any mesh's data axis),
ParallelInference, gradient accumulation/encoding, TrainingMaster SPI
with the collective masters, plus TPU-first extensions completing the
mesh-axis family: tensor (``model``), sequence (ring/Ulysses), pipeline
(GPipe over ``pipe``) and expert (MoE over ``expert``) parallelism.
See docs/PARALLELISM.md "Unified mesh substrate"."""
from .mesh import (MeshSpec, mesh_block, require_axes, zero_update_specs)
from .sharding import (DATA_AXIS, MODEL_AXIS, SEQUENCE_AXIS, make_mesh,
                       replicated, batch_sharded, shard_batch,
                       data_parallel_step)
from .wrapper import ParallelWrapper, TrainingMode
from .inference import ParallelInference, InferenceMode
from .accumulation import (GradientsAccumulator, EncodedGradientsAccumulator,
                           EncodingHandler, threshold_encode, threshold_decode,
                           serialize_encoded, deserialize_encoded)
from .transport import UpdateChannel, PeerFailedError
from .distributed import (ProcessLocalIterator, is_chief,
                          TrainingMaster, ParameterAveragingTrainingMaster,
                          SharedTrainingMaster, SharedGradientsClusterTrainer,
                          DistributedMultiLayerNetwork,
                          DistributedComputationGraph, SparkDl4jMultiLayer,
                          SparkComputationGraph, initialize_distributed,
                          allgather_objects, DistributedDataSetLossCalculator,
                          DistributedEarlyStoppingTrainer)
from .sequence import (ring_attention, ulysses_attention, full_attention,
                       ring_flash_attention, ring_flash_supported,
                       ulysses_flash_attention, ulysses_flash_supported,
                       sequence_parallel_step)
from .tensor import megatron_rules, tensor_parallel_step, param_shardings
from .pipeline import (PIPELINE_AXIS, GPipe, spmd_pipeline,
                       PipelinedNetwork, PipelinedGraph,
                       pipeline_parallel_step,
                       partition_network, partition_graph,
                       stack_stage_params)
from .expert import EXPERT_AXIS, expert_rules, expert_parallel_step

__all__ = [
    "MeshSpec", "mesh_block", "require_axes", "zero_update_specs",
    "DATA_AXIS", "MODEL_AXIS", "SEQUENCE_AXIS", "make_mesh", "replicated",
    "batch_sharded", "shard_batch", "data_parallel_step",
    "ParallelWrapper", "TrainingMode", "ParallelInference", "InferenceMode",
    "GradientsAccumulator", "EncodedGradientsAccumulator", "EncodingHandler",
    "threshold_encode", "threshold_decode", "serialize_encoded",
    "deserialize_encoded", "UpdateChannel", "PeerFailedError",
    "SharedGradientsClusterTrainer",
    "TrainingMaster", "ParameterAveragingTrainingMaster", "SharedTrainingMaster",
    "DistributedMultiLayerNetwork", "DistributedComputationGraph",
    "SparkDl4jMultiLayer", "SparkComputationGraph", "initialize_distributed",
    "ProcessLocalIterator", "is_chief",
    "ring_attention", "ulysses_attention", "full_attention",
    "ulysses_flash_attention", "ulysses_flash_supported",
    "ring_flash_attention", "ring_flash_supported",
    "sequence_parallel_step",
    "megatron_rules", "tensor_parallel_step", "param_shardings",
    "PIPELINE_AXIS", "GPipe", "spmd_pipeline", "stack_stage_params",
    "PipelinedNetwork", "PipelinedGraph", "pipeline_parallel_step",
    "partition_network", "partition_graph",
    "EXPERT_AXIS", "expert_rules", "expert_parallel_step",
    "allgather_objects", "DistributedDataSetLossCalculator",
    "DistributedEarlyStoppingTrainer",
]
