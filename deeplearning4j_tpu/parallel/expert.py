"""Expert parallelism: shard MoE expert parameters over an ``expert`` axis.

Net-new vs the 0.9.x reference, completing the dp/tp/pp/sp/ep mesh-axis
family. An :class:`~deeplearning4j_tpu.nn.conf.layers.MoEDenseLayer` keeps
its experts on a leading array axis (``W: [E, n_in, n_out]`` —
``nn/layers/moe.py``); expert parallelism is therefore *just a sharding
rule*: annotate that axis over the mesh ``expert`` dim and jit the SAME
train step — XLA partitions the per-expert einsums so each device holds and
computes only its expert shard, and the gate-weighted combine's expert-dim
reduction lowers to a psum over ICI. Composes with data parallelism by
adding a ``data`` mesh axis (batch sharded, experts replicated across it).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from jax.sharding import Mesh, PartitionSpec as P

from .mesh import EXPERT_AXIS
from .tensor import tensor_parallel_step


def expert_rules(net, axis: str = EXPERT_AXIS) -> Dict[str, P]:
    """{param-path regex: PartitionSpec} sharding every MoE layer's expert
    dim; the router (``Wg``) stays replicated (it is tiny and every token
    needs it)."""
    rules: Dict[str, P] = {}
    layers = getattr(net.conf, "layers", None)
    if layers is not None:  # MultiLayerNetwork
        it = [(str(i), l) for i, l in enumerate(layers)]
    else:  # ComputationGraph: vertices map name → Layer config (or vertex)
        it = list(net.conf.vertices.items())
    for key, layer in it:
        if type(layer).__name__ == "MoEDenseLayer":
            k = re.escape(key)  # CG vertex names may hold regex metachars
            rules[rf"^{k}/W$"] = P(axis, None, None)
            rules[rf"^{k}/b$"] = P(axis, None)
    return rules


def expert_parallel_step(net, mesh: Mesh,
                         extra_rules: Optional[Dict[str, P]] = None):
    """Jit the network's train step with expert shardings (+DP over ``data``
    when that axis is present). Returns ``(step, place)`` like
    :func:`~deeplearning4j_tpu.parallel.tensor.tensor_parallel_step`, whose
    machinery (updater-state mirroring, placement) is reused — EP is a rules
    preset, not a different engine."""
    rules = expert_rules(net)
    if extra_rules:
        rules.update(extra_rules)
    return tensor_parallel_step(net, mesh, rules=rules)
