"""ParallelInference: multi-device inference serving.

TPU-native equivalent of reference ``ParallelInference.java:32``
(``InferenceMode.SEQUENTIAL/BATCHED`` ``inference/InferenceMode.java:7-8``,
``observers/BatchedInferenceObservable.java``): instead of per-device model
replicas fed by observer threads, ONE jitted forward with the batch dim sharded
over the mesh serves every device; BATCHED mode keeps the reference's
accumulate-then-flush behavior for many small concurrent requests.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .sharding import DATA_AXIS, make_mesh, replicated, batch_sharded
from ..monitor.jitwatch import monitored_jit

class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"
    INPLACE = "inplace"


class ParallelInference:
    class Builder:
        def __init__(self, net):
            self._net = net
            self._mode = InferenceMode.BATCHED
            self._batch_limit = 64
            self._queue_limit = 64
            self._workers = None

        def inference_mode(self, mode):
            self._mode = mode
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n):
            self._batch_limit = int(n)
            return self

        batchLimit = batch_limit

        def queue_limit(self, n):
            self._queue_limit = int(n)
            return self

        queueLimit = queue_limit

        def workers(self, n):
            self._workers = int(n)
            return self

        def build(self):
            return ParallelInference(self._net, mode=self._mode,
                                     batch_limit=self._batch_limit,
                                     queue_limit=self._queue_limit,
                                     workers=self._workers)

    def __init__(self, net, mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 64, queue_limit: int = 64,
                 workers: Optional[int] = None, mesh=None,
                 flush_after_ms: float = 10.0):
        self.net = net
        devices = jax.devices()
        if workers is not None and workers < len(devices):
            devices = devices[:workers]
        self.mesh = mesh if mesh is not None else make_mesh(devices,
                                                            axes=(DATA_AXIS,))
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.mode = mode
        self.batch_limit = batch_limit
        self.queue_limit = queue_limit
        self.flush_after_ms = float(flush_after_ms)
        self._jit_fwd = None
        from ..monitor.lockwatch import make_lock
        self._lock = make_lock("ParallelInference._lock")
        self._pending: List = []  # (features, future)
        self._flush_timer = None

    # ------------------------------------------------------------------
    def _forward(self, x):
        """Sharded forward: pad the batch to a device multiple, run one SPMD
        forward, strip padding."""
        net = self.net
        if self._jit_fwd is None:
            def fwd(params, states, f):
                f = net._adapt_input(f)
                y, _, _ = net._apply_layers(params, states, f, None, False, None)
                return y
            repl = replicated(self.mesh)
            data = batch_sharded(self.mesh)
            self._jit_fwd = monitored_jit(
                fwd, name="inference/fwd",
                in_shardings=(repl, repl, data), out_shardings=data)
            net.params = jax.device_put(net.params, repl)
            net.states = jax.device_put(net.states, repl)
        b = x.shape[0]
        pad = (-b) % self.n_devices
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        xs = jax.device_put(jnp.asarray(x), batch_sharded(self.mesh))
        y = np.asarray(self._jit_fwd(self.net.params, self.net.states, xs))
        return y[:b]

    def output(self, x):
        """Synchronous inference (reference ``output``). SEQUENTIAL mode runs
        the request immediately; BATCHED coalesces concurrent ``submit``s —
        a direct ``output`` call always flushes."""
        x = np.asarray(x, np.float32)
        if self.mode == InferenceMode.BATCHED:
            self.flush()
        return self._forward(x)

    # ----------------------------------------------------- async batched path
    def submit(self, x) -> Future:
        """Queue a request; BATCHED mode flushes when ``batch_limit`` examples
        accumulate, or after ``flush_after_ms`` so a lone partial batch never
        starves (reference BatchedInferenceObservable drains whatever is
        queued)."""
        x = np.asarray(x, np.float32)
        fut: Future = Future()
        with self._lock:
            self._pending.append((x, fut))
            total = sum(arr.shape[0] for arr, _ in self._pending)
            if (self.mode != InferenceMode.BATCHED
                    or total >= self.batch_limit
                    or len(self._pending) >= self.queue_limit):
                pending, self._pending = self._pending, []
                self._cancel_timer_locked()
            else:
                pending = None
                if self._flush_timer is None:
                    self._flush_timer = threading.Timer(
                        self.flush_after_ms / 1e3, self.flush)
                    self._flush_timer.daemon = True
                    self._flush_timer.start()
        if pending:
            self._run_batch(pending)
        return fut

    def _cancel_timer_locked(self):
        if self._flush_timer is not None:
            self._flush_timer.cancel()
            self._flush_timer = None

    def flush(self):
        with self._lock:
            pending, self._pending = self._pending, []
            self._cancel_timer_locked()
        if pending:
            self._run_batch(pending)

    def _run_batch(self, pending):
        xs = np.concatenate([p for p, _ in pending], axis=0)
        try:
            ys = self._forward(xs)
            pos = 0
            for x, fut in pending:
                n = x.shape[0]
                fut.set_result(ys[pos:pos + n])
                pos += n
        except Exception as e:
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
