"""ParallelInference: multi-device inference serving.

TPU-native equivalent of reference ``ParallelInference.java:32``
(``InferenceMode.SEQUENTIAL/BATCHED`` ``inference/InferenceMode.java:7-8``,
``observers/BatchedInferenceObservable.java``): instead of per-device model
replicas fed by observer threads, ONE jitted forward with the batch dim sharded
over the mesh serves every device; BATCHED mode keeps the reference's
accumulate-then-flush behavior for many small concurrent requests.

The BATCHED scheduling (accumulate, flush on batch/queue limits, max-linger
timeout so a lone request is never stranded, graceful drain on ``close``)
is delegated to the serving tier's
:class:`~deeplearning4j_tpu.serving.batcher.ContinuousBatcher` — one
scheduler implementation for both this API and the HTTP front door
(docs/SERVING.md). The previous ad-hoc per-batch ``threading.Timer``
linger is gone: a single scheduler thread owns flush timing, so
concurrent fills and timer callbacks can no longer race each other into
duplicate jit-wrapper construction.
"""
from __future__ import annotations

from concurrent.futures import Future
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .mesh import record_step
from .sharding import DATA_AXIS, make_mesh, replicated, batch_sharded
from ..monitor.jitwatch import monitored_jit

class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"
    INPLACE = "inplace"


class ParallelInference:
    class Builder:
        def __init__(self, net):
            self._net = net
            self._mode = InferenceMode.BATCHED
            self._batch_limit = 64
            self._queue_limit = 64
            self._workers = None
            self._flush_after_ms = 10.0

        def inference_mode(self, mode):
            self._mode = mode
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n):
            self._batch_limit = int(n)
            return self

        batchLimit = batch_limit

        def queue_limit(self, n):
            self._queue_limit = int(n)
            return self

        queueLimit = queue_limit

        def workers(self, n):
            self._workers = int(n)
            return self

        def flush_after_ms(self, ms):
            """Max-linger for a partial batch (reference
            ``BatchedInferenceObservable`` drains whatever is queued)."""
            self._flush_after_ms = float(ms)
            return self

        flushAfterMs = flush_after_ms

        def build(self):
            return ParallelInference(self._net, mode=self._mode,
                                     batch_limit=self._batch_limit,
                                     queue_limit=self._queue_limit,
                                     workers=self._workers,
                                     flush_after_ms=self._flush_after_ms)

    def __init__(self, net, mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 64, queue_limit: int = 64,
                 workers: Optional[int] = None, mesh=None,
                 flush_after_ms: float = 10.0):
        self.net = net
        devices = jax.devices()
        if workers is not None and workers < len(devices):
            devices = devices[:workers]
        self.mesh = mesh if mesh is not None else make_mesh(devices,
                                                            axes=(DATA_AXIS,))
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self.mode = mode
        self.batch_limit = batch_limit
        self.queue_limit = queue_limit
        self.flush_after_ms = float(flush_after_ms)
        self._jit_fwd = None
        from ..monitor.lockwatch import make_lock
        self._lock = make_lock("ParallelInference._lock")
        self._batcher = None      # lazy: built on the first BATCHED submit

    # ------------------------------------------------------------------
    def _forward(self, x):
        """Sharded forward: pad the batch to a device multiple, run one SPMD
        forward, strip padding."""
        net = self.net
        with self._lock:
            # under the lock: output() callers and the batching scheduler
            # may race the first forward — exactly one builds the wrapper
            if self._jit_fwd is None:
                def fwd(params, states, f):
                    f = net._adapt_input(f)
                    y, _, _ = net._apply_layers(params, states, f, None,
                                                False, None)
                    return y
                repl = replicated(self.mesh)
                data = batch_sharded(self.mesh)
                record_step("inference/fwd", self.mesh, {"batch": data})
                self._jit_fwd = monitored_jit(
                    fwd, name="inference/fwd",
                    in_shardings=(repl, repl, data), out_shardings=data)
                net.params = jax.device_put(net.params, repl)
                net.states = jax.device_put(net.states, repl)
        b = x.shape[0]
        pad = (-b) % self.n_devices
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        xs = jax.device_put(jnp.asarray(x), batch_sharded(self.mesh))
        y = np.asarray(self._jit_fwd(self.net.params, self.net.states, xs))
        return y[:b]

    def output(self, x):
        """Synchronous inference (reference ``output``). SEQUENTIAL mode runs
        the request immediately; BATCHED coalesces concurrent ``submit``s —
        a direct ``output`` call always flushes."""
        x = np.asarray(x, np.float32)
        if self.mode == InferenceMode.BATCHED:
            self.flush()
        return self._forward(x)

    # ----------------------------------------------------- async batched path
    def _ensure_batcher(self):
        with self._lock:
            if self._batcher is None:
                from ..serving.batcher import ContinuousBatcher
                # queue_policy="flush": hitting batch_limit examples or
                # queue_limit requests forces a flush (the reference
                # semantics) rather than rejecting — admission control
                # with 429s is the HTTP tier's job, not this API's
                # device_path=False: _forward pads to the DEVICE MULTIPLE
                # and device_puts with the mesh's batch sharding itself —
                # the batcher's single-device resident path would only
                # add a host round-trip in front of that
                self._batcher = ContinuousBatcher(
                    self._forward, name="parallel-inference",
                    max_batch=self.batch_limit,
                    max_queue_examples=None,
                    max_queue_requests=self.queue_limit,
                    linger_ms=self.flush_after_ms,
                    queue_policy="flush", device_path=False)
            return self._batcher

    def submit(self, x) -> Future:
        """Queue a request; BATCHED mode flushes when ``batch_limit``
        examples or ``queue_limit`` requests accumulate, or after
        ``flush_after_ms`` so a lone partial batch is never stranded
        (reference BatchedInferenceObservable drains whatever is queued).
        Scheduling runs on the shared continuous-batching scheduler
        (``serving/batcher.py``)."""
        x = np.asarray(x, np.float32)
        if self.mode != InferenceMode.BATCHED:
            fut: Future = Future()
            try:
                fut.set_result(self._forward(x))
            except Exception as e:
                fut.set_exception(e)
            return fut
        return self._ensure_batcher().submit(x)

    def flush(self):
        """Force everything queued to run now; returns once the queue is
        drained (a direct ``output`` call relies on that ordering)."""
        if self._batcher is not None:
            self._batcher.flush(wait=True)

    def close(self, drain: bool = True):
        """Stop the batching scheduler; ``drain=True`` serves every
        already-submitted request first."""
        with self._lock:
            batcher, self._batcher = self._batcher, None
        if batcher is not None:
            batcher.close(drain=drain)
