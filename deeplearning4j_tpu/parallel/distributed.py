"""Distributed training: TrainingMaster SPI + multi-host collective design.

TPU-native equivalent of the reference's Spark layer (SURVEY.md §2.4):
``TrainingMaster`` SPI (``spark/dl4j-spark/.../spark/api/TrainingMaster.java:28``),
``ParameterAveragingTrainingMaster`` (sync DP, ``impl/paramavg/...:308``),
``SharedTrainingMaster`` (async quantized gradient sharing over Aeron,
``dl4j-spark-parameterserver/.../SharedTrainingMaster.java:55``) and the
user-facing ``SparkDl4jMultiLayer`` facade (``impl/multilayer/...:214``).

Architecture shift: the reference's control plane (driver serializes the model
to executors each averaging round; Aeron UDP data plane for encoded updates)
collapses into JAX's multi-controller SPMD model — every host runs the SAME
program, ``jax.distributed.initialize`` forms the cluster, the global mesh
spans hosts, and the gradient ``psum`` rides ICI within a slice and DCN across
slices. There is no parameter broadcast step: compiled-once params live
sharded/replicated on device. The TrainingMaster seam is retained so user code
written against the reference's API maps 1:1.

Multi-host bring-up (real cluster):
    jax.distributed.initialize(coordinator_address, num_processes, process_id)
    master = ParameterAveragingTrainingMaster(batch_size_per_worker=...,
                                              averaging_frequency=1)
    DistributedMultiLayerNetwork(net, master).fit(iterator)
Single-process testing uses the same code on a virtual device mesh.
"""
from __future__ import annotations

import logging
from typing import Optional

import numpy as np
import jax

from .sharding import DATA_AXIS, make_mesh
from ..monitor.jitwatch import monitored_jit
from .wrapper import ParallelWrapper, TrainingMode
from .accumulation import EncodedGradientsAccumulator

log = logging.getLogger(__name__)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           heartbeat_timeout_s: Optional[int] = None,
                           initialization_timeout_s: Optional[int] = None):
    """Form the multi-host cluster (replaces the reference's
    ``VoidParameterServer.init`` Aeron mesh handshake,
    ``SharedTrainingMaster.java:469``). No-op when single-process.

    On the CPU backend (tests / virtual clusters) cross-process collectives
    need the gloo transport — configured automatically when available.

    FAILURE SEMANTICS: the cluster is fate-shared, like the reference's
    Spark stage — there is no in-framework elastic recovery (SURVEY.md §5:
    the reference's only failure handling is RDD-lineage retry OUTSIDE the
    training step). What the framework guarantees is DETECTION, not
    resurrection: when a peer dies, the coordination service notices within
    ``heartbeat_timeout_s`` (the barrier/collective path raises a
    distributed-runtime error naming the dead/timed-out peer) and survivors
    FAIL CLEANLY instead of hanging — catch the error, checkpoint if
    appropriate, and let the job scheduler relaunch the whole cluster
    (resume via ``ModelSerializer`` exact-restore). Lower
    ``heartbeat_timeout_s`` (default 100 s upstream) to shrink
    detection latency; see ``tests/test_multiprocess.py``
    ``test_killed_worker_fails_cleanly`` for the pinned behavior."""
    if coordinator_address is None:
        return False
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        # TPU backends use ICI/DCN natively — but log the skip so a
        # renamed config flag can't silently disable CPU collectives
        log.debug("gloo CPU-collectives config not applied", exc_info=True)
    kw = {}
    if heartbeat_timeout_s is not None:
        kw["heartbeat_timeout_seconds"] = int(heartbeat_timeout_s)
    if initialization_timeout_s is not None:
        kw["initialization_timeout"] = int(initialization_timeout_s)
    import inspect
    supported = set(inspect.signature(jax.distributed.initialize).parameters)
    dropped = sorted(set(kw) - supported)
    if dropped:  # older jax: runtime defaults apply (detection still works,
        # just at the stock heartbeat cadence)
        log.warning("jax.distributed.initialize does not support %s on this "
                    "jax version; using runtime defaults", dropped)
        kw = {k: v for k, v in kw.items() if k in supported}
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)
    return True


def is_chief() -> bool:
    """True on the coordinator process (host 0) — checkpointing, listener
    output and UI posting are gated on this so N hosts don't write N copies
    (the reference's Spark driver/executor role split)."""
    return jax.process_index() == 0


class ProcessLocalIterator:
    """Round-robins a shared data stream across processes: process ``p`` of
    ``P`` keeps batches ``p, p+P, p+2P, ...`` — the multi-controller
    equivalent of the reference's per-executor RDD partition feeding
    (``VirtualDataSetIterator``; fixes the naive every-host-feeds-everything
    double-feed). The stream is truncated to a multiple of ``P`` batches so
    every process sees the same number of steps (collective schedules must
    match)."""

    def __init__(self, iterator, process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 drop_remainder: bool = True):
        self.it = iterator
        self.p = jax.process_index() if process_index is None else process_index
        self.P = jax.process_count() if process_count is None else process_count
        # training needs equal step counts on every process (collective
        # schedules must match) → drop the final partial window; evaluation/
        # scoring has no per-batch collective, so the tail is kept and
        # assigned to the low-indexed processes (full-stream metrics)
        self.drop_remainder = drop_remainder

    def __iter__(self):
        # rolling window of P batches — never materializes the stream
        chunk = []
        for b in self.it:
            chunk.append(b)
            if len(chunk) == self.P:
                yield chunk[self.p]
                chunk = []
        if chunk and not self.drop_remainder and self.p < len(chunk):
            yield chunk[self.p]

    def reset(self):
        if hasattr(self.it, "reset"):
            self.it.reset()

    def async_supported(self):
        return False


class TrainingMaster:
    """SPI (reference ``TrainingMaster.java:28``): how distributed fitting is
    executed. Implementations configure mesh + step strategy.

    Implementations: :class:`ParameterAveragingTrainingMaster` (fused sync
    all-reduce), :class:`SharedTrainingMaster` (async quantized sharing —
    full-mesh ``UpdateChannel`` across hosts), and
    ``deeplearning4j_tpu.paramserver.ParameterServerTrainingMaster``
    (server-mediated async push/pull with bounded staleness — the mode where
    a worker can die and rejoin without taking down training)."""

    def execute_training(self, net, iterator):
        raise NotImplementedError

    executeTraining = execute_training


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Sync DP (reference ``ParameterAveragingTrainingMaster``): averaging
    every iteration == fused gradient all-reduce; ``averaging_frequency > 1``
    == local SGD with periodic param+updater averaging. ``aggregation_depth``
    (the reference's tree-aggregation knob) is obsolete — XLA picks the
    reduction topology on ICI/DCN."""

    class Builder:
        def __init__(self, batch_size_per_worker: int = 32):
            self._batch = batch_size_per_worker
            self._freq = 1
            self._workers = None

        def averaging_frequency(self, n):
            self._freq = int(n)
            return self

        averagingFrequency = averaging_frequency

        def batch_size_per_worker(self, n):
            self._batch = int(n)
            return self

        batchSizePerWorker = batch_size_per_worker

        def workers(self, n):
            self._workers = int(n)
            return self

        def build(self):
            return ParameterAveragingTrainingMaster(
                batch_size_per_worker=self._batch,
                averaging_frequency=self._freq, workers=self._workers)

    def __init__(self, batch_size_per_worker: int = 32,
                 averaging_frequency: int = 1, workers: Optional[int] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.workers = workers

    def execute_training(self, net, iterator):
        pw = (ParallelWrapper.Builder(net)
              .workers(self.workers or len(jax.devices()))
              .averaging_frequency(self.averaging_frequency)
              .training_mode(TrainingMode.AVERAGING)
              .build())
        pw.fit(iterator)
        return pw


class SharedTrainingMaster(TrainingMaster):
    """Async quantized-update sharing (reference ``SharedTrainingMaster``):
    within a slice this degenerates to the same fused all-reduce (ICI makes
    compression pointless — SURVEY.md §2.4 note); the threshold/accumulator
    knobs are kept and drive the DCN codec when updates cross slices."""

    class Builder:
        def __init__(self, threshold: float = 1e-3):
            self._threshold = threshold
            self._batch = 32
            self._workers = None

        def threshold(self, t):
            self._threshold = float(t)
            return self

        def batch_size_per_worker(self, n):
            self._batch = int(n)
            return self

        batchSizePerWorker = batch_size_per_worker

        def workers(self, n):
            self._workers = int(n)
            return self

        def build(self):
            return SharedTrainingMaster(threshold=self._threshold,
                                        batch_size_per_worker=self._batch,
                                        workers=self._workers)

    def __init__(self, threshold: float = 1e-3,
                 batch_size_per_worker: int = 32,
                 workers: Optional[int] = None):
        self.threshold = threshold
        self.batch_size_per_worker = batch_size_per_worker
        self.workers = workers
        self.accumulator = EncodedGradientsAccumulator(
            initial_threshold=threshold)

    def execute_training(self, net, iterator):
        pw = (ParallelWrapper.Builder(net)
              .workers(self.workers or len(jax.devices()))
              .training_mode(TrainingMode.SHARED_GRADIENTS)
              .gradients_accumulator(self.accumulator)
              .build())
        pw.fit(iterator)
        return pw


class SharedGradientsClusterTrainer:
    """Cross-host SHARED_GRADIENTS training over a real wire (reference
    ``SharedTrainingWrapper.java:160-244``: each executor encodes its local
    update, relays it to peers over Aeron, and applies everyone's decoded
    updates). Here the wire is ``parallel/transport.py``'s TCP mesh carrying
    the flat threshold-encoded frames; the *encoded* bytes are what cross the
    process boundary. All replicas apply the identical rank-ordered sum of
    decoded updates, so parameters stay bit-identical across hosts while the
    wire carries a fraction of the dense update size.

    Unlike ``ParameterAveragingTrainingMaster`` (a single jitted psum), hosts
    here run independent jitted steps — the pattern for training across
    slices where a fused collective is unavailable or DCN bandwidth makes
    dense exchange uneconomical.
    """

    def __init__(self, net, channel, accumulator: Optional[
            EncodedGradientsAccumulator] = None):
        import jax.numpy as jnp
        self.net = net
        self.channel = channel
        self.accumulator = accumulator or EncodedGradientsAccumulator()
        self._update_step = monitored_jit(net._raw_update_step(),
                                          name="distributed/update_step",
                                          donate_argnums=(2,))

        def apply_fn(params, update):
            return jax.tree_util.tree_map(
                lambda p, u: p - u.astype(p.dtype), params, update)

        self._apply_step = monitored_jit(apply_fn,
                                         name="distributed/apply_step",
                                         donate_argnums=(0,))
        self.wire_bytes_sent = 0
        self.dense_bytes_equiv = 0

    def fit(self, iterator, epochs: int = 1):
        import jax.numpy as jnp
        # function-level import: paramserver.training imports this module,
        # so a top-level import here would be circular
        from ..paramserver.overlap import async_device_get
        net = self.net
        acc = self.accumulator
        for _ in range(epochs):
            for ds in iterator:
                f = jnp.asarray(ds.features)
                l = jnp.asarray(ds.labels)
                itc = jnp.asarray(net.iteration_count, jnp.int32)
                update, net.states, net.updater_state, loss = \
                    self._update_step(net.params, net.states,
                                      net.updater_state, itc,
                                      net._next_rng(), f, l, None, None)
                # overlapped d2h (paramserver/overlap.py): every leaf's
                # transfer starts before the first gather blocks — the
                # PERF001 shape (blocking tree_map(np.asarray) in a hot
                # loop) removed the same way the paramserver master's was
                update = async_device_get(update)
                decoded_own = acc.store_update(update)
                frame = acc.serialize_last()
                self.wire_bytes_sent += len(frame) * (self.channel.P - 1)
                self.dense_bytes_equiv += sum(
                    np.asarray(u).nbytes for u in
                    jax.tree_util.tree_leaves(update)) * (self.channel.P - 1)
                peer_frames = self.channel.exchange(frame)
                # rank-ordered sum → identical float addition order on every
                # host → bit-identical replicas
                contributions = {self.channel.p: decoded_own}
                peers = [q for q in range(self.channel.P)
                         if q != self.channel.p]
                for q, fr in zip(peers, peer_frames):
                    contributions[q] = acc.decode_payload(fr)
                total = None
                for q in sorted(contributions):
                    c = contributions[q]
                    total = c if total is None else jax.tree_util.tree_map(
                        np.add, total, c)
                net.params = self._apply_step(
                    net.params, jax.tree_util.tree_map(jnp.asarray, total))
                net.score_ = loss
                net.iteration_count += 1
                for lst in net.listeners:
                    lst.iteration_done(net, net.iteration_count - 1,
                                       float(loss))
        return net


class DistributedMultiLayerNetwork:
    """User-facing facade (reference ``SparkDl4jMultiLayer``:
    ``fit(JavaRDD<DataSet>)`` :214 → ``trainingMaster.executeTraining``)."""

    def __init__(self, net, training_master: TrainingMaster,
                 checkpoint_path: Optional[str] = None):
        self.net = net
        self.training_master = training_master
        self.checkpoint_path = checkpoint_path

    def fit(self, iterator, epochs: int = 1):
        multi = jax.process_count() > 1
        if multi:
            # each process consumes only its round-robin share of the stream;
            # the wrapper assembles the global batch from the process locals
            iterator = ProcessLocalIterator(iterator)
            if not is_chief():
                # host-0 gating: listeners fire once per cluster, not per host
                saved_listeners, self.net.listeners = self.net.listeners, []
        try:
            for _ in range(epochs):
                self.training_master.execute_training(self.net, iterator)
        finally:
            if multi and not is_chief():
                self.net.listeners = saved_listeners
        if self.checkpoint_path and is_chief():
            from ..utils.model_serializer import ModelSerializer
            ModelSerializer.write_model(self.net, self.checkpoint_path)
        return self.net

    def evaluate(self, iterator):
        """Distributed evaluation (reference
        ``spark/impl/multilayer/evaluation/IEvaluateFlatMapFunction.java`` +
        ``IEvaluationReduceFunction.java``): each process evaluates only its
        round-robin shard of the stream, partial Evaluations are allgathered
        and MERGED, and every process returns the identical cluster-wide
        result."""
        import jax

        if jax.process_count() <= 1:
            return self.net.evaluate(iterator)
        local = self.net.evaluate(
            ProcessLocalIterator(iterator, drop_remainder=False))
        merged = None
        for part in allgather_objects(local):
            merged = part if merged is None else merged.merge(part)
        return merged

    def calculate_score(self, iterator, average: bool = True):
        """Reference ``calculateScore`` :332."""
        total, n = 0.0, 0
        for ds in iterator:
            b = ds.num_examples()
            total += self.net.score(ds) * b
            n += b
        return total / n if (average and n) else total

    calculateScore = calculate_score


SparkDl4jMultiLayer = DistributedMultiLayerNetwork  # reference-name alias


class DistributedComputationGraph(DistributedMultiLayerNetwork):
    """Reference ``SparkComputationGraph`` counterpart."""


SparkComputationGraph = DistributedComputationGraph


# -------------------------------------------------- cluster-wide reductions
def allgather_objects(obj) -> list:
    """Allgather arbitrary picklable host objects across processes (the
    reduce transport for distributed evaluation/scoring). Single-process:
    identity. Multi-process: length-prefixed pickle bytes through
    ``jax.experimental.multihost_utils.process_allgather`` (two fixed-shape
    collectives: max-length agreement, then padded payloads)."""
    import pickle

    if jax.process_count() <= 1:
        return [obj]
    from jax.experimental import multihost_utils

    data = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.asarray([data.size], np.int64))).reshape(-1)
    m = int(sizes.max())
    padded = np.zeros(m, np.uint8)
    padded[:data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(jax.process_count(), m)
    return [pickle.loads(gathered[i, :int(sizes[i])].tobytes())
            for i in range(jax.process_count())]


class DistributedDataSetLossCalculator:
    """Cluster-wide validation loss (reference
    ``spark/earlystopping/SparkDataSetLossCalculator.java``): each process
    sums loss over its shard, partial (total, n) pairs are allgathered, and
    every process computes the identical global average."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def minimize_score(self) -> bool:
        return True

    def calculate_score(self, net) -> float:
        it = (ProcessLocalIterator(self.iterator, drop_remainder=False)
              if jax.process_count() > 1 else self.iterator)
        total, n = 0.0, 0
        for ds in it:
            b = ds.num_examples()
            total += float(net.score(ds)) * b
            n += b
        parts = allgather_objects((total, n))
        total = sum(t for t, _ in parts)
        n = sum(c for _, c in parts)
        return total / n if (self.average and n) else total

    calculateScore = calculate_score


from ..earlystopping import EarlyStoppingTrainer, TerminationReason  # noqa: E402


class DistributedEarlyStoppingTrainer(EarlyStoppingTrainer):
    """Early stopping over the distributed facade (reference
    ``spark/earlystopping/SparkEarlyStoppingTrainer.java``): each epoch runs
    through the facade's TrainingMaster (process-sharded data, collective
    sync), and scoring should use :class:`DistributedDataSetLossCalculator`
    so conditions fire identically on every process."""

    def __init__(self, config, dist_net: DistributedMultiLayerNetwork,
                 train_iterator):
        super().__init__(config, dist_net.net, train_iterator)
        self.dist_net = dist_net

    def _train_one_epoch(self, c, reason, details):
        # the wrapper's fit already advances net.epoch_count; the base
        # trainer loop increments it too, so restore to avoid double-count
        before = self.net.epoch_count
        self.dist_net.fit(self.iterator, epochs=1)
        self.net.epoch_count = before
        last = float(self.net.score_)
        for cond in c.iteration_termination_conditions:
            if cond.terminate(last):
                reason = TerminationReason.IterationTerminationCondition
                details = f"{type(cond).__name__} at score {last}"
                return True, reason, details
        return False, reason, details


SparkEarlyStoppingTrainer = DistributedEarlyStoppingTrainer
SparkDataSetLossCalculator = DistributedDataSetLossCalculator
