"""Gradient accumulation + Strom-style threshold encoding.

TPU-native equivalent of reference ``optimize/solvers/accumulation/``
(``EncodedGradientsAccumulator.java:33`` with ``EncodingHandler.java:136-178``:
``Nd4j.getExecutioner().thresholdEncode/bitmapEncode``, adaptive threshold,
residual kept in the accumulator).

On-TPU the reference's quantized-update broadcast is unnecessary — gradient
all-reduce rides ICI as one fused ``psum`` (SURVEY.md §2.4 "Distributed
communication backend") — so inside a slice the accumulator is a no-op seam.
The encoding survives for the **DCN / cross-slice** path, where bandwidth is
the reference's 2017-Ethernet situation all over again: updates crossing slices
can be threshold-encoded exactly like the reference's wire format. A native C++
codec (ops/native) plugs in behind the same functions when built.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax


from ..ops import native as _native


def threshold_encode(grad: np.ndarray, threshold: float
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Sparsify: indices where |g| >= threshold, values quantized to
    ±threshold (the reference's 1-bit-per-significant-element scheme;
    ``EncodingHandler.java:136``). Returns (int32 indices, int8 signs).
    Uses the native codec (ops/libdl4jtpu.so) when built."""
    idx, signs, _ = _native.threshold_encode(np.asarray(grad, np.float32),
                                             threshold)
    return idx, signs


def threshold_decode(idx: np.ndarray, signs: np.ndarray, threshold: float,
                     shape) -> np.ndarray:
    """Densify an encoded update (reference ``thresholdDecode``).

    ``signs`` is normally the int8 ±1 vector of a quantized frame; a
    float32 ``signs`` array is an *exact* frame (lossless accumulator,
    threshold 0) carrying the raw values, scattered here without ever
    reaching the int8-only native codec."""
    signs = np.asarray(signs)
    if signs.dtype == np.float32:
        out = np.zeros(int(np.prod(shape)) if shape else 1, np.float32)
        out[np.asarray(idx, np.int64)] = signs
        return out.reshape(shape)
    return _native.threshold_decode(idx, signs, threshold, shape)


def encode_residual(grad: np.ndarray, threshold: float
                    ) -> Tuple[Tuple[np.ndarray, np.ndarray], np.ndarray]:
    """Encode and return the residual kept locally for the next round
    (reference keeps sub-threshold mass in the accumulator)."""
    idx, signs, residual = _native.threshold_encode(
        np.asarray(grad, np.float32), threshold)
    return (idx, signs), residual


class EncodingHandler:
    """Adaptive threshold controller (reference ``EncodingHandler``): the
    threshold shrinks when too little of the update is transmitted and grows
    when the encoding gets dense, targeting ``target_sparsity``."""

    def __init__(self, initial_threshold: float = 1e-3,
                 min_threshold: float = 1e-5,
                 target_sparsity: float = 1e-2,
                 adaptation: float = 1.2):
        self.threshold = float(initial_threshold)
        self.min_threshold = float(min_threshold)
        self.target_sparsity = float(target_sparsity)
        self.adaptation = float(adaptation)
        self.iterations = 0

    def encode(self, grad: np.ndarray):
        used = self.threshold  # adaptation applies to the NEXT round
        (idx, signs), residual = encode_residual(grad, used)
        density = len(idx) / max(grad.size, 1)
        if density > 2 * self.target_sparsity:
            self.threshold *= self.adaptation
        elif density < 0.5 * self.target_sparsity:
            self.threshold = max(self.threshold / self.adaptation,
                                 self.min_threshold)
        self.iterations += 1
        return (idx, signs, used), residual


def flatten_tree_f32(tree):
    """THE canonical pytree→flat-f32 layout for everything that crosses the
    update wire or lives in a parameter server: ``jax.tree_util`` leaf
    order, each leaf raveled as float32. Returns ``(vec, treedef, shapes)``.
    ``EncodedGradientsAccumulator`` encodes updates in this layout and
    ``paramserver`` holds/indexes parameters in it — both MUST go through
    this one function or pushed updates would scatter into wrong offsets."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [np.shape(l) for l in leaves]
    if not leaves:
        return np.zeros(0, np.float32), treedef, shapes
    vec = np.concatenate([np.asarray(l, np.float32).ravel() for l in leaves])
    return vec, treedef, shapes


class GradientsAccumulator:
    """SPI seam (reference ``GradientsAccumulator``): receives local updates,
    hands back the aggregate to apply. The base implementation is the ICI
    identity (all-reduce happens inside the jitted step)."""

    def store_update(self, grads):
        return grads

    storeUpdate = store_update

    def apply_update(self, grads):
        return grads

    applyUpdate = apply_update

    def reset(self):
        pass


class EncodedGradientsAccumulator(GradientsAccumulator):
    """Host-side residual accumulator for updates that must cross DCN
    (reference ``EncodedGradientsAccumulator``): each ``store_update`` call
    threshold-encodes the *flattened* update vector — the reference encodes
    the flat param-view buffer, not per-layer tensors — keeps the residual,
    and returns the decoded (quantized) update pytree: what a peer slice
    would apply after receiving the wire bytes. One native codec call per
    round (``threshold_encode_f32`` over the whole vector) instead of a
    Python loop over leaves.
    """

    def __init__(self, initial_threshold: float = 1e-3, **handler_kw):
        self._handler_kw = dict(initial_threshold=initial_threshold,
                                **handler_kw)
        self._handler = EncodingHandler(**self._handler_kw)
        self._residual: Optional[np.ndarray] = None
        self._treedef = None
        self._shapes = None
        self.last_encoded = None  # (idx, signs, threshold, n) — wire form

    def _flatten(self, grads) -> np.ndarray:
        vec, self._treedef, self._shapes = flatten_tree_f32(grads)
        return vec

    def _unflatten(self, flat: np.ndarray):
        out = []
        off = 0
        for shp in self._shapes:
            n = int(np.prod(shp)) if shp else 1
            out.append(flat[off:off + n].reshape(shp))
            off += n
        return jax.tree_util.tree_unflatten(self._treedef, out)

    @property
    def lossless(self) -> bool:
        """True when the codec is exact: threshold 0 encodes the raw f32
        values, so decode(encode(g)) == g and the residual stays empty."""
        return self._handler.threshold <= 0.0

    @property
    def has_residual(self) -> bool:
        """True when reinjected/sub-threshold mass is pending — the next
        ``store_update`` will fold it in, so the stored update is NOT equal
        to the incoming gradient alone."""
        return self._residual is not None and bool(np.any(self._residual))

    def store_update(self, grads):
        g = self._flatten(grads)
        if self._residual is not None:
            g = g + self._residual
        if self._handler.threshold <= 0.0:
            # lossless fast path: an *exact* frame (f32 values instead of
            # int8 signs) — decode is the identity, nothing stays behind
            idx = np.flatnonzero(g).astype(np.int32)
            self._residual = None
            self._handler.iterations += 1
            self.last_encoded = (idx, np.ascontiguousarray(g[idx]),
                                 0.0, g.size)
            return self._unflatten(g)
        (idx, signs, thr), residual = self._handler.encode(g)
        self._residual = residual
        self.last_encoded = (idx, signs, thr, g.size)
        return self._unflatten(threshold_decode(idx, signs, thr, (g.size,)))

    storeUpdate = store_update

    def reinject(self, dense_update: np.ndarray):
        """Return un-deliverable quantized mass to the residual: a sharded
        push that lost a shard server hands the dead shard's DECODED update
        back here, so the next ``store_update`` re-encodes it — supra- and
        sub-threshold mass alike is never lost to a down server (the same
        never-lose-mass rule the residual already guarantees)."""
        d = np.asarray(dense_update, np.float32)
        self._residual = (d.copy() if self._residual is None
                          else self._residual + d)

    def encoded_bytes(self) -> int:
        """Wire size of the last encoding (index + sign bytes)."""
        if not self.last_encoded:
            return 0
        idx, signs, _, _ = self.last_encoded
        return idx.nbytes + signs.nbytes

    # ------------------------------------------------------------- wire form
    def serialize_last(self) -> bytes:
        """Wire bytes of the last encoding (the reference's
        ``SilentUpdatesMessage`` payload)."""
        if self.last_encoded is None:
            raise ValueError("no update stored yet")
        return serialize_encoded(self.last_encoded)

    serializeLast = serialize_last

    def decode_payload(self, data: bytes):
        """Decode a peer's wire bytes into an update pytree shaped like the
        last stored update (reference ``SilentTrainingDriver`` applying a
        received ``SilentUpdatesMessage``)."""
        idx, signs, thr, n = deserialize_encoded(data)
        return self._unflatten(threshold_decode(idx, signs, thr, (n,)))

    decodePayload = decode_payload

    def reset(self):
        self._residual = None
        # fresh handler: the adaptive threshold returns to initial_threshold,
        # matching a newly constructed accumulator
        self._handler = EncodingHandler(**self._handler_kw)
        self.last_encoded = None


# ------------------------------------------------------------------ wire I/O
_WIRE_MAGIC = 0x444C3454        # "DL4T" — quantized frame (int8 signs)
_WIRE_MAGIC_EXACT = 0x444C3458  # "DL4X" — exact frame (f32 values)


def serialize_encoded(encoded) -> bytes:
    """Pack (idx, signs, threshold, n) into the wire frame: little-endian
    header [magic u32, n u64, k u64, threshold f32] + idx i32[k] + signs
    i8[k] — the Aeron-free counterpart of the reference's
    ``SilentUpdatesMessage`` (``networking/messages/SilentUpdatesMessage.java``).
    Float32 ``signs`` mark an *exact* frame (lossless accumulator): the
    payload carries f32 values under ``_WIRE_MAGIC_EXACT`` instead."""
    idx, signs, thr, n = encoded
    idx = np.ascontiguousarray(idx, np.int32)
    signs = np.asarray(signs)
    exact = signs.dtype == np.float32
    signs = np.ascontiguousarray(signs,
                                 np.float32 if exact else np.int8)
    header = np.zeros(6, np.uint32)
    header[0] = _WIRE_MAGIC_EXACT if exact else _WIRE_MAGIC
    header[1] = n & 0xFFFFFFFF
    header[2] = n >> 32
    header[3] = idx.size & 0xFFFFFFFF
    header[4] = idx.size >> 32
    header[5] = np.float32(thr).view(np.uint32)
    return header.tobytes() + idx.tobytes() + signs.tobytes()


def deserialize_encoded(data: bytes):
    header = np.frombuffer(data[:24], np.uint32)
    if int(header[0]) not in (_WIRE_MAGIC, _WIRE_MAGIC_EXACT):
        raise ValueError("bad wire frame")
    n = int(header[1]) | (int(header[2]) << 32)
    k = int(header[3]) | (int(header[4]) << 32)
    thr = float(header[5:6].view(np.float32)[0])
    idx = np.frombuffer(data[24:24 + 4 * k], np.int32)
    if int(header[0]) == _WIRE_MAGIC_EXACT:
        signs = np.frombuffer(data[24 + 4 * k:24 + 8 * k], np.float32)
    else:
        signs = np.frombuffer(data[24 + 4 * k:24 + 5 * k], np.int8)
    return idx, signs, thr, n
