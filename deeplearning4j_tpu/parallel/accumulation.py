"""Gradient accumulation + Strom-style threshold encoding.

TPU-native equivalent of reference ``optimize/solvers/accumulation/``
(``EncodedGradientsAccumulator.java:33`` with ``EncodingHandler.java:136-178``:
``Nd4j.getExecutioner().thresholdEncode/bitmapEncode``, adaptive threshold,
residual kept in the accumulator).

On-TPU the reference's quantized-update broadcast is unnecessary — gradient
all-reduce rides ICI as one fused ``psum`` (SURVEY.md §2.4 "Distributed
communication backend") — so inside a slice the accumulator is a no-op seam.
The encoding survives for the **DCN / cross-slice** path, where bandwidth is
the reference's 2017-Ethernet situation all over again: updates crossing slices
can be threshold-encoded exactly like the reference's wire format. A native C++
codec (ops/native) plugs in behind the same functions when built.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax


from ..ops import native as _native


def threshold_encode(grad: np.ndarray, threshold: float
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Sparsify: indices where |g| >= threshold, values quantized to
    ±threshold (the reference's 1-bit-per-significant-element scheme;
    ``EncodingHandler.java:136``). Returns (int32 indices, int8 signs).
    Uses the native codec (ops/libdl4jtpu.so) when built."""
    idx, signs, _ = _native.threshold_encode(np.asarray(grad, np.float32),
                                             threshold)
    return idx, signs


def threshold_decode(idx: np.ndarray, signs: np.ndarray, threshold: float,
                     shape) -> np.ndarray:
    """Densify an encoded update (reference ``thresholdDecode``)."""
    return _native.threshold_decode(idx, signs, threshold, shape)


def encode_residual(grad: np.ndarray, threshold: float
                    ) -> Tuple[Tuple[np.ndarray, np.ndarray], np.ndarray]:
    """Encode and return the residual kept locally for the next round
    (reference keeps sub-threshold mass in the accumulator)."""
    idx, signs, residual = _native.threshold_encode(
        np.asarray(grad, np.float32), threshold)
    return (idx, signs), residual


class EncodingHandler:
    """Adaptive threshold controller (reference ``EncodingHandler``): the
    threshold shrinks when too little of the update is transmitted and grows
    when the encoding gets dense, targeting ``target_sparsity``."""

    def __init__(self, initial_threshold: float = 1e-3,
                 min_threshold: float = 1e-5,
                 target_sparsity: float = 1e-2,
                 adaptation: float = 1.2):
        self.threshold = float(initial_threshold)
        self.min_threshold = float(min_threshold)
        self.target_sparsity = float(target_sparsity)
        self.adaptation = float(adaptation)
        self.iterations = 0

    def encode(self, grad: np.ndarray):
        used = self.threshold  # adaptation applies to the NEXT round
        (idx, signs), residual = encode_residual(grad, used)
        density = len(idx) / max(grad.size, 1)
        if density > 2 * self.target_sparsity:
            self.threshold *= self.adaptation
        elif density < 0.5 * self.target_sparsity:
            self.threshold = max(self.threshold / self.adaptation,
                                 self.min_threshold)
        self.iterations += 1
        return (idx, signs, used), residual


class GradientsAccumulator:
    """SPI seam (reference ``GradientsAccumulator``): receives local updates,
    hands back the aggregate to apply. The base implementation is the ICI
    identity (all-reduce happens inside the jitted step)."""

    def store_update(self, grads):
        return grads

    storeUpdate = store_update

    def apply_update(self, grads):
        return grads

    applyUpdate = apply_update

    def reset(self):
        pass


class EncodedGradientsAccumulator(GradientsAccumulator):
    """Host-side residual accumulator for updates that must cross DCN
    (reference ``EncodedGradientsAccumulator``): each ``store_update`` call
    threshold-encodes the gradient pytree per-leaf, keeps the residual, and
    returns the decoded (quantized) update — what a peer slice would apply.
    """

    def __init__(self, initial_threshold: float = 1e-3, **handler_kw):
        self._handlers: Dict[str, EncodingHandler] = {}
        self._residual: Dict[str, np.ndarray] = {}
        self._kw = dict(initial_threshold=initial_threshold, **handler_kw)
        self.last_encoded = None  # {path: (idx, signs, threshold)} — wire form

    def _handler(self, path) -> EncodingHandler:
        if path not in self._handlers:
            self._handlers[path] = EncodingHandler(**self._kw)
        return self._handlers[path]

    def store_update(self, grads):
        leaves = jax.tree_util.tree_flatten_with_path(grads)[0]
        encoded = {}
        decoded = {}
        for keypath, leaf in leaves:
            path = jax.tree_util.keystr(keypath)
            g = np.asarray(leaf, np.float32)
            if path in self._residual:
                g = g + self._residual[path]
            (idx, signs, thr), residual = self._handler(path).encode(g)
            self._residual[path] = residual
            encoded[path] = (idx, signs, thr)
            decoded[path] = threshold_decode(idx, signs, thr, g.shape)
        self.last_encoded = encoded
        # rebuild pytree with decoded leaves
        flat_vals = [decoded[jax.tree_util.keystr(kp)] for kp, _ in leaves]
        treedef = jax.tree_util.tree_structure(grads)
        return jax.tree_util.tree_unflatten(treedef, flat_vals)

    storeUpdate = store_update

    def encoded_bytes(self) -> int:
        """Wire size of the last encoding (index + sign bytes)."""
        if not self.last_encoded:
            return 0
        return sum(idx.nbytes + signs.nbytes
                   for idx, signs, _ in self.last_encoded.values())

    def reset(self):
        self._residual.clear()
        self._handlers.clear()
        self.last_encoded = None
