"""Unified 2-D mesh substrate: ONE place that builds, validates and
describes device meshes for every parallelism style in ``parallel/``.

Before this module, each style constructed its own mesh logic (wrapper,
tensor, pipeline, sequence each validated axes ad hoc) and the ZeRO paths
(``weight_update_sharding``/``fsdp``, after PAPERS.md's "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training",
arXiv:2004.13336) only understood a 1-D data mesh. The substrate makes the
composition real: a :class:`MeshSpec` names the axes, auto-factorizes the
extents over the available devices, and validates loudly; the partition-spec
helpers here (:func:`rule_shardings`, :func:`mirror_updater_shardings`,
:func:`zero_update_specs`) compose tensor-parallel rules over ``model`` with
ZeRO sharding over the ``data`` axis *of whatever mesh they are given* —
reduce-scatter grads along ``data``, update the local shard, all-gather
weights — so DP × TP/PP stack on one 2-D mesh instead of excluding each
other.

Axis conventions (canonical order — earlier axes get the larger
auto-factorized extents):
  - ``data``     — batch (data parallelism; ParallelWrapper drives it)
  - ``model``    — tensor parallelism (Megatron-style param rules)
  - ``pipe``     — pipeline stages (GPipe schedule, ``parallel/pipeline.py``)
  - ``sequence`` — sequence/context parallelism (ring attention)
  - ``expert``   — MoE expert sharding (``parallel/expert.py``)

Multi-process: ``jax.devices()`` returns the same globally-ordered device
list on every process, so a :class:`MeshSpec` resolved from defaults is
identical fleet-wide — the property every collective schedule depends on.
The ``data`` axis should span processes (each process feeds its addressable
share of the global batch, ``sharding.shard_batch``); model-family axes
are cheapest within a process (ICI, not DCN).

Every step factory in ``parallel/`` reports its topology here
(:func:`record_step`), so ``GET /profile`` carries a ``mesh`` block —
axis names, extents, per-style active steps, sharded-vs-replicated leaf
counts — and an operator can see what topology a fit is actually running
on (docs/PARALLELISM.md "Unified mesh substrate").
"""
from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipe"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"

#: canonical axis order — MeshSpec sorts nothing, but docs and the
#: auto-factorizer's "earlier axes get bigger extents" rule follow it
CANONICAL_AXES = (DATA_AXIS, MODEL_AXIS, PIPELINE_AXIS, SEQUENCE_AXIS,
                  EXPERT_AXIS)


def _prime_factors(n: int):
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return sorted(out, reverse=True)


def auto_factor(n: int, k: int):
    """Factorize ``n`` devices into ``k`` near-balanced extents,
    deterministically: prime factors (largest first) go to the currently
    smallest extent, then the extents are ordered largest-first — so
    earlier axes get the larger extents (8 over 2 axes → (4, 2); 12 →
    (4, 3); 8 over 3 → (2, 2, 2))."""
    ext = [1] * k
    for f in _prime_factors(n):
        i = min(range(k), key=lambda j: (ext[j], j))
        ext[i] *= f
    return tuple(sorted(ext, reverse=True))


class MeshSpec:
    """Declarative mesh: named axes with fixed or auto (``None``/``-1``)
    extents, resolved over a device list at :meth:`build` time.

    Validation is loud and actionable: duplicate axes, non-positive
    extents, and fixed extents that do not divide / cover the device
    count all raise ``ValueError`` naming the numbers involved — the
    degenerate ``[n, 1, …]`` default that used to pile every device on
    the first axis is gone (auto extents factorize instead).
    """

    def __init__(self, axes: Sequence[str] = (DATA_AXIS,),
                 shape: Optional[Sequence[Optional[int]]] = None,
                 devices: Optional[Sequence] = None):
        axes = tuple(axes)
        if not axes:
            raise ValueError("MeshSpec needs at least one axis name")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate mesh axis names: {axes}")
        if shape is None:
            shape = (None,) * len(axes)
        shape = tuple(shape)
        if len(shape) != len(axes):
            raise ValueError(
                f"mesh shape {shape} names {len(shape)} extents for "
                f"{len(axes)} axes {axes}")
        norm = []
        for ax, s in zip(axes, shape):
            if s is None or s == -1:
                norm.append(None)
            elif int(s) <= 0:
                raise ValueError(
                    f"axis {ax!r} has non-positive extent {s}; use None "
                    f"(or -1) for an auto-factorized extent")
            else:
                norm.append(int(s))
        self.axes = axes
        self.shape = tuple(norm)
        self.devices = None if devices is None else list(devices)

    # ------------------------------------------------------------------
    def resolve_shape(self, n_devices: int):
        """Concrete per-axis extents over ``n_devices``: fixed extents must
        divide the device count; auto extents split the quotient
        near-balanced (:func:`auto_factor`, earlier axes ≥ later)."""
        fixed = [s for s in self.shape if s is not None]
        prod = int(np.prod(fixed)) if fixed else 1
        if n_devices % prod:
            raise ValueError(
                f"mesh axes {dict(zip(self.axes, self.shape))} need a "
                f"multiple of {prod} devices but {n_devices} are "
                f"available; change the fixed extents so their product "
                f"divides {n_devices}, or pass an explicit device subset")
        n_auto = sum(1 for s in self.shape if s is None)
        rest = n_devices // prod
        if n_auto == 0:
            if rest != 1:
                raise ValueError(
                    f"mesh shape {dict(zip(self.axes, self.shape))} covers "
                    f"{prod} devices but {n_devices} are available; mark "
                    f"one axis auto (None) to absorb the rest, or shrink "
                    f"the device list")
            return tuple(self.shape)
        auto = list(auto_factor(rest, n_auto))
        return tuple(s if s is not None else auto.pop(0)
                     for s in self.shape)

    def build(self) -> Mesh:
        """Resolve to a ``jax.sharding.Mesh`` (the ONE sanctioned
        construction site — tpulint JAX004 flags raw ``Mesh(...)`` calls
        outside the substrate)."""
        devices = (list(jax.devices()) if self.devices is None
                   else list(self.devices))
        shape = self.resolve_shape(len(devices))
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, self.axes)

    @property
    def process_count(self) -> int:
        return jax.process_count()

    def __repr__(self):
        return (f"MeshSpec(axes={self.axes!r}, shape={self.shape!r}, "
                f"devices={'default' if self.devices is None else len(self.devices)})")


def make_mesh(devices: Optional[Sequence] = None,
              axes: Sequence[str] = (DATA_AXIS,),
              shape: Optional[Sequence[Optional[int]]] = None) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) with named ``axes`` —
    the long-standing entry point, now routed through :class:`MeshSpec`.

    ``shape`` gives per-axis extents; ``None``/``-1`` entries (and a
    wholly omitted shape) auto-factorize over the device count instead of
    the old degenerate ``[n, 1, …]`` default. Shapes that don't cover the
    devices raise with an actionable message."""
    return MeshSpec(axes=axes, shape=shape, devices=devices).build()


def require_axes(mesh: Mesh, axes: Sequence[str], style: str = "step"):
    """Loudly verify ``mesh`` carries every named axis (the shared
    validation every style used to hand-roll)."""
    missing = [a for a in axes if a and a not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"{style} needs mesh axis(es) {missing} but the mesh has "
            f"{tuple(mesh.axis_names)} (shape "
            f"{dict(mesh.shape)}); build it with "
            f"parallel.make_mesh/MeshSpec naming those axes")
    return mesh


# ---------------------------------------------------------------- specs
def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim across ``axis``."""
    return NamedSharding(mesh, P(axis))


def spec_for_path(path: str, rules: Dict[str, P]) -> P:
    """First rule whose regex matches ``path`` (replicated otherwise)."""
    for pat, spec in rules.items():
        if re.search(pat, path):
            return spec
    return P()


def clean_spec(spec: P, dims, mesh: Mesh) -> P:
    """Drop spec axes that don't divide their dim (falls back to
    replication on that dim) and pad to the leaf's rank."""
    cleaned = []
    for d, s in zip(dims, tuple(spec) + (None,) * (len(dims)
                                                   - len(tuple(spec)))):
        if s is None or d % mesh.shape[s] != 0:
            cleaned.append(None)
        else:
            cleaned.append(s)
    return P(*cleaned)


def _keypath_str(keypath) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in keypath)


def rule_shardings(params, mesh: Mesh, rules: Dict[str, P]):
    """NamedSharding pytree for ``params`` from {path-regex: PartitionSpec}
    rules (the machinery behind ``tensor.param_shardings`` — axes that
    don't divide a dim fall back to replication on that dim)."""
    def one(keypath, leaf):
        spec = spec_for_path(_keypath_str(keypath), rules)
        return NamedSharding(mesh, clean_spec(spec, np.shape(leaf), mesh))
    return jax.tree_util.tree_map_with_path(one, params)


def mirror_updater_shardings(params, updater_state, mesh: Mesh,
                             rules: Dict[str, P]):
    """Updater-state entries shaped like a param inherit that param's
    rule sharding (Adam moments must shard WITH their param or the
    optimizer-state memory saving is silently lost); everything else
    replicates. Updater keypaths look like ``layer/param/slot`` (e.g.
    ``0/W/0`` for Adam's first moment) or ``layer/param``, so the param
    name is searched among ALL trailing path segments."""
    p_sh_flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        path = _keypath_str(keypath)
        p_sh_flat[(path, np.shape(leaf))] = NamedSharding(
            mesh, clean_spec(spec_for_path(path, rules), np.shape(leaf),
                             mesh))

    def one(keypath, leaf):
        parts = [str(getattr(k, "key", getattr(k, "idx", k)))
                 for k in keypath]
        shape = np.shape(leaf)
        for (ppath, pshape), sh in p_sh_flat.items():
            psegs = ppath.split("/")
            if (shape == pshape and parts and psegs
                    and parts[0] == psegs[0] and psegs[-1] in parts[1:]):
                return sh
        return NamedSharding(mesh, P())
    return jax.tree_util.tree_map_with_path(one, updater_state)


def zero_update_specs(tree, mesh: Mesh, axis: str = DATA_AXIS,
                      base=None):
    """ZeRO sharding over the ``axis`` extent of WHATEVER mesh is given
    (arXiv:2004.13336 expressed as sharding annotations): each leaf
    shards its largest ``axis``-divisible dim that the ``base`` specs
    (e.g. tensor-parallel rules over ``model``) have not already claimed
    — ties broken toward the later dim, so an NHWC/HWIO conv kernel
    shards over channels rather than a small spatial dim that happens to
    divide. Leaves with no free divisible dim keep their base sharding
    (replicated when ``base`` is None).

    With optimizer state annotated this way the SPMD partitioner
    reduce-scatters gradients along ``axis``, updates the local shard,
    and all-gathers weights — numerically identical to replicated DP
    (pinned bit-exact in tests/test_mesh.py) with ~N× less state per
    device. Composes: on a 2-D ``data × model`` mesh the base specs keep
    the TP split and ZeRO rides the remaining dims over ``data``."""
    n = int(mesh.shape[axis])

    def one(x, base_sh):
        shape = getattr(x, "shape", ())
        spec = () if base_sh is None else tuple(
            getattr(base_sh, "spec", base_sh))
        spec = spec + (None,) * (len(shape) - len(spec))
        best = None
        # a base rule may already claim the ZeRO axis itself (a user TP
        # rule over 'data') — adding it twice would build an invalid
        # duplicate-axis PartitionSpec, so such leaves keep their base
        if axis not in spec:
            for d, s in enumerate(shape):
                if (spec[d] is None and s >= n and s % n == 0
                        and (best is None or s >= shape[best])):
                    best = d
        new = list(spec)
        if best is not None:
            new[best] = axis
        while new and new[-1] is None:       # P(None,) is not P()
            new.pop()
        return NamedSharding(mesh, P(*new))

    if base is None:
        return jax.tree_util.tree_map(lambda x: one(x, None), tree)
    return jax.tree_util.tree_map(one, tree, base)


# ------------------------------------------------- active-topology registry
_REG_LOCK = threading.Lock()
_ACTIVE: Dict[str, Dict] = {}


def _leaf_counts(*spec_trees):
    """(sharded, replicated) leaf counts over NamedSharding/PartitionSpec
    pytrees (scalars count as one replicated leaf)."""
    sharded = replicated_n = 0
    for tree in spec_trees:
        if tree is None:
            continue
        leaves = jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, (NamedSharding, P)))
        for leaf in leaves:
            spec = getattr(leaf, "spec", leaf)
            if any(s is not None for s in tuple(spec)):
                sharded += 1
            else:
                replicated_n += 1
    return sharded, replicated_n


def record_step(style: str, mesh: Mesh, *spec_trees, zero: bool = False):
    """Register a parallel step built on ``mesh`` under a stable ``style``
    name (``wrapper/sync``, ``tensor/step``, …) for the ``/profile`` mesh
    block. ``spec_trees`` are the model-state sharding pytrees the step
    was built with — their sharded-vs-replicated leaf split is what tells
    an operator whether a topology is actually sharding anything."""
    sharded, repl = _leaf_counts(*spec_trees)
    with _REG_LOCK:
        row = _ACTIVE.setdefault(style, {
            "axes": {}, "devices": 0, "steps": 0,
            "sharded_leaves": 0, "replicated_leaves": 0, "zero": False})
        row["axes"] = {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
        row["devices"] = int(np.prod(mesh.devices.shape))
        row["steps"] += 1
        row["sharded_leaves"] = sharded
        row["replicated_leaves"] = repl
        row["zero"] = bool(zero) or row["zero"]


def mesh_block() -> Dict[str, Dict]:
    """The ``/profile`` mesh block: per-style active topology (axis names,
    extents, device count, steps built, sharded-vs-replicated leaf
    counts). Empty until a parallel step factory runs."""
    with _REG_LOCK:
        return {style: dict(row) for style, row in sorted(_ACTIVE.items())}


def reset_mesh_registry():
    """Test hook: forget every recorded topology."""
    with _REG_LOCK:
        _ACTIVE.clear()
