"""Device-mesh sharding helpers — the SPMD substrate for data/model parallelism.

TPU-native replacement for the reference's device-affinity machinery
(``Nd4j.getAffinityManager()`` uses in ``ParallelWrapper.java:484`` and
``MultiLayerNetwork.java:1161``): instead of pinning model replicas to devices
from host threads, we declare a `jax.sharding.Mesh` and annotate the jitted
train step's inputs with `NamedSharding`s; XLA's SPMD partitioner inserts the
ICI collectives (psum for gradient all-reduce) that replace both parameter
averaging and Aeron gradient broadcast (SURVEY.md §2.4 "Distributed
communication backend").

Mesh axis conventions used throughout the framework:
  - ``data``     — batch (data parallelism; ParallelWrapper equivalent)
  - ``model``    — tensor parallelism (net-new vs the reference, §2.4 note)
  - ``sequence`` — sequence/context parallelism (ring attention, net-new)
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax

from ..monitor.jitwatch import monitored_jit
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"


def make_mesh(devices: Optional[Sequence] = None,
              axes: Sequence[str] = (DATA_AXIS,),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) with named ``axes``.

    ``shape`` gives the per-axis extents; by default all devices go on the
    first axis and the rest get extent 1.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axes) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim across ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_batch(x, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host batch with its leading dim split across ``axis``.

    Single-process: a plain sharded device_put. Multi-process (after
    ``jax.distributed.initialize``): ``x`` is this process's LOCAL portion of
    the global batch — the global array is assembled from every process's
    local data without any host ever holding the full batch (the reference's
    per-executor ``VirtualDataSetIterator`` partition feeding, done the JAX
    multi-controller way)."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            batch_sharded(mesh, axis), np.asarray(x))
    return jax.device_put(x, batch_sharded(mesh, axis))


def put_replicated(x, mesh: Mesh):
    """Replicate a host value over the (possibly multi-process) mesh. Every
    process must hold the same value (same-seed init guarantees this)."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(replicated(mesh),
                                                      np.asarray(x))
    return jax.device_put(x, replicated(mesh))


def put_sharded_tree(tree, specs):
    """Place a host pytree with per-leaf ``NamedSharding``s. Single-process:
    plain sharded device_put. Multi-process: every process holds the same
    full host value (same-seed init), and ``make_array_from_callback``
    slices out each process's addressable shards — no host ever transfers
    more than its devices' portion."""
    multi = jax.process_count() > 1

    def put(x, sh):
        cur = getattr(x, "sharding", None)
        if cur == sh:
            return x                      # already placed (second fit call)
        if multi:
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # already distributed under another sharding: device-side
                # reshard, no host round-trip
                return jax.device_put(x, sh)
            a = np.asarray(x)
            return jax.make_array_from_callback(
                a.shape, sh, lambda idx, _a=a: _a[idx])
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, tree, specs)


def update_sharded_specs(tree, mesh: Mesh, axis: str = DATA_AXIS):
    """Sharding pytree for OPTIMIZER STATE sharded over the data axis —
    weight-update / optimizer-state sharding (Xu et al. 2020,
    arXiv:2004.13336 "Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training"; the ZeRO-1 idea expressed as XLA sharding
    annotations). Each leaf shards its LARGEST dim divisible by the axis
    extent (ties broken toward the later dim, so an NHWC/HWIO conv kernel
    shards over channels rather than a small spatial dim that happens to
    divide); leaves with no divisible dim — scalar step counts, biases
    narrower than the axis extent — replicate.
    With the updater state annotated this way and params replicated, the
    SPMD partitioner keeps each replica's m/v (etc.) shard-resident —
    optimizer memory drops ~N-fold — and reshards gradients into the
    update instead of applying it N times redundantly."""
    n = int(mesh.shape[axis])
    repl = replicated(mesh)

    def spec(x):
        shape = getattr(x, "shape", ())
        best = None
        for d, s in enumerate(shape):
            if s >= n and s % n == 0 and (best is None or s >= shape[best]):
                best = d
        if best is not None:
            return NamedSharding(mesh, P(*([None] * best + [axis])))
        return repl

    return jax.tree_util.tree_map(spec, tree)


def data_parallel_step(net, mesh: Mesh, axis: str = DATA_AXIS, donate=True,
                       shard_update: bool = False,
                       shard_params: bool = False):
    """Jit a network's train step for synchronous data parallelism.

    Equivalent role to the reference's ``ParallelWrapper`` AVERAGING mode with
    ``averagingFrequency=1`` (``ParallelWrapper.java:551-562``) — except the
    "averaging" is a single fused gradient ``psum`` over ICI emitted by the
    SPMD partitioner, not a host-side barrier + parameter copy.

    Returns a jitted ``step(params, states, upd_state, iteration, rng, f, l,
    fm, lm)`` whose batch inputs must be sharded along ``axis`` (use
    :func:`shard_batch`) and whose params/updater-state are replicated.

    ``shard_update=True`` enables weight-update/optimizer-state sharding
    (:func:`update_sharded_specs`): updater state lives sharded over the
    data axis instead of replicated — numerically identical, ~N× less
    optimizer memory per device.

    ``shard_params=True`` additionally SHARDS THE PARAMETERS over the data
    axis (ZeRO-3/FSDP-style sharded storage): each leaf's largest
    axis-divisible dim (see :func:`update_sharded_specs`) is stored 1/N
    per device, and the SPMD partitioner inserts the all-gathers at the
    points of use and reduce-scatters the gradients into the sharded
    update. Leaves with no divisible dim stay replicated.
    Numerically identical to replicated DP.
    """
    raw = net._raw_step(False)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    upd = (update_sharded_specs(net.updater_state, mesh, axis)
           if shard_update else repl)
    par = (update_sharded_specs(net.params, mesh, axis)
           if shard_params else repl)
    in_sh = (par, repl, upd, repl, repl, data, data, data, data)
    out_sh = (par, repl, upd, repl)
    return monitored_jit(raw, name="sharding/dp_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())


def _rnn_state_shardings(net, mesh: Mesh, axis: str):
    """Sharding pytree for a container's RNN/KV stream state: leaves with a
    batch dimension (LSTM (h, c), attention KV cache/positions) are sharded
    along ``axis``; scalars (the attention global token counter) replicate."""
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    template = net._init_rnn_state(1)
    return jax.tree_util.tree_map(
        lambda x: data if getattr(x, "ndim", 0) >= 1 else repl, template)


def data_parallel_tbptt_step(net, mesh: Mesh, axis: str = DATA_AXIS,
                             donate=True, shard_update: bool = False,
                             shard_params: bool = False):
    """Sharded train step that also threads the detached RNN/KV carry —
    the TBPTT segment step under data parallelism. Reference semantics:
    ``ParallelWrapper`` workers run the full ``MultiLayerNetwork.fit`` loop
    per replica (``trainer/DefaultTrainer.java:244``), truncated-BPTT
    included, so the SPMD equivalent must segment time the same way.
    ``shard_update`` as in :func:`data_parallel_step`."""
    raw = net._raw_step(True)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    state_sh = _rnn_state_shardings(net, mesh, axis)
    upd = (update_sharded_specs(net.updater_state, mesh, axis)
           if shard_update else repl)
    par = (update_sharded_specs(net.params, mesh, axis)
           if shard_params else repl)
    in_sh = (par, repl, upd, repl, repl, data, data, data, data, state_sh)
    out_sh = (par, repl, upd, repl, state_sh)
    return monitored_jit(raw, name="sharding/dp_tbptt_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())


def data_parallel_tbptt_update_step(net, mesh: Mesh, axis: str = DATA_AXIS):
    """TBPTT segment variant of the SHARED_GRADIENTS update step: returns the
    updater-transformed (un-applied) update plus the detached carry, so the
    host codec seam can encode per segment."""
    raw = net._raw_update_step(with_rnn_state=True)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    state_sh = _rnn_state_shardings(net, mesh, axis)
    in_sh = (repl, repl, repl, repl, repl, data, data, data, data, state_sh)
    out_sh = (repl, repl, repl, repl, state_sh)
    return monitored_jit(raw, name="sharding/dp_tbptt_update_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(2,))


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` inside shard_map
    (vma typing). Wraps ``lax.pcast(..., to='varying')`` with a fallback to
    the older ``lax.pvary`` name."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    return x  # pre-vma jax (0.4.x): no varying-axis typing to satisfy
