"""Device-mesh sharding helpers — the SPMD data-parallel steps.

TPU-native replacement for the reference's device-affinity machinery
(``Nd4j.getAffinityManager()`` uses in ``ParallelWrapper.java:484`` and
``MultiLayerNetwork.java:1161``): instead of pinning model replicas to devices
from host threads, we declare a `jax.sharding.Mesh` and annotate the jitted
train step's inputs with `NamedSharding`s; XLA's SPMD partitioner inserts the
ICI collectives (psum for gradient all-reduce) that replace both parameter
averaging and Aeron gradient broadcast (SURVEY.md §2.4 "Distributed
communication backend").

Mesh construction, axis conventions, validation, and the partition-spec
machinery all live in ``parallel/mesh.py`` (the unified substrate —
docs/PARALLELISM.md "Unified mesh substrate"); this module keeps the
data-parallel STEP factories, now composition-aware: ``tp_rules`` shards
the ``model`` axis of a 2-D mesh inside the same jitted step, and the
ZeRO flags (``shard_update``/``shard_params``) ride the ``data`` axis of
whatever mesh they are given (:func:`~deeplearning4j_tpu.parallel.mesh.
zero_update_specs`).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax

from ..monitor.jitwatch import monitored_jit
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (DATA_AXIS, MODEL_AXIS, SEQUENCE_AXIS, MeshSpec,
                   make_mesh, replicated, batch_sharded,
                   mirror_updater_shardings, require_axes, rule_shardings,
                   zero_update_specs, record_step)

__all__ = ["DATA_AXIS", "MODEL_AXIS", "SEQUENCE_AXIS", "MeshSpec",
           "make_mesh", "replicated", "batch_sharded", "shard_batch",
           "put_replicated", "put_sharded_tree", "update_sharded_specs",
           "composed_specs", "data_parallel_step",
           "data_parallel_tbptt_step", "data_parallel_tbptt_update_step",
           "pvary"]


def shard_batch(x, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a host batch with its leading dim split across ``axis``.

    Single-process: a plain sharded device_put. Multi-process (after
    ``jax.distributed.initialize``): ``x`` is this process's LOCAL portion of
    the global batch — the global array is assembled from every process's
    local data without any host ever holding the full batch (the reference's
    per-executor ``VirtualDataSetIterator`` partition feeding, done the JAX
    multi-controller way)."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            batch_sharded(mesh, axis), np.asarray(x))
    return jax.device_put(x, batch_sharded(mesh, axis))


def put_replicated(x, mesh: Mesh):
    """Replicate a host value over the (possibly multi-process) mesh. Every
    process must hold the same value (same-seed init guarantees this)."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(replicated(mesh),
                                                      np.asarray(x))
    return jax.device_put(x, replicated(mesh))


def put_sharded_tree(tree, specs):
    """Place a host pytree with per-leaf ``NamedSharding``s. Single-process:
    plain sharded device_put. Multi-process: every process holds the same
    full host value (same-seed init), and ``make_array_from_callback``
    slices out each process's addressable shards — no host ever transfers
    more than its devices' portion."""
    multi = jax.process_count() > 1

    def put(x, sh):
        cur = getattr(x, "sharding", None)
        if cur == sh:
            return x                      # already placed (second fit call)
        if multi:
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # already distributed under another sharding: device-side
                # reshard, no host round-trip
                return jax.device_put(x, sh)
            a = np.asarray(x)
            return jax.make_array_from_callback(
                a.shape, sh, lambda idx, _a=a: _a[idx])
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, tree, specs)


def update_sharded_specs(tree, mesh: Mesh, axis: str = DATA_AXIS):
    """Sharding pytree for OPTIMIZER STATE sharded over the data axis —
    weight-update / optimizer-state sharding (Xu et al. 2020,
    arXiv:2004.13336; the ZeRO-1 idea expressed as XLA sharding
    annotations). Thin alias of :func:`~deeplearning4j_tpu.parallel.mesh.
    zero_update_specs` with no base specs — see it for the dim-selection
    rule and the 2-D composition semantics."""
    return zero_update_specs(tree, mesh, axis)


def composed_specs(net, mesh: Mesh, axis: str = DATA_AXIS,
                   tp_rules: Optional[Dict[str, P]] = None,
                   shard_update: bool = False, shard_params: bool = False):
    """The ONE place the composed model-state shardings are decided, shared
    by the step factories below and ``ParallelWrapper._device_put_model``
    (specs used to jit and specs used to place MUST agree or every fit
    pays a reshard).

    Returns ``(param_specs, updater_specs)`` pytrees: tensor-parallel
    ``tp_rules`` claim the ``model`` axis first (updater state mirrors its
    param's sharding), then the ZeRO flags layer the ``data`` axis of the
    same mesh onto the remaining dims — ``shard_update`` for optimizer
    state (ZeRO-1), ``shard_params`` additionally for parameter storage
    (ZeRO-3/FSDP)."""
    # every axis the rules (or the ZeRO flags) name must exist on the
    # mesh — a raw KeyError from deep inside a tree_map is not a
    # substrate error message
    needed = set()
    if tp_rules:
        needed.update(s for spec in tp_rules.values()
                      for s in tuple(spec) if s is not None)
    if shard_update or shard_params:
        needed.add(axis)
    require_axes(mesh, sorted(needed), style="composed_specs(tp_rules/ZeRO)")
    if tp_rules:
        par = rule_shardings(net.params, mesh, tp_rules)
        upd = mirror_updater_shardings(net.params, net.updater_state, mesh,
                                       tp_rules)
    else:
        repl = replicated(mesh)
        par = jax.tree_util.tree_map(lambda _: repl, net.params)
        upd = jax.tree_util.tree_map(lambda _: repl, net.updater_state)
    if shard_update:
        upd = zero_update_specs(net.updater_state, mesh, axis, base=upd)
    if shard_params:
        par = zero_update_specs(net.params, mesh, axis, base=par)
    return par, upd


def data_parallel_step(net, mesh: Mesh, axis: str = DATA_AXIS, donate=True,
                       shard_update: bool = False,
                       shard_params: bool = False,
                       tp_rules: Optional[Dict[str, P]] = None):
    """Jit a network's train step for synchronous data parallelism.

    Equivalent role to the reference's ``ParallelWrapper`` AVERAGING mode with
    ``averagingFrequency=1`` (``ParallelWrapper.java:551-562``) — except the
    "averaging" is a single fused gradient ``psum`` over ICI emitted by the
    SPMD partitioner, not a host-side barrier + parameter copy.

    Returns a jitted ``step(params, states, upd_state, iteration, rng, f, l,
    fm, lm)`` whose batch inputs must be sharded along ``axis`` (use
    :func:`shard_batch`) and whose params/updater-state follow
    :func:`composed_specs`.

    ``tp_rules`` composes tensor parallelism INTO the same jitted step on a
    2-D ``data × model`` mesh: the rules' param shardings claim the
    ``model`` axis while the batch stays sharded over ``axis`` — DP and TP
    in one XLA computation instead of excluding each other.

    ``shard_update=True`` enables weight-update/optimizer-state sharding
    (ZeRO-1 over the ``data`` axis of whatever mesh is given) — numerically
    identical, ~N× less optimizer memory per device. ``shard_params=True``
    additionally SHARDS THE PARAMETER STORAGE (ZeRO-3/FSDP-style): the SPMD
    partitioner inserts the all-gathers at the points of use and
    reduce-scatters the gradients into the sharded update. Both compose
    with ``tp_rules`` (ZeRO takes the dims TP left free)."""
    raw = net._raw_step(False)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    par, upd = composed_specs(net, mesh, axis, tp_rules,
                              shard_update, shard_params)
    in_sh = (par, repl, upd, repl, repl, data, data, data, data)
    out_sh = (par, repl, upd, repl)
    record_step("sharding/dp_step", mesh, par, upd,
                zero=shard_update or shard_params)
    return monitored_jit(raw, name="sharding/dp_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())


def _rnn_state_shardings(net, mesh: Mesh, axis: str):
    """Sharding pytree for a container's RNN/KV stream state: leaves with a
    batch dimension (LSTM (h, c), attention KV cache/positions) are sharded
    along ``axis``; scalars (the attention global token counter) replicate."""
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    template = net._init_rnn_state(1)
    return jax.tree_util.tree_map(
        lambda x: data if getattr(x, "ndim", 0) >= 1 else repl, template)


def data_parallel_tbptt_step(net, mesh: Mesh, axis: str = DATA_AXIS,
                             donate=True, shard_update: bool = False,
                             shard_params: bool = False,
                             tp_rules: Optional[Dict[str, P]] = None):
    """Sharded train step that also threads the detached RNN/KV carry —
    the TBPTT segment step under data parallelism. Reference semantics:
    ``ParallelWrapper`` workers run the full ``MultiLayerNetwork.fit`` loop
    per replica (``trainer/DefaultTrainer.java:244``), truncated-BPTT
    included, so the SPMD equivalent must segment time the same way.
    ``shard_update``/``shard_params``/``tp_rules`` as in
    :func:`data_parallel_step`."""
    raw = net._raw_step(True)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    state_sh = _rnn_state_shardings(net, mesh, axis)
    par, upd = composed_specs(net, mesh, axis, tp_rules,
                              shard_update, shard_params)
    in_sh = (par, repl, upd, repl, repl, data, data, data, data, state_sh)
    out_sh = (par, repl, upd, repl, state_sh)
    record_step("sharding/dp_tbptt_step", mesh, par, upd,
                zero=shard_update or shard_params)
    return monitored_jit(raw, name="sharding/dp_tbptt_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(0, 2) if donate else ())


def data_parallel_tbptt_update_step(net, mesh: Mesh, axis: str = DATA_AXIS):
    """TBPTT segment variant of the SHARED_GRADIENTS update step: returns the
    updater-transformed (un-applied) update plus the detached carry, so the
    host codec seam can encode per segment."""
    raw = net._raw_update_step(with_rnn_state=True)
    repl = replicated(mesh)
    data = batch_sharded(mesh, axis)
    state_sh = _rnn_state_shardings(net, mesh, axis)
    in_sh = (repl, repl, repl, repl, repl, data, data, data, data, state_sh)
    out_sh = (repl, repl, repl, repl, state_sh)
    record_step("sharding/dp_tbptt_update_step", mesh)
    return monitored_jit(raw, name="sharding/dp_tbptt_update_step",
                         in_shardings=in_sh, out_shardings=out_sh,
                         donate_argnums=(2,))


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over ``axis_names`` inside shard_map
    (vma typing). Wraps ``lax.pcast(..., to='varying')`` with a fallback to
    the older ``lax.pvary`` name."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axis_names))
    return x  # pre-vma jax (0.4.x): no varying-axis typing to satisfy
