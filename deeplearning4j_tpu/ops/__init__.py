"""Host-side native ops + bindings (reference libnd4j host-op seam —
SURVEY.md §2.8). Device compute is XLA; this package covers the host data
plane: gradient wire codec, fast dataset parsers."""
from . import native

__all__ = ["native"]
