"""Pallas persistent-LSTM kernel — the recurrent hot loop with the
recurrent weights VMEM-RESIDENT across the whole sequence.

Why: the container LSTM (``nn/layers/recurrent.py``) hoists the input
projection out of the scan (one big MXU gemm), but the remaining sequential
chain ``z_t = xp_t + h @ RW`` re-streams ``RW [H, 4H]`` from HBM every
timestep: at char-RNN shapes (H=512 → 2 MB bf16) that is T × 2 MB per layer
per direction, and the step is weight-bandwidth-bound at ~1% MFU — exactly
the workload the reference dedicates ``CudnnLSTMHelper.java`` (persistent
RNN) to. These kernels run the whole time loop on a 1-D Pallas grid with
``RW`` (and its transpose, in the backward) loaded into VMEM ONCE
(constant index_map → the DMA is issued for step 0 and skipped after),
h/c carried in VMEM scratch, and only the per-step activations
([b, 4H] / [b, H]) streamed — turning the weight stream from O(T·H·4H)
into O(H·4H).

Backward is the standard LSTM BPTT, hand-written (the cuDNN-helper pattern
the repo already uses for flash attention: custom kernel behind the same
layer math, ``lax.scan`` path as the always-available oracle/fallback):
the forward saves the post-activation gates [T, b, 4H] and the cell
sequence (cuDNN "reserve space"), the reverse kernel carries (dh, dc) and
emits per-step pre-activation gradients dz [T, b, 4H]; everything
batched-over-time (dW, dRW, dx, db, h_prev) happens OUTSIDE as single MXU
gemms. Supports the Graves peephole variant (``pi/pf/po``) and per-step
[b] sequence masks — both GravesLSTM semantics from the reference
(``GravesLSTM.java``, ``LSTMHelpers.java:206-212``).

Layout: time-major [T, b, ...] inside the kernels (grid walks T); the
public :func:`lstm_scan` takes the layer's batch-major arrays. f32
accumulation throughout; tanh cell activation and sigmoid gates (the
``supported()`` contract — other activations fall back to the scan).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .flash_attention import _vspec, _scratch, _interpret, pltpu

__all__ = ["lstm_scan", "supported"]


def _sig(x):
    return jax.nn.sigmoid(x)


def _stream_dtype():
    """Dtype of the HBM-streamed per-step tensors (xp in, ys/gates/cseq
    reserve out, dz out): ``DL4J_TPU_LSTM_STREAM_DTYPE`` = ``float32``
    (default) or ``bfloat16``. bf16 halves the dominant HBM traffic of the
    sequential chain (the cuDNN reserve-space convention stores the
    compute dtype) at a small recompute-precision cost in the backward;
    h/c state and all gate math stay f32 regardless. TRACE-TIME knob, same
    caveat as ``DL4J_TPU_LSTM_UNROLL``: set it before the first step of a
    config."""
    import os
    v = os.environ.get("DL4J_TPU_LSTM_STREAM_DTYPE", "float32")
    return jnp.bfloat16 if v in ("bfloat16", "bf16") else jnp.float32


def _vmem_fits(b: int, H: int, weight_bytes: int, u: int = 1) -> bool:
    """One budget definition for supported() AND _unroll_factor: resident
    [H, 4H] weights + the u-scaled double-buffered streamed blocks must fit
    a core's VMEM (measured heuristic — see supported()). The stream term
    scales with the stream dtype (30·stream_bytes·u·b·H: 120 coeff at f32,
    60 at bf16 — bf16 streams double the U the budget admits)."""
    sb = jnp.dtype(_stream_dtype()).itemsize
    return 4 * H * H * weight_bytes + 30 * sb * u * b * H <= 12 * 2 ** 20


def _unroll_factor(T: int, b: int, H: int, weight_bytes: int) -> int:
    """Timesteps per grid step. The sequential chain is bound by per-grid-
    step latency (PERF.md round-4 addendum 3), so U > 1 divides it — but
    every streamed block ([U, b, 4H] xp/gates/dz, double-buffered) scales
    with U, so U shrinks until the VMEM budget fits. T must divide evenly.
    ``DL4J_TPU_LSTM_UNROLL`` overrides the default (2); 1 disables.

    TRACE-TIME knob: the env var is read when the enclosing step is traced
    (first call per shape). Once jit has cached a compiled step, changing
    it has NO effect on subsequent steps of the same config — set it before
    the first fit/step, or clear jax caches to re-trace."""
    import os
    try:
        u = int(os.environ.get("DL4J_TPU_LSTM_UNROLL", "2"))
    except ValueError:
        u = 2
    u = max(1, min(u, T))
    while u > 1 and (T % u or not _vmem_fits(b, H, weight_bytes, u)):
        u -= 1
    return u


# ------------------------------------------------------------------ forward
def _fwd_kernel(xp_ref, rw_ref, peep_ref, m_ref, h0_ref, c0_ref,
                ys_ref, gates_ref, cseq_ref, hc_ref,
                h_s, c_s, *, nb, H, peep, U):
    """One grid step processes U consecutive timesteps (statically
    unrolled): the measured bound at the char-RNN config is per-grid-step
    latency × the sequential chain length, not FLOPs or HBM bytes
    (PERF.md round-4 addendum 3) — U steps per launch divides that chain
    by U. All block operands carry a leading [U] time dim."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0_ref[...].astype(jnp.float32)
        c_s[:] = c0_ref[...].astype(jnp.float32)

    h = h_s[:]
    c = c_s[:]
    # resident [H, 4H] in its SOURCE dtype (bf16 under the mixed-precision
    # policy): the MXU runs a native bf16×bf16→f32 pass instead of the
    # multi-pass f32 algorithm, and the resident footprint halves. h/c stay
    # f32 in scratch (accumulation dtype); only the gemm operand is cast.
    rw = rw_ref[...]
    if peep:
        pi = peep_ref[0].astype(jnp.float32)              # [H]
        pf = peep_ref[1].astype(jnp.float32)
        po = peep_ref[2].astype(jnp.float32)
    for u in range(U):
        z = xp_ref[u].astype(jnp.float32) + jax.lax.dot_general(
            h.astype(rw.dtype), rw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [b, 4H]
        zi, zf, zo, zg = (z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H],
                          z[:, 3 * H:])
        if peep:
            zi = zi + c * pi[None, :]
            zf = zf + c * pf[None, :]
        i = _sig(zi)
        f = _sig(zf)
        g = jnp.tanh(zg)
        c_new = f * c + i * g
        if peep:
            zo = zo + c_new * po[None, :]
        o = _sig(zo)
        h_new = o * jnp.tanh(c_new)
        if m_ref is not None:
            m = m_ref[u, :, 0][:, None]                   # [b, 1]
            h_new = m * h_new + (1.0 - m) * h
            c_new = m * c_new + (1.0 - m) * c
        ys_ref[u] = h_new.astype(ys_ref.dtype)
        if gates_ref is not None:  # reserve for BPTT (training fwd only)
            gates_ref[u] = jnp.concatenate([i, f, o, g], axis=-1
                                           ).astype(gates_ref.dtype)
            cseq_ref[u] = c_new.astype(cseq_ref.dtype)
        h, c = h_new, c_new
    h_s[:] = h
    c_s[:] = c

    @pl.when(t == nb - 1)
    def _():
        hc_ref[0] = h.astype(hc_ref.dtype)
        hc_ref[1] = c.astype(hc_ref.dtype)


def _fwd(xp, rw, peep, h0, c0, mask, save_reserve=True):
    """xp: [T, b, 4H] (input projection + bias), rw: [H, 4H], peep: [8, H]
    or None, h0/c0: [b, H], mask: [T, b, 8] or None →
    (ys [T, b, H], gates [T, b, 4H], cseq [T, b, H], hcT [2, b, H]);
    ``save_reserve=False`` (inference primal) omits the gates/cseq reserve
    outputs entirely — no dead HBM writes on the non-training path — and
    returns (ys, None, None, hcT)."""
    T, b, H4 = xp.shape
    H = H4 // 4
    U = _unroll_factor(T, b, H, jnp.dtype(rw.dtype).itemsize)
    nb = T // U
    kern = functools.partial(_fwd_kernel, nb=nb, H=H, peep=peep is not None,
                             U=U)
    const3 = lambda t: (0, 0, 0)
    const2 = lambda t: (0, 0)
    specs = [
        _vspec((U, b, H4), lambda t: (t, 0, 0)),          # xp (streamed)
        _vspec((H, H4), const2),                          # rw (resident)
    ]
    ops = [xp, rw]
    if peep is not None:
        specs.append(_vspec((8, H), const2))              # peepholes
        ops.append(peep)
    has_mask = mask is not None
    if has_mask:
        specs.append(_vspec((U, b, 8), lambda t: (t, 0, 0)))
        ops.append(mask)
    specs += [_vspec((b, H), const2), _vspec((b, H), const2)]   # h0, c0
    ops += [h0, c0]

    def shim(*refs):
        n_in = 2 + int(peep is not None) + int(has_mask) + 2
        ins, rest = refs[:n_in], refs[n_in:]
        pos = 2
        peep_ref = ins[pos] if peep is not None else None
        pos += int(peep is not None)
        m_ref = ins[pos] if has_mask else None
        pos += int(has_mask)
        if save_reserve:
            ys_ref, gates_ref, cseq_ref, hc_ref, h_s, c_s = rest
        else:
            (ys_ref, hc_ref, h_s, c_s), gates_ref, cseq_ref = rest, None, \
                None
        return kern(ins[0], ins[1], peep_ref, m_ref, ins[pos], ins[pos + 1],
                    ys_ref, gates_ref, cseq_ref, hc_ref, h_s, c_s)

    sd = _stream_dtype()          # reserve stream dtype (policy knob)
    out_specs = [_vspec((U, b, H), lambda t: (t, 0, 0))]  # ys
    out_shape = [jax.ShapeDtypeStruct((T, b, H), xp.dtype)]
    if save_reserve:
        out_specs += [
            _vspec((U, b, H4), lambda t: (t, 0, 0)),      # gates (reserve)
            _vspec((U, b, H), lambda t: (t, 0, 0)),       # c sequence
        ]
        out_shape += [jax.ShapeDtypeStruct((T, b, H4), sd),
                      jax.ShapeDtypeStruct((T, b, H), sd)]
    out_specs.append(_vspec((2, b, H), const3))           # final (h, c):
    out_shape.append(jax.ShapeDtypeStruct((2, b, H), jnp.float32))
    res = pl.pallas_call(
        shim,
        grid=(nb,),
        in_specs=specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        scratch_shapes=[_scratch((b, H)), _scratch((b, H))],
        interpret=_interpret(),
    )(*ops)
    if save_reserve:
        return res
    ys, hc = res
    return ys, None, None, hc


# ----------------------------------------------------------------- backward
def _bwd_kernel(dy_ref, gates_ref, cseq_ref, cprev_ref, rwt_ref, peep_ref,
                m_ref, c0_ref, dhT_ref, dcT_ref,
                dz_ref, dh0_ref, dc0_ref, dpeep_ref,
                dh_s, dc_s, dp_s, *, nb, H, peep, U):
    """Reverse BPTT, U timesteps per grid step (statically unrolled, walked
    u = U-1 … 0 inside the block). ``cprev_ref`` streams the PREVIOUS
    block of the c sequence — in-block u > 0 takes c_{t-1} from the local
    block, u == 0 takes it from ``cprev_ref[U-1]`` (or c0 at the sequence
    start)."""
    t = pl.program_id(0)          # walks 0..nb-1; blocks indexed nb-1-t

    @pl.when(t == 0)
    def _():
        dh_s[:] = dhT_ref[...].astype(jnp.float32)
        dc_s[:] = dcT_ref[...].astype(jnp.float32)
        if peep:
            dp_s[:] = jnp.zeros_like(dp_s)

    rt_is_first = t == nb - 1     # reverse block at sequence start
    rwt = rwt_ref[...]            # resident [4H, H], source (bf16) dtype
    if peep:
        pi = peep_ref[0].astype(jnp.float32)
        pf = peep_ref[1].astype(jnp.float32)
        po = peep_ref[2].astype(jnp.float32)
    dh_carry = dh_s[:]
    dc_carry = dc_s[:]
    for u in reversed(range(U)):
        gts = gates_ref[u].astype(jnp.float32)
        i, f, o, g = (gts[:, :H], gts[:, H:2 * H], gts[:, 2 * H:3 * H],
                      gts[:, 3 * H:])
        c_out = cseq_ref[u].astype(jnp.float32)
        if u > 0:
            c_prev = cseq_ref[u - 1].astype(jnp.float32)
        else:
            # first step of the block: c_{t-1} lives in the previous block
            # (clamped stream), or is c0 at the very start of the sequence
            c_prev = jnp.where(rt_is_first,
                               c0_ref[...].astype(jnp.float32),
                               cprev_ref[0].astype(jnp.float32))
        dh_tot = dy_ref[u].astype(jnp.float32) + dh_carry
        dc_tot = dc_carry
        if m_ref is not None:
            m = m_ref[u, :, 0][:, None]
        else:
            m = None
        dh_c = dh_tot if m is None else m * dh_tot
        dc_c = dc_tot if m is None else m * dc_tot
        # cseq stores the POST-mask c_eff (it is the next step's c_prev);
        # the tanh/peephole-o in the forward used the PRE-mask candidate —
        # recompute it from the saved gates so masked-step gradients are
        # exact for any mask value in [0, 1], not just binary
        c_cand = c_out if m is None else f * c_prev + i * g
        tc = jnp.tanh(c_cand)
        do = dh_c * tc
        dzo = do * o * (1.0 - o)
        dc = dc_c + dh_c * o * (1.0 - tc * tc)
        if peep:
            dc = dc + dzo * po[None, :]
        di = dc * g
        df = dc * c_prev
        dg = dc * i
        dzi = di * i * (1.0 - i)
        dzf = df * f * (1.0 - f)
        dzg = dg * (1.0 - g * g)
        dc_prev = dc * f
        if peep:
            dc_prev = dc_prev + dzi * pi[None, :] + dzf * pf[None, :]
            # peephole grads accumulate across steps ([8, H] scratch 0-2)
            dp_s[0] = dp_s[0] + jnp.sum(dzi * c_prev, axis=0)
            dp_s[1] = dp_s[1] + jnp.sum(dzf * c_prev, axis=0)
            dp_s[2] = dp_s[2] + jnp.sum(dzo * c_cand, axis=0)
        dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)   # [b, 4H]
        dh_prev = jax.lax.dot_general(dz.astype(rwt.dtype), rwt,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        if m is not None:
            # dc/dz already carry the m factor (via dh_c/dc_c) — only the
            # straight-through (1-m) residual is added here; an extra m
            # factor would double-scale fractional masks (binary: m² = m)
            dh_prev = dh_prev + (1.0 - m) * dh_tot
            dc_prev = dc_prev + (1.0 - m) * dc_tot
        dz_ref[u] = dz.astype(dz_ref.dtype)
        dh_carry, dc_carry = dh_prev, dc_prev
    dh_s[:] = dh_carry
    dc_s[:] = dc_carry

    @pl.when(t == nb - 1)
    def _():
        dh0_ref[...] = dh_carry.astype(dh0_ref.dtype)
        dc0_ref[...] = dc_carry.astype(dc0_ref.dtype)
        if peep:
            dpeep_ref[...] = dp_s[:].astype(dpeep_ref.dtype)
        else:
            dpeep_ref[...] = jnp.zeros(dpeep_ref.shape, dpeep_ref.dtype)


def _bwd_call(dy, gates, cseq, rwt, peep, mask, c0, dhT, dcT):
    T, b, H = dy.shape
    H4 = 4 * H
    U = _unroll_factor(T, b, H, jnp.dtype(rwt.dtype).itemsize)
    nb = T // U
    kern = functools.partial(_bwd_kernel, nb=nb, H=H, peep=peep is not None,
                             U=U)
    rev = lambda t: (nb - 1 - t, 0, 0)
    # c_prev stream: ONE row — the last element of block rt-1 (block size 1
    # on the time dim ⇒ the index map is an ELEMENT index), clamped at 0
    # and selected against c0 in-kernel at the sequence start
    rev_prev = lambda t: (jnp.maximum((nb - 1 - t) * U - 1, 0), 0, 0)
    const2 = lambda t: (0, 0)
    specs = [
        _vspec((U, b, H), rev),                           # dy
        _vspec((U, b, H4), rev),                          # gates
        _vspec((U, b, H), rev),                           # c sequence
        _vspec((1, b, H), rev_prev),                      # c_{t-1} stream
        _vspec((H4, H), const2),                          # rw^T (resident)
    ]
    ops = [dy, gates, cseq, cseq, rwt]
    if peep is not None:
        specs.append(_vspec((8, H), const2))
        ops.append(peep)
    has_mask = mask is not None
    if has_mask:
        specs.append(_vspec((U, b, 8), rev))
        ops.append(mask)
    specs += [_vspec((b, H), const2)] * 3                 # c0, dhT, dcT
    ops += [c0, dhT, dcT]

    def shim(*refs):
        n_in = 5 + int(peep is not None) + int(has_mask) + 3
        ins, rest = refs[:n_in], refs[n_in:]
        pos = 5
        peep_ref = ins[pos] if peep is not None else None
        pos += int(peep is not None)
        m_ref = ins[pos] if has_mask else None
        pos += int(has_mask)
        return kern(ins[0], ins[1], ins[2], ins[3], ins[4], peep_ref, m_ref,
                    ins[pos], ins[pos + 1], ins[pos + 2], *rest)

    sd = _stream_dtype()          # dz rides the stream-dtype policy too
    f32 = jnp.float32
    return pl.pallas_call(
        shim,
        grid=(nb,),
        in_specs=specs,
        out_specs=(
            _vspec((U, b, H4), rev),                      # dz per step
            _vspec((b, H), const2),                       # dh0
            _vspec((b, H), const2),                       # dc0
            _vspec((8, H), const2),                       # dpeep
        ),
        out_shape=(jax.ShapeDtypeStruct((T, b, H4), sd),
                   jax.ShapeDtypeStruct((b, H), f32),
                   jax.ShapeDtypeStruct((b, H), f32),
                   jax.ShapeDtypeStruct((8, H), f32)),
        scratch_shapes=[_scratch((b, H)), _scratch((b, H)),
                        _scratch((8, H))],
        interpret=_interpret(),
    )(*ops)


# ------------------------------------------------------------- public entry
@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _lstm(xp, rw, peep, h0, c0, mask):
    # primal (inference) path: no reserve tensors — the BPTT residuals are
    # only materialized by _lstm_fwd under differentiation
    ys, _, _, hc = _fwd(xp, rw, peep, h0, c0, mask, save_reserve=False)
    return ys, hc[0], hc[1]


def _lstm_fwd(xp, rw, peep, h0, c0, mask):
    ys, gates, cseq, hc = _fwd(xp, rw, peep, h0, c0, mask)
    return (ys, hc[0], hc[1]), (rw, peep, h0, c0, mask, ys, gates, cseq)


def _lstm_bwd(res, grads):
    rw, peep, h0, c0, mask, ys, gates, cseq = res
    dy, dhT, dcT = grads
    T, b, H = dy.shape
    dy = dy.astype(jnp.float32)
    rwt = jnp.swapaxes(rw, 0, 1)
    dz, dh0, dc0, dpeep = _bwd_call(dy, gates, cseq, rwt, peep, mask,
                                    c0.astype(jnp.float32),
                                    dhT.astype(jnp.float32),
                                    dcT.astype(jnp.float32))
    # batched-over-time pieces as single MXU gemms (outside the kernel):
    # z_t = xp_t + h_{t-1} @ RW  →  dxp = dz,  dRW = Σ_t h_{t-1}ᵀ dz_t
    h_prev = jnp.concatenate([h0.astype(ys.dtype)[None], ys[:-1]], axis=0)
    # batched gemm in the weight dtype (bf16 policy), f32 accumulation
    drw = jnp.einsum("tbh,tbg->hg", h_prev.astype(rw.dtype),
                     dz.astype(rw.dtype),
                     preferred_element_type=jnp.float32).astype(rw.dtype)
    dxp = dz                                              # z = xp + h @ RW
    dpeep_out = None if peep is None else dpeep.astype(peep.dtype)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return (dxp, drw, dpeep_out, dh0, dc0, dmask)


_lstm.defvjp(_lstm_fwd, _lstm_bwd)


#: kernel contract: tanh cell activation + sigmoid gates, TPU-tileable dims
def supported(b: int, T: int, H: int, activation: str,
              gate_activation: str, weight_bytes: int = 4) -> bool:
    """Whether the persistent kernel applies: TPU backend (or the tests'
    forced interpret mode), tanh/sigmoid activations (the kernel hard-codes
    them), lane-aligned width and sublane-aligned batch. Everything else
    falls back to the ``lax.scan`` oracle path. Escape hatch:
    ``DL4J_TPU_NO_PERSISTENT_LSTM=1`` forces the scan path (first-hardware
    insurance — the kernel is interpret-verified, and this keeps a
    one-variable rollback if a Mosaic lowering gap surfaces on a new
    jaxlib)."""
    import os
    if os.environ.get("DL4J_TPU_NO_PERSISTENT_LSTM"):
        return False
    from . import flash_attention as _fa
    if not _fa._FORCE_INTERPRET:
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:  # pragma: no cover
            return False
    # VMEM budget: resident [H, 4H] weights (4H² elements × weight_bytes;
    # the bwd kernel holds the transpose) PLUS the batch-dependent per-step
    # blocks — xp/ys/gates/cseq/dz streams (double-buffered by the
    # pipeline), h0/c0/dhT/dcT and the h/c scratch. Worst case (bwd) ≈
    # 4H²·wb + 30·sb·u·b·H bytes where sb is the STREAM dtype's width
    # (DL4J_TPU_LSTM_STREAM_DTYPE: 120·u·b·H at the f32 default, 60·u·b·H
    # at bf16 — see _vmem_fits); cap the SUM under a core's VMEM so
    # oversized configs fall back to the scan instead of failing a Mosaic
    # allocation. bf16-resident weights (weight_bytes=2, the
    # mixed-precision policy) halve the resident term. At f32 streams:
    # f32-weights b=64,H=512 → 7.9 MB ✓; b=256,H=512 → 19.7 MB ✗ → scan;
    # bf16-weights b=64,H=1024 → 16.2 MB ✗ → scan, b=128,H=512 → 10 MB ✓.
    # bf16 streams halve the b-dependent term, roughly doubling each bound.
    if not _vmem_fits(b, H, weight_bytes) or b > 1024:
        return False
    return (activation == "tanh" and gate_activation == "sigmoid"
            and H % 128 == 0 and b % 8 == 0 and T >= 1)


def lstm_scan(xp, rw, peep, h0, c0, mask=None):
    """Persistent-LSTM sequence step. ``xp``: [b, T, 4H] hoisted input
    projection (+bias), ``rw``: [H, 4H], ``peep``: (pi, pf, po) tuple or
    None, ``h0``/``c0``: [b, H], ``mask``: [b, T] (1 = real step, values in
    [0, 1]) or None. The mask is NON-differentiable (the custom_vjp returns
    a zero cotangent for it); callers differentiating through a soft mask
    must stop_gradient it on their fallback path too (recurrent.py does).
    Returns (ys [b, T, H] in the stream dtype — f32 unless
    ``DL4J_TPU_LSTM_STREAM_DTYPE=bfloat16`` — and (hT, cT) in f32) — a
    drop-in for the ``lax.scan`` recurrent loop with the weight stream
    eliminated."""
    b, T, H4 = xp.shape
    H = H4 // 4
    xp_tm = jnp.swapaxes(xp, 0, 1)                        # time-major
    pk = None
    if peep is not None:
        pk = jnp.zeros((8, H), jnp.float32)
        pk = pk.at[0].set(peep[0].astype(jnp.float32))
        pk = pk.at[1].set(peep[1].astype(jnp.float32))
        pk = pk.at[2].set(peep[2].astype(jnp.float32))
    mk = None
    if mask is not None:
        mk = jnp.broadcast_to(
            jnp.swapaxes(jnp.asarray(mask, jnp.float32), 0, 1)[..., None],
            (T, b, 8))
    # xp (the accumulated input projection) rides the STREAM dtype policy
    # (f32 default; DL4J_TPU_LSTM_STREAM_DTYPE=bfloat16 halves the per-step
    # HBM stream — gate math stays f32 in-kernel either way); RW rides in
    # its caller dtype (bf16 under the mixed-precision policy) so the
    # recurrent gemm runs the MXU's native bf16 pass with f32 accumulation
    ys, hT, cT = _lstm(xp_tm.astype(_stream_dtype()), rw, pk,
                       h0.astype(jnp.float32), c0.astype(jnp.float32), mk)
    return jnp.swapaxes(ys, 0, 1), (hT, cT)
